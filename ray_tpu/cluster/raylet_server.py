"""Raylet server — the per-node daemon, as its own process.

Process-tier equivalent of the reference raylet (src/ray/raylet/main.cc:72
entry; node_manager.h:140 NodeManager): hosts the node's object store,
leases OS worker processes (cluster/process_pool.py) for task execution,
resolves task-argument dependencies by pulling objects from peer raylets
(the object-transfer plane of object_manager.cc:302,463,509 — chunked
push/pull over the framed-TCP RPC substrate, admission-gated by
scheduler/pull_manager.py), registers object locations with the GCS
directory, heartbeats the GCS failure detector, and serves the
placement-group bundle 2PC (placement_group_resource_manager.h).

Run as ``python -m ray_tpu.cluster.raylet_server --gcs HOST:PORT``.
SIGKILLing this process is a *node death*: its worker children exit when
their control pipes close, the GCS detector declares the node dead after
``num_heartbeats_timeout`` missed beats, and owners re-submit lost work.
"""

from __future__ import annotations

import itertools
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private.config import Config
from ray_tpu.cluster import integrity, protocol
from ray_tpu.cluster import overload as _overload
from ray_tpu.cluster.byte_store import ByteStore, PushManager, shm_key
from ray_tpu.cluster.process_pool import ProcessWorkerPool
from ray_tpu.cluster.rpc import RpcClient, RpcConnectionError, RpcServer
from ray_tpu.cluster.threads import ThreadRegistry
from ray_tpu.exceptions import (
    ActorInitError,
    ObjectCorruptedError,
    RetryLaterError,
    WorkerCrashedError,
)

logger = logging.getLogger(__name__)


class _QueuedTask:
    __slots__ = ("spec", "attempts")

    def __init__(self, spec: dict):
        self.spec = spec
        self.attempts = 0


class RayletServer:
    def __init__(self, gcs_address: str,
                 resources: Optional[Dict[str, float]] = None,
                 num_workers: int = 2, node_id: Optional[str] = None,
                 object_store_memory: Optional[int] = None):
        from ray_tpu._private.ids import NodeID
        from ray_tpu.cluster import fault_plane

        fault_plane.set_process_role("raylet")
        self.node_id = node_id or NodeID.from_random().hex()
        self.gcs_address = gcs_address
        from ray_tpu.cluster.rpc import ReconnectingRpcClient

        # survives GCS restarts: directory/pubsub/KV calls retry through
        # a fresh connection while the heartbeat loop re-registers us
        self.gcs = ReconnectingRpcClient(gcs_address)
        # dropped-replica ids queue here; a background flusher
        # deregisters their GCS locations (eviction must never block on
        # a GCS round trip)
        # raycheck: disable=RC10 — growth is bounded by eviction churn (entries are 28-byte ids of replicas the bounded store just dropped); a maxlen would silently leak stale GCS directory entries instead
        self._dropped_replicas: deque = deque()
        self.store = ByteStore(
            object_store_memory,
            on_replica_dropped=self._dropped_replicas.append)
        self.push_manager = PushManager(self._send_push)
        # inbound chunked pushes being reassembled: oid -> state; and an
        # event for pulls to wait on instead of double-fetching
        self._inbound_lock = threading.Lock()
        self._inbound_pushes: Dict[bytes, dict] = {}
        # chunk-tree failover: (object_id, dest) pairs whose next push
        # is a re-root re-offer — push_begin travels with reroot=True
        # so the orphaned receiver supersedes its half-open inbound
        # instead of declining until the stale sweep
        self._reroot_lock = threading.Lock()
        self._reroot_pending: set = set()
        self.resources = dict(resources or {"CPU": float(num_workers)})
        self._avail_lock = threading.RLock()
        self.available = dict(self.resources)
        # worker stderr lines fan out on the GCS LOG channel, keyed by
        # node (reference: log_monitor.py tails worker logs and publishes
        # them for the driver to print). Log state must exist BEFORE the
        # pool: workers spawn in its ctor and drain threads start at once.
        self._log_lock = threading.Lock()
        self._log_buffer: deque = deque(maxlen=10_000)  # drop-oldest
        self._log_flusher: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # background threads spawn through the registry so shutdown()
        # joins them by name (a hung teardown surfaces its culprit);
        # must exist before the pool — workers spawn in its ctor and
        # the log flusher can start at once
        self._threads = ThreadRegistry(f"raylet-{self.node_id[:8]}")
        # explicit seeded stream (raycheck RC03): replica-shuffle
        # decisions replay under a fault plan's single seed instead of
        # drawing from the process-global RNG
        self._pull_rng = fault_plane.derive_rng(
            f"raylet-pull|{self.node_id}")
        # workers (and their subprocesses, e.g. job entrypoints) learn
        # their node through the environment
        import os as _os

        _os.environ["RAY_TPU_NODE_ID"] = self.node_id
        # workers attach the node's shm segment: large task args and
        # results move through shared memory, not the control pipe
        # (plasma worker-mmap contract)
        _cfg = Config.instance()
        self.pool = ProcessWorkerPool(
            size=num_workers,
            shm_path=self.store.shm_path or "",
            log_callback=self._publish_log,
            # warm actor-worker pool (worker_pool.cc prestart): off ⇒
            # exact fork-per-actor behavior
            warm_size=(_cfg.worker_pool_warm_size
                       if _cfg.worker_pool_enabled else 0),
            threads=self._threads)
        from collections import OrderedDict

        # raycheck: disable=RC10 — bounded by the submit_task admission check (raylet_max_queued_tasks): over-bound submits are shed with RetryLaterError, never enqueued
        self._task_queue: deque[_QueuedTask] = deque()
        self._queue_cv = threading.Condition()
        # guards the plain int/float stats counters (num_*, ct_*):
        # they are bumped from dispatch/handler threads and read by
        # node_stats — a bare += is a lost-update race (raycheck RC16).
        # Hold it only for the increment/read itself, never across
        # calls.
        self._stats_lock = threading.Lock()
        self.num_tasks_shed = 0  # submits pushed back (backpressure)
        self._running: Dict[str, dict] = {}
        # task_id -> "done"|"failed"; LRU-bounded so a long-lived node
        # does not grow one entry per task forever
        self._done: "OrderedDict[str, str]" = OrderedDict()
        self._done_cap = 100_000
        # per-row batch-frame dedupe (exactly-once submit rows): row
        # token -> cached reply row, LRU-bounded, guarded by _queue_cv
        self._row_tokens: "OrderedDict[str, dict]" = OrderedDict()
        self._row_token_cap = 100_000
        self._actors: Dict[str, dict] = {}
        self._actor_lock = threading.RLock()
        # peer-client cache: get-or-create races between concurrent
        # handlers (pull/push/actor paths) would leak duplicate open
        # connections — every read/insert holds _peer_lock, with the
        # blocking connect itself outside it (RC01)
        self._peer_clients: Dict[str, RpcClient] = {}
        self._peer_lock = threading.Lock()
        # PG 2PC bundle state, all under _avail_lock: prepared
        # reservations (with lease timestamps, so a GCS that dies
        # between prepare and commit cannot leak the reservation) and
        # the committed set making commit/return idempotent under
        # frame duplication and GCS retries.
        self._prepared_bundles: Dict[Tuple[str, int], Dict[str, float]] = {}
        self._prepared_at: Dict[Tuple[str, int], float] = {}
        self._committed_bundles: set = set()
        self.server: Optional[RpcServer] = None
        self._pull_lock = threading.Lock()
        self._inflight_pulls: Dict[bytes, threading.Event] = {}
        # drain plane: monotonic eviction deadline of a pending
        # preemption notice (None = no notice). Written by the
        # preempt_notice RPC, read by the heartbeat loop, which
        # reports the REMAINING window so the GCS can drain inside it.
        # Both drain-plane flags cross the preempt-handler /
        # heartbeat / node_stats threads, so _drain_lock guards every
        # access (RC16).
        self._drain_lock = threading.Lock()
        self._preempt_deadline: Optional[float] = None
        # set when a heartbeat reply says the GCS is draining this node
        self._draining = False
        cfg = Config.instance()
        self.chunk_size = cfg.object_chunk_size
        self.heartbeat_period_s = cfg.raylet_heartbeat_period_ms / 1000.0

    def _publish_log(self, pid: int, line: str) -> None:
        """Buffer one worker log line for the GCS LOG channel. Appending
        never blocks the stderr drain thread — a hung GCS must not
        back-pressure the worker's stderr pipe and stall user code
        (the deque's maxlen drops oldest, best effort)."""
        with self._log_lock:
            self._log_buffer.append({"pid": pid, "line": line})
            if self._log_flusher is None:
                self._log_flusher = self._threads.spawn(
                    self._log_flush_loop,
                    f"log-flush-{self.node_id[:8]}")

    def _log_flush_loop(self) -> None:
        """Ship buffered lines in batches (reference: log_monitor.py
        publishes batches, not lines)."""
        from ray_tpu.pubsub import LOG_CHANNEL

        while not self._stop.wait(0.2):
            with self._log_lock:
                if not self._log_buffer:
                    continue
                batch = list(self._log_buffer)
                self._log_buffer.clear()
            try:
                self.gcs.call("pubsub_publish", channel=LOG_CHANNEL,
                              key=self.node_id,
                              message={"batch": batch}, timeout=5.0)
            except Exception as e:
                # GCS briefly unreachable: logs are best-effort
                logger.debug("log batch publish (%d lines) failed: %r",
                             len(batch), e)

    # ------------------------------------------------------------- lifecycle
    def serve(self, host: str = "127.0.0.1", port: int = 0) -> RpcServer:
        srv = RpcServer(host, port)
        fast = {  # queue appends / store lookups: inline dispatch
            # (put_object stays threaded: it calls out to the GCS to
            # register the location)
            "submit_task", "submit_task_batch", "task_state",
            "prepare_bundle", "commit_bundle", "return_bundle",
            "node_stats", "ping", "get_object_info",
            "preempt_notice",  # one timestamp write: pure bookkeeping
            # inline => handled on the sender's connection reader
            # thread, so a pipelined begin/chunk.../end sequence stays
            # ordered (threaded dispatch would race chunks past begin)
            "push_begin", "push_chunk", "push_end", "push_abort",
            "perf_dump",
        }
        for name in (
            "submit_task", "submit_task_batch", "wait_task",
            "task_state",
            "put_object", "wait_object",
            "free_objects", "get_object_info",
            "push_object", "push_offer", "push_begin", "push_chunk",
            "push_end", "push_abort", "pull_object",
            "create_actor", "actor_call", "kill_actor",
            "kill_actor_batch",
            "prepare_bundle", "commit_bundle", "return_bundle",
            "node_stats", "ping", "perf_dump", "preempt_notice",
        ):
            srv.register(name, getattr(self, name), inline=name in fast)
        srv.register_stream("get_object", self.get_object)
        # raw data frames (chunk payload out of band, recv_into the
        # final segment bytes) dispatch inline on the reader thread by
        # construction — same ordering contract as the fast set above
        srv.register_data("push_chunk_data", self.push_chunk_data)
        srv.start()
        self.server = srv
        reply = self.gcs.call("register_node", node_id=self.node_id,
                              address=srv.address,
                              resources=self.resources, timeout=30.0)
        self.heartbeat_period_s = reply["heartbeat_period_ms"] / 1000.0
        nid = self.node_id[:8]
        self._threads.spawn(self._heartbeat_loop, f"raylet-hb-{nid}")
        self._threads.spawn(self._dereg_loop, f"raylet-dereg-{nid}")
        for i in range(max(2, int(self.resources.get("CPU", 2)))):
            self._threads.spawn(self._dispatch_loop,
                                f"raylet-dispatch-{nid}-{i}")
        return srv

    def ping(self) -> str:
        return "pong"

    def preempt_notice(self, notice_s: float, reason: str = "") -> dict:
        """Drain plane: the infrastructure (or the fault plane's seeded
        `preempt_node` storm kind) announces this node will be evicted
        in ``notice_s`` seconds. Record the deadline; the heartbeat
        loop reports the remaining window on its next beat and the GCS
        starts a graceful drain inside it. With the plane off the
        notice is acknowledged-but-ignored — eviction then lands as an
        abrupt kill, the pre-plane behavior."""
        if not Config.instance().drain_plane_enabled:
            return {"ok": False, "reason": "drain plane disabled"}
        from ray_tpu.observability import metrics

        with self._drain_lock:
            self._preempt_deadline = time.monotonic() + max(
                0.0, float(notice_s))
        metrics.preemption_notices.inc(tags={"role": "raylet"})
        logger.warning("preemption notice: node %s evicted in %.1fs%s",
                       self.node_id[:8], notice_s,
                       f" ({reason})" if reason else "")
        return {"ok": True}

    def _preempt_remaining(self) -> Optional[float]:
        """Seconds left on a pending preemption notice (None if none).
        Keeps reporting 0.0 past the deadline: a drain the GCS missed
        (lost beats during the window) must still start."""
        with self._drain_lock:
            deadline = self._preempt_deadline
        if deadline is None:
            return None
        return max(0.0, deadline - time.monotonic())

    def shutdown(self) -> None:
        self._stop.set()
        with self._queue_cv:
            self._queue_cv.notify_all()
        self.pool.shutdown()
        if self.server is not None:
            self.server.stop()
        self.gcs.close()
        with self._peer_lock:
            peers = list(self._peer_clients.values())
        for c in peers:
            c.close()
        # join background threads BEFORE closing the store they touch;
        # a hung one is WARN-logged by name instead of leaking
        self._threads.join_all(timeout=2.0)
        self.push_manager.join_all(timeout=2.0)
        self.store.close()

    def _dereg_loop(self) -> None:
        """Deregister GCS locations of replicas the store evicted (the
        eviction callback only queues, so a full store never blocks on
        the GCS)."""
        while not self._stop.wait(0.2):
            while self._dropped_replicas:
                oid = self._dropped_replicas.popleft()
                try:
                    self.gcs.call("object_remove_location",
                                  object_id=oid, node_id=self.node_id,
                                  timeout=10.0)
                except Exception:
                    # GCS briefly unreachable: requeue, retry next sweep
                    self._dropped_replicas.appendleft(oid)
                    break

    def _heartbeat_loop(self) -> None:
        # Heartbeats ride their OWN connection: the shared self.gcs client
        # carries blocking calls (object_wait_location during dependency
        # pulls) and the RPC server handles one connection's requests
        # serially — sharing would starve liveness past the death
        # threshold while a pull waits.
        hb: Optional[RpcClient] = None
        gcs_instance: Optional[str] = None
        pending_reconcile = False
        while not self._stop.wait(self.heartbeat_period_s):
            self._expire_prepared_bundles()
            self._sweep_stale_inbound()
            try:
                if hb is None or hb.closed:
                    hb = RpcClient(self.gcs_address)
                with self._avail_lock:
                    avail = dict(self.available)
                    totals = dict(self.resources)
                t_send = time.monotonic()
                reply = hb.call("heartbeat", node_id=self.node_id,
                                available=avail, resources=totals,
                                overload=self._overload_stats(),
                                integrity=self._integrity_stats(),
                                serve=self._serve_stats(),
                                worker_pool=self._worker_pool_stats(),
                                preempt_notice_s=self._preempt_remaining(),
                                threads=self._threads.roots(),
                                timeout=10.0)
                rtt = time.monotonic() - t_send
                if reply.get("draining"):
                    # the GCS is draining this node (our notice, or an
                    # operator/scale-down drain): surfaced in node_stats
                    with self._drain_lock:
                        self._draining = True
                server_time = reply.get("server_time")
                if server_time is not None:
                    # Clock-offset estimate over the heartbeat RTT
                    # (NTP's symmetric-delay assumption): the GCS
                    # stamped server_time mid-flight, so GCS wall clock
                    # minus (our wall clock at receipt - rtt/2) is the
                    # skew. The flight recorder reports it per node and
                    # `cli.py timeline` shifts every node's spans onto
                    # the GCS clock before merging.
                    # raycheck: disable=RC02 — wall-clock sample for cross-node clock correlation, not deadline arithmetic
                    local_mid = time.time() - rtt / 2.0
                    from ray_tpu.observability import flight_recorder
                    flight_recorder.global_recorder.set_clock_offset(
                        server_time - local_mid)
                instance = reply.get("gcs_instance")
                if not reply.get("registered", True):
                    # GCS declared us dead then saw us again — a healed
                    # partition — or has no record of us at all.
                    # UNLESS we know we're being drained out: then the
                    # deregistration was deliberate, and heartbeating on
                    # would resurrect the record (the handler flips
                    # alive back on) just for the GCS to drain it again
                    # — so fall silent and wait for the eviction
                    with self._drain_lock:
                        draining = self._draining
                    if draining:
                        logger.info("drained out of the cluster; "
                                    "heartbeats stop (awaiting "
                                    "eviction)")
                        break
                    pending_reconcile = True
                if (gcs_instance is not None and instance is not None
                        and instance != gcs_instance):
                    # GCS RESTARTED: its location directory started empty
                    pending_reconcile = True
                if pending_reconcile:
                    # Re-announce the node, re-publish resources, and
                    # re-report every resident object location
                    # (reference: raylets resend object locations on GCS
                    # failover). The flag clears only after the FULL
                    # reconcile lands: a connection drop mid-reconcile
                    # retries everything next beat.
                    self._reconcile_with_gcs(hb)
                    pending_reconcile = False
                if instance is not None:
                    gcs_instance = instance
            except (RpcConnectionError, TimeoutError):
                logger.warning("heartbeat to GCS failed; retrying")
                try:
                    if hb is not None:
                        hb.close()
                except Exception as e:
                    logger.debug("closing stale heartbeat connection "
                                 "failed: %r", e)
                hb = None

    def _reconcile_with_gcs(self, hb: RpcClient) -> None:
        """Resubscribe-and-reconcile after a partition heals or the GCS
        restarts: re-announce the node (scheduling resumes), re-publish
        its resource totals (PG shadow resources included), and re-pin
        every resident object's location in the directory — the GCS
        dropped them when it declared us dead (or restarted empty), and
        objects that only live here would otherwise be unfetchable
        forever. Batched into chunked RPCs so the re-report costs
        O(entries/4096) round trips inside the heartbeat loop, not one
        blocking call per object (which would stall liveness past the
        death threshold right after recovery)."""
        with self._avail_lock:
            totals = dict(self.resources)
        hb.call("register_node", node_id=self.node_id,
                address=self.server.address,
                resources=totals, timeout=10.0)
        entries = list(self.store.entries())
        for i in range(0, len(entries), 4096):
            hb.call("object_add_locations",
                    node_id=self.node_id,
                    entries=entries[i:i + 4096],
                    timeout=30.0)

    # -------------------------------------------------------------- objects
    def put_object(self, object_id: bytes, payload: bytes,
                   is_error: bool = False, register: bool = True,
                   primary: bool = True) -> dict:
        self.store.put(object_id, payload, is_error, primary=primary)
        if register:
            self._register_location(object_id, len(payload))
        return {"ok": True}

    def _register_location(self, object_id: bytes, size: int) -> None:
        try:
            self.gcs.call("object_add_location", object_id=object_id,
                          node_id=self.node_id, size=size, timeout=10.0)
        except (RpcConnectionError, TimeoutError):
            logger.warning("failed to register location for %s",
                           object_id.hex()[:8])

    def wait_object(self, object_id: bytes, timeout_s: float = 10.0) -> dict:
        return {"present": self.store.wait(object_id, timeout_s)}

    def delete_object(self, object_id: bytes) -> dict:
        # internal (not a registered RPC): the wire surface for
        # deletion is the batched free_objects
        self.store.delete(object_id)
        try:
            self.gcs.call("object_remove_location", object_id=object_id,
                          node_id=self.node_id, timeout=10.0)
        except (RpcConnectionError, TimeoutError) as e:
            # stale directory entry: readers fall back to the pull
            # retry loop, which re-resolves locations
            logger.debug("deregistering %s with GCS failed: %r",
                         object_id.hex()[:8], e)
        return {"ok": True}

    def free_objects(self, object_ids: List[bytes]) -> dict:
        for oid in object_ids:
            self.delete_object(oid)
        return {"ok": True}

    def get_object(self, object_id: bytes):
        """Stream handler: header dict then payload chunks (the chunked
        Push of object_manager.cc:463 SendObjectChunk, pull-initiated).
        Serving a spilled object restores it from disk first."""
        meta = self.store.info(object_id)
        entry = self.store.get(object_id)
        if entry is None:
            raise KeyError(f"object {object_id.hex()[:8]} not on node "
                           f"{self.node_id[:8]}")
        is_error, payload = entry
        # the header frame carries this holder's digest so the puller
        # verifies the reassembled payload at completion (integrity
        # plane; a store.get above already verified a spilled replica)
        yield {"size": len(payload), "is_error": is_error,
               "crc": (meta or {}).get("crc")}
        view = memoryview(payload)
        for off in range(0, len(payload), self.chunk_size):
            yield view[off:off + self.chunk_size]
        if not payload:
            yield b""

    def get_object_info(self, object_id: bytes) -> dict:
        """Transfer negotiation: tells a peer whether (and how) this
        node can serve the object. ``shm_path`` is set when the payload
        sits in this node's shared-memory segment — a peer ON THE SAME
        HOST attaches the segment and copies under the C store's
        process-shared mutex, skipping the TCP stream entirely (the
        plasma insight — src/ray/object_manager/plasma/: intra-host
        transport is shared memory, sockets are for metadata)."""
        meta = self.store.info(object_id)
        if meta is None:
            return {"present": False}
        info = {"present": True, "size": meta["size"],
                "is_error": meta["is_error"], "crc": meta.get("crc")}
        if meta["where"] == "shm" and meta.get("shm_path"):
            # per-entry path: an ADOPTED replica names the owner's
            # segment (where the bytes physically are), not ours
            info["shm_path"] = meta["shm_path"]
        return info

    # ------------------------------------------------------ object transfer
    def _peer(self, address: str) -> RpcClient:
        with self._peer_lock:
            c = self._peer_clients.get(address)
        if c is not None and not c.closed:
            return c
        # connect OUTSIDE the lock (RC01: the TCP dial blocks); on a
        # lost race the loser closes its own dial instead of leaking it
        fresh = RpcClient(address)
        with self._peer_lock:
            cur = self._peer_clients.get(address)
            if cur is not None and not cur.closed:
                c = cur
            else:
                self._peer_clients[address] = fresh
                c = fresh
        if c is not fresh:
            fresh.close()
        return c

    def _attach_peer_shm(self, path: str):
        from ray_tpu.cluster.byte_store import attach_shm

        return attach_shm(path)

    def _pull_object(self, object_id: bytes, timeout: float = 60.0) -> bool:
        """Ensure object_id is in the local store, pulling from a peer if
        needed. Concurrent pulls of the same object dedup onto one fetch
        (reference: ObjectManager pull dedup + PullManager retry)."""
        if self.store.contains(object_id):
            return True
        with self._pull_lock:
            ev = self._inflight_pulls.get(object_id)
            if ev is None:
                ev = threading.Event()
                self._inflight_pulls[object_id] = ev
                leader = True
            else:
                leader = False
        if not leader:
            ev.wait(timeout)
            return self.store.contains(object_id)
        try:
            return self._pull_object_leader(object_id, timeout)
        finally:
            with self._pull_lock:
                self._inflight_pulls.pop(object_id, None)
            ev.set()

    def _pull_object_leader(self, object_id: bytes, timeout: float) -> bool:
        from ray_tpu.scheduler.pull_manager import BundlePriority

        deadline = time.monotonic() + timeout
        # a sender is already pushing this object to us: wait for that
        # transfer instead of opening a duplicate pull stream
        with self._inbound_lock:
            st = self._inbound_pushes.get(object_id)
        if st is not None:
            # bounded: a sender that died mid-stream must not consume
            # the whole pull deadline (its slot is reclaimed by the
            # next push_begin after the staleness window)
            st["event"].wait(
                min(10.0, max(0.0, deadline - time.monotonic())))
            if self.store.contains(object_id):
                return True
        while time.monotonic() < deadline:
            try:
                wait_s = min(5.0, max(0.1, deadline - time.monotonic()))
                reply = self.gcs.call(
                    "object_wait_location", object_id=object_id,
                    timeout_s=wait_s, timeout=wait_s + 10.0,
                )
            except (RpcConnectionError, TimeoutError) as e:
                logger.warning("pull: location wait failed for %s: %r",
                               object_id.hex()[:8], e)
                return False
            locations = [loc for loc in reply["locations"]
                         if loc["node_id"] != self.node_id]
            # spread load across replicas: each completed fetch registers
            # a new location, so a fan-in (N nodes pulling one object)
            # organically becomes a fan-out tree — later pullers hit the
            # fresh replicas instead of all hammering the producer
            # (reference broadcast behavior; object_store.json baseline)
            self._pull_rng.shuffle(locations)
            if not locations:
                if self.store.contains(object_id):
                    return True
                time.sleep(0.05)
                continue
            size = reply.get("size", 0)
            pm = self.store.pull_manager
            bundle = pm.pull(BundlePriority.TASK_ARGS, [object_id],
                             [size])
            try:
                if not pm.wait_active(
                        bundle, max(0.0, deadline - time.monotonic())):
                    logger.warning("pull: admission wait timed out for %s",
                                   object_id.hex()[:8])
                    return False
                for loc in locations:
                    if self._fetch_from(loc["address"], object_id):
                        return True
                logger.warning("pull: every holder failed for %s (locations %s)",
                               object_id.hex()[:8],
                               [l["node_id"][:8] for l in locations])
            finally:
                pm.cancel(bundle)
            time.sleep(0.05)
        return self.store.contains(object_id)

    num_shm_fetches = 0
    num_stream_fetches = 0
    num_zero_copy_handoffs = 0
    # dispatch fast lane: task_batch pipe frames sent / rows they carried
    num_exec_batches = 0
    num_exec_batch_rows = 0
    # inbound push accounting: same-host segment adoption/memcpy vs
    # chunked TCP stream — the broadcast bench reads these to prove
    # which path its rate measured
    num_push_shm_in = 0
    num_push_stream_in = 0
    # data-plane pipeline: chunk-tree traffic through this node, torn
    # down half-receives, and the cut-through overlap aggregate (what
    # fraction of downstream forwarding happened inside our own
    # receive window — ~1.0 is true cut-through, ~0 store-and-forward)
    num_chunks_in = 0
    num_chunks_forwarded = 0
    num_push_teardowns = 0
    # chunk-tree failover: subtrees re-rooted here after their feeding
    # relay died mid-broadcast (chunk_tree_failover_enabled)
    num_tree_failovers = 0
    ct_overlap_sum = 0.0
    ct_overlap_n = 0

    def _fetch_from(self, address: str, object_id: bytes) -> bool:
        from ray_tpu.cluster.rpc import fetch_object

        try:
            peer = self._peer(address)
        except (RpcConnectionError, OSError):
            return False
        # Same-host fast path: when the holder's copy sits in its shm
        # segment and that segment is reachable through the filesystem
        # (= same host), attach it and copy under the C store's
        # process-shared mutex — one memcpy instead of a framed TCP
        # stream. Falls back to the stream on any miss or race (holder
        # evicted/spilled the object between info and read).
        try:
            info = peer.call("get_object_info", object_id=object_id,
                             timeout=10.0)
        except (RpcConnectionError, TimeoutError) as e:
            logger.warning("pull: info rpc to holder failed for %s: %r",
                           object_id.hex()[:8], e)
            return False
        if not info.get("present"):
            logger.warning("pull: %s no longer resident at %s (stale location)",
                           object_id.hex()[:8], address)
            return False
        shm_path = info.get("shm_path")
        if shm_path and Config.instance().data_plane_stream_only:
            # bench/test knob: pretend the holder is on another host —
            # skip every same-host shm shortcut so the pull exercises
            # the framed stream path
            shm_path = None
        if shm_path:
            if Config.instance().data_plane_pipeline_enabled:
                # data plane ON: ADOPT the holder's sealed segment entry
                # — a shared mapping plus a cross-process pin, zero
                # payload bytes moved (plasma's one-copy-per-host
                # posture). Verification is the O(1) trailer/offer digest
                # compare inside adopt_remote_shm. Any failure falls
                # through to the copying fast path below.
                if self.store.adopt_remote_shm(
                        object_id, shm_path, info["size"],
                        info["is_error"], crc=info.get("crc"),
                        primary=False):
                    self._register_location(object_id, info["size"])
                    with self._stats_lock:
                        self.num_shm_fetches += 1
                    return True
            seg = self._attach_peer_shm(shm_path)
            if seg is not None:
                key = shm_key(object_id)
                try:
                    # segment-to-segment: pin the holder's entry (the C
                    # refcount lives in the shared segment, so the
                    # holder cannot free it mid-copy), then write the
                    # replica straight into our own segment — one
                    # memcpy, no heap bounce
                    buf = seg.get_buffer(key)
                except Exception:
                    buf = None
                if buf is not None:
                    try:
                        # trailer-aware slice: the holder's crc rides
                        # along into our entry; the copy itself is
                        # re-verified only under the
                        # integrity_verify_shm_reads knob (an
                        # intra-host memcpy — see config.py)
                        payload, t_crc = integrity.split_shm(
                            buf, info["size"])
                        if payload is not None:
                            crc = info.get("crc")
                            crc = crc if crc is not None else t_crc
                            try:
                                if integrity.verify_shm_reads():
                                    integrity.verify(payload, crc,
                                                     "shm_read",
                                                     object_id)
                            except ObjectCorruptedError:
                                payload = None  # stream fallback
                        if payload is not None:
                            self.store.put(object_id, payload,
                                           info["is_error"],
                                           primary=False, crc=crc)
                            self._register_location(object_id,
                                                    len(payload))
                            with self._stats_lock:
                                self.num_shm_fetches += 1
                            return True
                    finally:
                        seg.release(key)
        result = fetch_object(peer, object_id)
        if result is None:
            logger.warning("pull: chunked stream of %s from %s failed",
                           object_id.hex()[:8], address)
            return False
        is_error, payload = result
        self.store.put(object_id, payload, is_error, primary=False)
        self._register_location(object_id, len(payload))
        with self._stats_lock:
            self.num_stream_fetches += 1
        return True

    # ------------------------------------------------------------ push path
    # Reference: ObjectManager::Push / HandlePush / SendObjectChunk
    # (object_manager.cc:302,463,509) + PushManager throttling
    # (push_manager.h). A push is sender-initiated: offer (lets a
    # same-host receiver adopt the segment entry or take the shm copy
    # fast path), else a chunked stream. With the data-plane pipeline ON
    # the stream is raw wire frames recv_into'd straight into the
    # receiver's final segment bytes, per-chunk digests verify BEFORE
    # cut-through forwarding, and a ``downstream`` subtree plan turns
    # each receiver into an interior chunk-tree node that forwards chunk
    # k the moment it verified — tree depth costs latency per CHUNK, not
    # per object.
    def push_object(self, object_id: bytes, to_address: str,
                    downstream: Optional[list] = None) -> dict:
        """Ask this node to push a local object to a peer. Dedup +
        concurrency limits are the PushManager's. ``downstream`` is the
        receiver's subtree plan ([[address, subtree], ...])."""
        if not self.store.contains(object_id):
            return {"ok": False, "reason": "not local"}
        return {"ok": self.push_manager.push(object_id, to_address,
                                             downstream=downstream)}

    def pull_object(self, object_id: bytes,
                    from_address: Optional[str] = None) -> dict:
        """Wire surface of ``_pull_object``: the flat broadcast
        topology and the driver's re-pull convergence fallback ask a
        node to ensure a local replica. ``from_address`` short-circuits
        the directory lookup when the caller knows a holder."""
        if self.store.contains(object_id):
            return {"ok": True}
        if from_address:
            try:
                if self._fetch_from(from_address, object_id):
                    return {"ok": True}
            except Exception as e:
                logger.debug("pull_object: direct fetch of %s from %s "
                             "failed: %r", object_id.hex()[:8],
                             from_address, e)
        return {"ok": self._pull_object(object_id, timeout=60.0)}

    def _dp_chunk_bytes(self) -> int:
        cfg = Config.instance()
        return (cfg.data_plane_chunk_bytes
                if cfg.data_plane_chunk_bytes > 0
                else cfg.object_chunk_size)

    def _send_push(self, object_id: bytes, dest: str,
                   downstream: Optional[list] = None) -> None:
        # metadata first: when the receiver takes the shm fast path the
        # payload never needs materializing here (a spilled or
        # shm-resident multi-GiB object would otherwise be copied to
        # the heap just to measure its length)
        cfg = Config.instance()
        # lane breaker (cluster/overload.py): K consecutive pipelined
        # push failures degrade this sender to the legacy stream until
        # a half-open probe transfer survives; the Config master switch
        # itself is never written
        dp = (cfg.data_plane_pipeline_enabled
              and _overload.lane_enabled("data_plane"))
        reroot = self._pop_reroot(object_id, dest)
        meta = self.store.info(object_id)
        if meta is None:
            return
        peer = self._peer(dest)
        offer = {"object_id": object_id, "size": meta["size"],
                 "is_error": meta["is_error"], "crc": meta.get("crc")}
        if (meta["where"] == "shm" and meta.get("shm_path")
                and not (dp and cfg.data_plane_stream_only)):
            # per-entry path: an adopted replica offers the OWNER's
            # segment; stream_only (test/bench knob) withholds the path
            # so the chunk-tree stream is what gets exercised
            offer["shm_path"] = meta["shm_path"]
        if dp and downstream:
            offer["downstream"] = downstream
        if peer.call("push_offer", timeout=60.0, **offer).get("done"):
            if dp:
                _overload.lane_ok("data_plane")
            return
        if dp:
            try:
                self._send_push_pipelined(peer, object_id, dest, meta,
                                          downstream, reroot=reroot)
            except BaseException:
                _overload.lane_failed("data_plane")
                raise
            _overload.lane_ok("data_plane")
            return
        entry = self.store.get(object_id)  # stream fallback: need bytes
        if entry is None:
            return
        is_error, payload = entry
        if not peer.call("push_begin", object_id=object_id,
                         size=len(payload), is_error=is_error,
                         crc=meta.get("crc"),
                         timeout=30.0).get("accept"):
            return  # receiver already has it (or one is inbound)
        view = memoryview(payload)
        with_crc = integrity.enabled()
        # raycheck: disable=RC10 — bounded by the in-flight throttle directly below (len(pending) > 4 drains before the next chunk enqueues)
        pending: deque = deque()
        try:
            for off in range(0, len(payload), self.chunk_size):
                chunk = bytes(view[off:off + self.chunk_size])
                pending.append(peer.call_async(
                    "push_chunk", object_id=object_id, chunk=chunk,
                    crc=(integrity.checksum(chunk) if with_crc
                         else None)))
                while len(pending) > 4:  # chunks in flight, the throttle
                    pending.popleft().result(timeout=60.0)
            while pending:
                pending.popleft().result(timeout=60.0)
            peer.call("push_end", object_id=object_id, timeout=60.0)
        except BaseException:
            try:  # free the receiver's reassembly slot
                peer.call("push_abort", object_id=object_id, timeout=10.0)
            except Exception as e:
                # receiver unreachable: its push_begin staleness window
                # reclaims the slot
                logger.debug("push_abort of %s to %s failed: %r",
                             object_id.hex()[:8], dest, e)
            raise

    def _pop_reroot(self, object_id: bytes, dest: str) -> bool:
        """Consume a pending failover mark for (object, dest): True
        means this push is a re-root re-offer and its push_begin should
        carry ``reroot=True``."""
        with self._reroot_lock:
            try:
                self._reroot_pending.remove((object_id, dest))
                return True
            except KeyError:
                return False

    def _mark_reroot(self, object_id: bytes, dest: str) -> None:
        with self._reroot_lock:
            self._reroot_pending.add((object_id, dest))

    def _send_push_pipelined(self, peer: RpcClient, object_id: bytes,
                             dest: str, meta: dict,
                             downstream: Optional[list],
                             reroot: bool = False) -> None:
        """Data-plane ON stream: zero-copy source (chunks are slices of
        the pinned entry view, no heap bounce), raw wire frames (the
        payload travels out of band of the pickled header and lands via
        ``recv_into`` in the receiver's final segment bytes), a
        config-sized in-flight window, and the nested ``downstream``
        plan that makes the receiver an interior chunk-tree node."""
        cfg = Config.instance()
        pv = self.store.view_and_pin(object_id)
        if pv is None:
            return
        is_error, view, crc = pv
        try:
            size = len(view)
            chunk = self._dp_chunk_bytes()
            window = max(1, cfg.data_plane_window)
            if not peer.call("push_begin", object_id=object_id,
                             size=size, is_error=is_error, crc=crc,
                             downstream=downstream or None,
                             chunk_bytes=chunk, reroot=reroot,
                             timeout=30.0).get("accept"):
                return  # receiver already has it (or one is inbound)
            with_crc = integrity.enabled()
            # raycheck: disable=RC10 — bounded by the in-flight window drain directly below
            pending: deque = deque()
            try:
                for off in range(0, size, chunk):
                    piece = view[off:off + chunk]
                    pending.append(peer.call_data_async(
                        "push_chunk_data", piece, object_id=object_id,
                        offset=off,
                        crc=(integrity.checksum(piece) if with_crc
                             else None)))
                    while len(pending) >= window:
                        r = pending.popleft().result(timeout=60.0)
                        if not r.get("ok"):
                            raise RuntimeError(
                                f"receiver rejected chunk of "
                                f"{object_id.hex()[:8]}: {r}")
                while pending:
                    r = pending.popleft().result(timeout=60.0)
                    if not r.get("ok"):
                        raise RuntimeError(
                            f"receiver rejected chunk of "
                            f"{object_id.hex()[:8]}: {r}")
                end = peer.call("push_end", object_id=object_id,
                                timeout=120.0)
                if not end.get("ok"):
                    logger.info("pipelined push of %s to %s did not "
                                "seal: %s", object_id.hex()[:8], dest,
                                end)
            except BaseException:
                try:  # free the receiver's (and its subtree's) slots
                    peer.call("push_abort", object_id=object_id,
                              timeout=10.0)
                except Exception as e:
                    # receiver unreachable: the stale-inbound sweep
                    # reclaims the slot
                    logger.debug("push_abort of %s to %s failed: %r",
                                 object_id.hex()[:8], dest, e)
                raise
        finally:
            self.store.unpin(object_id)

    def _relay_downstream(self, object_id: bytes,
                          downstream: Optional[list]) -> None:
        """Feed a subtree plan from THIS node's copy: each child gets
        its own push (with its sub-subtree riding along) through the
        push manager — the adoption fast path's analogue of cut-through
        forwarding (there are no chunks to forward; the whole object is
        already servable here)."""
        for item in downstream or []:
            try:
                addr, subtree = item[0], item[1]
            except (TypeError, IndexError):
                continue
            self.push_manager.push(object_id, addr,
                                   downstream=subtree or None)

    def push_offer(self, object_id: bytes, size: int, is_error: bool,
                   shm_path: Optional[str] = None,
                   crc: Optional[int] = None,
                   downstream: Optional[list] = None) -> dict:
        """Receiver side of a push: adopts the sender's segment entry
        (data plane ON, same host — a shared mapping, zero bytes moved)
        or takes the copying shm fast path; ``done=False`` asks the
        sender to stream. A ``downstream`` subtree is relayed onward
        from this node's copy either way."""
        dp = Config.instance().data_plane_pipeline_enabled
        if self.store.contains(object_id):
            if dp:
                self._relay_downstream(object_id, downstream)
            return {"done": True}
        if shm_path and dp:
            if self.store.adopt_remote_shm(object_id, shm_path, size,
                                           is_error, crc=crc,
                                           primary=False):
                self._register_location(object_id, size)
                with self._stats_lock:
                    self.num_push_shm_in += 1
                self._relay_downstream(object_id, downstream)
                return {"done": True}
        if shm_path:
            seg = self._attach_peer_shm(shm_path)
            if seg is not None:
                key = shm_key(object_id)
                try:
                    # segment-to-segment single memcpy (same discipline
                    # as the pull fast path): pin the holder's entry and
                    # write the replica straight into our own store — a
                    # get_bytes() here would bounce GiB-scale payloads
                    # through the heap, doubling broadcast time
                    buf = seg.get_buffer(key)
                except Exception:
                    buf = None
                if buf is not None:
                    try:
                        # trailer-aware slice; the sender's digest is
                        # adopted with the replica, and the copy is
                        # re-verified under the verify-shm-reads knob
                        # — a mismatch asks the sender to stream
                        # instead (whose checksums always verify)
                        payload, t_crc = integrity.split_shm(buf, size)
                        if payload is not None:
                            eff = crc if crc is not None else t_crc
                            try:
                                if integrity.verify_shm_reads():
                                    integrity.verify(payload, eff,
                                                     "shm_read",
                                                     object_id)
                            except ObjectCorruptedError:
                                payload = None
                        if payload is not None:
                            self._accept_push(object_id, payload,
                                              is_error, crc=eff)
                            with self._stats_lock:
                                self.num_push_shm_in += 1
                            if dp:
                                self._relay_downstream(object_id,
                                                       downstream)
                            return {"done": True}
                    finally:
                        seg.release(key)
        return {"done": False}

    def push_begin(self, object_id: bytes, size: int, is_error: bool,
                   crc: Optional[int] = None,
                   downstream: Optional[list] = None,
                   chunk_bytes: Optional[int] = None,
                   reroot: bool = False) -> dict:
        reclaim = None
        with self._inbound_lock:
            st = self._inbound_pushes.get(object_id)
            if st is not None:
                h = st.get("h")
                t_last = h.t_last if h is not None else st["t0"]
                limit = (Config.instance().data_plane_inbound_stale_s
                         if h is not None else 120.0)
                if time.monotonic() - t_last > limit:
                    # the previous sender died mid-stream and never
                    # aborted: reclaim the slot so the object does not
                    # become permanently unpushable on this node
                    reclaim = self._inbound_pushes.pop(object_id)
                    st = None
                elif (reroot and h is not None and Config.instance()
                        .chunk_tree_failover_enabled):
                    # failover re-offer from a re-rooted parent: the
                    # half-open inbound we hold was fed by a relay that
                    # died mid-tree and will never complete — supersede
                    # it (the teardown cascades aborts down our own
                    # subtree, whose slots the fresh stream's downstream
                    # plan reopens) and accept the replacement. The
                    # whole-object CRC makes the spliced replica
                    # verifiably identical to the one the dead relay
                    # was sending.
                    reclaim = self._inbound_pushes.pop(object_id)
                    st = None
        if reclaim is not None:
            self._teardown_inbound(object_id, reclaim)
        if st is not None or self.store.contains(object_id):
            return {"accept": False}
        if chunk_bytes is None:
            # legacy stream: reassembly bytearray, admitted at push_end
            with self._inbound_lock:
                if object_id in self._inbound_pushes:
                    return {"accept": False}
                self._inbound_pushes[object_id] = {
                    "buf": bytearray(size), "off": 0,
                    "is_error": is_error,
                    "event": threading.Event(), "t0": time.monotonic(),
                    # integrity: whole-object digest + the running count
                    # of chunk-verified bytes (when every chunk carried
                    # a crc, the end-of-stream whole-buffer pass is
                    # redundant)
                    "crc": crc, "chunk_verified": 0}
            return {"accept": True}
        # ---- pipelined chunk-tree receive (data plane ON sender) ----
        # reserve the inbound slot FIRST (under the lock), then allocate
        # the final bytes and open the downstream children outside it —
        # child push_begins are blocking RPCs
        st = {"h": None, "event": threading.Event(),
              "t0": time.monotonic(), "crc": crc, "chunk_verified": 0,
              "children": [],
              "window": max(1, Config.instance().data_plane_window),
              "t_recv": [None, None], "t_fwd": [None, None]}
        with self._inbound_lock:
            if object_id in self._inbound_pushes:
                return {"accept": False}
            self._inbound_pushes[object_id] = st
        h = self.store.begin_receive(object_id, size, is_error, crc)
        if h is None:  # became resident in the window above
            with self._inbound_lock:
                self._inbound_pushes.pop(object_id, None)
            st["event"].set()
            return {"accept": False}
        st["h"] = h
        # open the subtree: each child gets its own push_begin with its
        # sub-subtree. A child that declines (already holds the object,
        # or has one inbound) orphans ITS subtree — adopt the
        # grandchildren as our own children so no leaf goes unfed.
        worklist = list(downstream or [])
        while worklist:
            item = worklist.pop(0)
            try:
                addr, subtree = item[0], item[1]
            except (TypeError, IndexError):
                continue
            try:
                c = self._peer(addr)
                r = c.call("push_begin", object_id=object_id,
                           size=size, is_error=is_error, crc=crc,
                           downstream=subtree or None,
                           chunk_bytes=chunk_bytes, timeout=30.0)
            except (RpcConnectionError, TimeoutError, OSError) as e:
                logger.info("chunk-tree child %s unreachable at begin "
                            "(%r); adopting its subtree", addr, e)
                worklist.extend(subtree or [])
                continue
            if r.get("accept"):
                # Bounded in practice: _forward_chunk drains each
                # child's pending below the in-flight window before
                # every enqueue (cut-through window backpressure).
                st["children"].append(
                    {"address": addr, "client": c,
                     "pending": deque(),  # raycheck: disable=RC10 — drained below the in-flight window before every enqueue
                     # the child's own subtree plan, kept so a child
                     # dying mid-stream can be failed over: this node
                     # re-roots the orphans at seal time
                     "subtree": subtree or [],
                     "dead": False})
            else:
                worklist.extend(subtree or [])
        return {"accept": True}

    def _teardown_inbound(self, object_id: bytes, st: dict) -> None:
        """Free a half-assembled inbound transfer (sender death, chunk
        digest failure, staleness): tear down the preallocated segment
        bytes and cascade aborts so the whole subtree's slots free too.
        The caller has already popped ``st`` from ``_inbound_pushes``."""
        if "h" in st:
            self.store.abort_receive(object_id)
            with self._stats_lock:
                self.num_push_teardowns += 1
            for ch in st.get("children", []):
                try:
                    ch["client"].call("push_abort", object_id=object_id,
                                      timeout=10.0)
                except Exception as e:
                    # unreachable child: its own stale sweep reclaims
                    logger.debug("cascading push_abort of %s to %s "
                                 "failed: %r", object_id.hex()[:8],
                                 ch["address"], e)
        st["event"].set()

    def _sweep_stale_inbound(self) -> None:
        """Heartbeat-driven staleness sweep: an inbound pipelined
        transfer whose sender stopped making progress (node died after
        push_begin) is torn down and counted — half-assembled segment
        bytes must not outlive their sender (ISSUE r08 satellite). The
        legacy 120 s begin-time reclaim stays as the backstop for
        legacy-mode streams."""
        cfg = Config.instance()
        now = time.monotonic()
        stale = []
        with self._inbound_lock:
            for oid, st in list(self._inbound_pushes.items()):
                h = st.get("h")
                t_last = h.t_last if h is not None else st["t0"]
                limit = (cfg.data_plane_inbound_stale_s
                         if h is not None else 120.0)
                if now - t_last >= limit:
                    self._inbound_pushes.pop(oid, None)
                    stale.append((oid, st))
        for oid, st in stale:
            logger.warning("inbound push of %s stalled past %.0fs; "
                           "torn down", oid.hex()[:8],
                           cfg.data_plane_inbound_stale_s)
            self._teardown_inbound(oid, st)
        # backstop: store-level receives orphaned of any inbound entry
        self.store.sweep_stale_receives(
            max(cfg.data_plane_inbound_stale_s * 4, 120.0))

    def push_abort(self, object_id: bytes) -> dict:
        """Sender-side cleanup of a failed chunked push: frees the
        reassembly state (including a pipelined receive's preallocated
        segment bytes), cascades down the chunk tree, and wakes pulls
        parked on the inbound event (reference: PushManager chunk
        failure handling)."""
        with self._inbound_lock:
            st = self._inbound_pushes.pop(object_id, None)
        if st is not None:
            self._teardown_inbound(object_id, st)
        return {"ok": st is not None}

    def push_chunk(self, object_id: bytes, chunk: bytes,
                   crc: Optional[int] = None) -> dict:
        with self._inbound_lock:
            st = self._inbound_pushes.get(object_id)
        if st is None:
            return {"ok": False}
        if crc is not None and integrity.enabled():
            try:
                integrity.verify(chunk, crc, "push_chunk", object_id)
                st["chunk_verified"] += len(chunk)
            except ObjectCorruptedError:
                # wire corruption caught at chunk granularity: tear
                # down the reassembly before the bad bytes can ever be
                # assembled into a replica — the sender's transfer
                # fails and the consumer re-pulls/re-pushes
                self.store.num_corrupt_dropped += 1
                with self._inbound_lock:
                    self._inbound_pushes.pop(object_id, None)
                st["event"].set()
                logger.warning("inbound push chunk of %s failed its "
                               "digest; transfer discarded",
                               object_id.hex()[:8])
                return {"ok": False, "corrupt": True}
        off = st["off"]
        st["buf"][off:off + len(chunk)] = chunk
        st["off"] = off + len(chunk)
        return {"ok": True}

    def push_chunk_data(self, payload_len: int, recv_payload,
                        object_id: bytes, offset: int = 0,
                        crc: Optional[int] = None) -> dict:
        """Raw-frame chunk receive (data plane ON): ``recv_payload``
        lands the wire bytes DIRECTLY in the object's final segment
        offset (one copy, socket -> sealed-entry bytes), the chunk
        digest is checked on the still-cache-hot slice, and only then
        is the chunk cut-through forwarded down the subtree — a corrupt
        chunk is caught at THIS node and never amplifies downstream."""
        with self._inbound_lock:
            st = self._inbound_pushes.get(object_id)
        h = st.get("h") if st is not None else None
        if h is None or offset < 0 or offset + payload_len > h.size:
            return {"ok": False}  # dispatcher drains the unread payload
        dst = h.view[offset:offset + payload_len]
        recv_payload(dst)
        now = time.monotonic()
        h.t_last = now
        if st["t_recv"][0] is None:
            st["t_recv"][0] = now
        if crc is not None and integrity.enabled():
            actual = integrity.checksum(dst)
            if actual != crc:
                # caught BEFORE any forward: teardown self + subtree
                integrity.record_corruption("push_chunk")
                self.store.num_corrupt_dropped += 1
                with self._inbound_lock:
                    self._inbound_pushes.pop(object_id, None)
                self._teardown_inbound(object_id, st)
                logger.warning("inbound chunk of %s at offset %d failed "
                               "its digest; transfer (and subtree) "
                               "discarded", object_id.hex()[:8], offset)
                return {"ok": False, "corrupt": True}
            st["chunk_verified"] += payload_len
        h.landed += payload_len
        with self._stats_lock:
            self.num_chunks_in += 1
        # cut-through: the verified chunk goes downstream NOW, while
        # later chunks are still in flight to us — tree depth costs one
        # chunk's latency per level, not one object's
        for ch in st["children"]:
            if ch["dead"]:
                continue
            try:
                ch["pending"].append(ch["client"].call_data_async(
                    "push_chunk_data", dst, object_id=object_id,
                    offset=offset, crc=crc))
                with self._stats_lock:
                    self.num_chunks_forwarded += 1
                if st["t_fwd"][0] is None:
                    st["t_fwd"][0] = time.monotonic()
                while len(ch["pending"]) >= st["window"]:
                    if not ch["pending"].popleft().result(
                            timeout=60.0).get("ok"):
                        ch["dead"] = True
                        break
            except Exception as e:
                ch["dead"] = True
                logger.info("cut-through forward of %s to %s failed: "
                            "%r", object_id.hex()[:8], ch["address"], e)
        if st["children"] and st["t_fwd"][0] is not None:
            st["t_fwd"][1] = time.monotonic()
        st["t_recv"][1] = time.monotonic()
        return {"ok": True}

    def push_end(self, object_id: bytes) -> dict:
        with self._inbound_lock:
            st = self._inbound_pushes.pop(object_id, None)
        if st is None:
            return {"ok": False}
        if "h" in st:
            return self._push_end_pipelined(object_id, st)
        ok = st["off"] == len(st["buf"])
        if ok and st.get("crc") is not None and integrity.enabled() \
                and st["chunk_verified"] < len(st["buf"]):
            # not every chunk carried its own digest: verify the whole
            # reassembled payload against the push_begin crc (one pass
            # either way — chunk-verified streams skip this)
            try:
                integrity.verify(st["buf"], st["crc"], "push_end",
                                 object_id)
            except ObjectCorruptedError:
                self.store.num_corrupt_dropped += 1
                st["event"].set()
                logger.warning("inbound push of %s failed its digest "
                               "at assembly; replica discarded",
                               object_id.hex()[:8])
                return {"ok": False, "corrupt": True}
        if ok:
            self._accept_push(object_id, bytes(st["buf"]),
                              st["is_error"], crc=st.get("crc"))
            with self._stats_lock:
                self.num_push_stream_in += 1
        st["event"].set()
        return {"ok": ok}

    def _push_end_pipelined(self, object_id: bytes, st: dict) -> dict:
        """Seal a pipelined receive (coverage + digest posture checks),
        then cascade push_end down the subtree — children already hold
        every chunk (cut-through forwarded), so the cascade costs one
        small RPC per level, not a re-send."""
        h = st["h"]
        ok = h is not None and h.landed >= h.size
        corrupt = False
        if (ok and h.crc is not None and integrity.enabled()
                and st["chunk_verified"] < h.size):
            # sender streamed without per-chunk digests: one
            # whole-buffer pass against the push_begin crc
            # (chunk-verified streams skip this — every byte was
            # already checked the moment it landed)
            try:
                integrity.verify(h.view, h.crc, "push_end", object_id)
            except ObjectCorruptedError:
                corrupt = True
                self.store.num_corrupt_dropped += 1
                logger.warning("inbound pipelined push of %s failed its "
                               "digest at assembly; replica discarded",
                               object_id.hex()[:8])
        if ok and not corrupt:
            try:
                self.store.seal_receive(h, primary=False)
                self._register_location(object_id, h.size)
                with self._stats_lock:
                    self.num_push_stream_in += 1
            except ObjectCorruptedError:
                corrupt = True  # seal's end-to-end check (defensive)
            except Exception as e:
                ok = False  # seal_receive discarded the rx on its way out
                logger.warning("sealing pipelined receive of %s failed: "
                               "%r", object_id.hex()[:8], e)
        else:
            self.store.abort_receive(object_id)
            with self._stats_lock:
                self.num_push_teardowns += 1
        # cut-through overlap accounting (bench: how much of the
        # downstream forwarding happened DURING our own receive)
        tr, tf = st["t_recv"], st["t_fwd"]
        if (st["children"] and None not in tr and None not in tf
                and tr[1] > tr[0]):
            overlap = max(0.0, min(tr[1], tf[1]) - max(tr[0], tf[0]))
            with self._stats_lock:
                self.ct_overlap_sum += overlap / (tr[1] - tr[0])
                self.ct_overlap_n += 1
        # cascade: live children seal (and cascade further); dead ones
        # get a best-effort abort so their subtree slots free
        for ch in st["children"]:
            try:
                if ch["dead"]:
                    ch["client"].call("push_abort", object_id=object_id,
                                      timeout=10.0)
                    continue
                while ch["pending"]:
                    ch["pending"].popleft().result(timeout=60.0)
                ch["client"].call("push_end", object_id=object_id,
                                  timeout=120.0)
            except Exception as e:
                ch["dead"] = True
                logger.info("cascading push_end of %s to %s failed: %r",
                            object_id.hex()[:8], ch["address"], e)
        failed_children = [ch for ch in st["children"] if ch["dead"]]
        # chunk-tree failover: a child that died mid-stream orphaned
        # its whole subtree. We hold a sealed, CRC-verified replica, so
        # re-root the orphans HERE: each grandchild gets a fresh push
        # whose push_begin carries reroot=True, superseding the
        # half-open inbound the dead relay left behind. Best-effort —
        # if the re-offer loses a race with the dead child's own abort
        # cascade, the driver's re-pull convergence still covers the
        # subtree (the pre-failover behavior).
        if (ok and not corrupt and failed_children
                and Config.instance().chunk_tree_failover_enabled):
            from ray_tpu.observability.metrics import chunk_tree_failovers
            for ch in failed_children:
                kids = ch.get("subtree") or []
                if not kids:
                    continue
                with self._stats_lock:
                    self.num_tree_failovers += 1
                chunk_tree_failovers.inc()
                _overload.lane_failed("data_plane")
                logger.info("re-rooting %d orphaned subtree(s) of %s "
                            "after relay %s died mid-broadcast",
                            len(kids), object_id.hex()[:8],
                            ch["address"])
                for item in kids:
                    try:
                        addr, sub = item[0], item[1]
                    except (TypeError, IndexError):
                        continue
                    self._mark_reroot(object_id, addr)
                    self.push_manager.push(object_id, addr,
                                           downstream=sub or None)
        st["event"].set()
        out = {"ok": ok and not corrupt}
        if corrupt:
            out["corrupt"] = True
        return out

    def _accept_push(self, object_id: bytes, payload: bytes,
                     is_error: bool, crc: Optional[int] = None) -> None:
        self.store.put(object_id, payload, is_error, primary=False,
                       crc=crc)
        self._register_location(object_id, len(payload))

    # ---------------------------------------------------------------- tasks
    def submit_task(self, spec: dict) -> dict:
        """spec: task_id, func(bytes), args(list of ("v", bytes)|("ref",
        oid)), kwargs(dict name->same), resources, return_id, owner."""
        demand = spec.get("resources") or {}
        with self._avail_lock:
            feasible = all(self.resources.get(k, 0.0) >= v
                           for k, v in demand.items())
        if not feasible:
            return {"accepted": False, "reason": "infeasible"}
        cfg = Config.instance()
        with self._queue_cv:
            # Backpressure: the submit queue is bounded — beyond the
            # bound the caller gets a typed RetryLaterError (with a
            # queue-scaled hint) instead of the queue growing without
            # limit (reference: raylet task backpressure /
            # max_pending_lease_requests_per_scheduling_category).
            if (cfg.overload_enabled
                    and len(self._task_queue)
                    >= cfg.raylet_max_queued_tasks):
                with self._stats_lock:
                    self.num_tasks_shed += 1
                depth = len(self._task_queue)
                from ray_tpu.observability.metrics import tasks_shed

                tasks_shed.inc()
                raise RetryLaterError(
                    f"node {self.node_id[:8]} task queue is full "
                    f"({depth} queued); slow down",
                    retry_after_s=min(2.0, 0.05 + 1e-4 * depth))
            self._task_queue.append(_QueuedTask(spec))
            self._queue_cv.notify()
        return {"accepted": True, "node_id": self.node_id}

    def submit_task_batch(self, specs: List[dict]) -> dict:
        """Batched ``submit_task`` (dispatch fast lane): N specs per
        wire frame, admitted under ONE condition hold. Admission is
        per row — feasibility and the bounded-queue shed are checked
        spec by spec, and backpressure rides the result row
        (``{accepted: False, reason: "backpressure", retry_after_s}``,
        the RetryLaterError hint in-band) instead of failing the
        frame, so an overload sheds only the overflow rows while their
        siblings land. Rows may carry a per-row ``token`` (stamped once
        at driver submit time, stable across retries): an accepted
        row's token caches its reply, so a RETRIED frame after a lost
        ack replays the ack instead of enqueueing the task twice.
        Tokens are popped before the spec reaches the queue — the
        executed spec is byte-identical to the untokened path."""
        cfg = Config.instance()
        from ray_tpu.observability.metrics import (
            batch_rows_deduped,
            tasks_shed,
        )

        with self._avail_lock:
            totals = dict(self.resources)
        results: List[dict] = []
        accepted: List[_QueuedTask] = []
        replayed = 0
        with self._queue_cv:
            depth = len(self._task_queue)
            for spec in specs:
                tok = spec.pop("token", "") or ""
                cached = self._row_token_seen(tok)
                if cached is not None:
                    results.append(cached)
                    replayed += 1
                    continue
                demand = spec.get("resources") or {}
                if any(totals.get(k, 0.0) < v
                       for k, v in demand.items()):
                    results.append({"accepted": False,
                                    "reason": "infeasible"})
                    continue
                if (cfg.overload_enabled
                        and depth >= cfg.raylet_max_queued_tasks):
                    with self._stats_lock:
                        self.num_tasks_shed += 1
                    tasks_shed.inc()
                    results.append({
                        "accepted": False, "reason": "backpressure",
                        "retry_after_s": min(2.0,
                                             0.05 + 1e-4 * depth)})
                    continue
                accepted.append(_QueuedTask(spec))
                depth += 1
                row = {"accepted": True, "node_id": self.node_id}
                # only ACCEPTED rows cache: a shed/infeasible row is
                # not a mutation — the retry must be re-admitted fresh
                self._row_token_store(tok, row)
                results.append(row)
            if accepted:
                self._task_queue.extend(accepted)
                self._queue_cv.notify_all()
        if replayed:
            batch_rows_deduped.inc(
                replayed, tags={"method": "submit_task_batch"})
        return {"results": results, "node_id": self.node_id}

    # --------------------------------------------- per-row batch dedupe
    def _row_token_seen(self, token: str) -> Optional[dict]:
        """Cached reply row for a retried batch row (caller holds
        ``_queue_cv``); None admits the row."""
        if not token:
            return None
        return self._row_tokens.get(token)

    def _row_token_store(self, token: str, row: dict) -> None:
        """Cache an applied row's reply under its token (caller holds
        ``_queue_cv``); LRU-bounded like the GCS request-token cache."""
        if not token:
            return
        self._row_tokens[token] = row
        while len(self._row_tokens) > self._row_token_cap:
            self._row_tokens.popitem(last=False)

    def task_state(self, task_id: str) -> dict:
        with self._queue_cv:
            if task_id in self._done:
                return {"state": self._done[task_id]}
            if task_id in self._running:
                return {"state": "running"}
            if any(t.spec["task_id"] == task_id for t in self._task_queue):
                return {"state": "queued"}
        return {"state": "unknown"}

    def wait_task(self, task_id: str, timeout_s: float = 10.0) -> dict:
        deadline = time.monotonic() + timeout_s
        with self._queue_cv:
            while task_id not in self._done:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._queue_cv.wait(remaining)
        return self.task_state(task_id)

    def _try_allocate(self, demand: Dict[str, float]) -> bool:
        with self._avail_lock:
            if all(self.available.get(k, 0.0) >= v - 1e-9
                   for k, v in demand.items()):
                for k, v in demand.items():
                    self.available[k] = self.available.get(k, 0.0) - v
                return True
            return False

    def _free(self, demand: Dict[str, float]) -> None:
        with self._avail_lock:
            for k, v in demand.items():
                self.available[k] = self.available.get(k, 0.0) + v

    def _dispatch_loop(self) -> None:
        while not self._stop.is_set():
            task: Optional[_QueuedTask] = None
            batch: List[_QueuedTask] = []
            with self._queue_cv:
                while not self._task_queue and not self._stop.is_set():
                    self._queue_cv.wait(0.5)
                if self._stop.is_set():
                    return
                for i, cand in enumerate(self._task_queue):
                    if self._try_allocate(cand.spec.get("resources") or {}):
                        task = cand
                        del self._task_queue[i]
                        break
                if task is None:
                    self._queue_cv.wait(0.05)
                    continue
                self._running[task.spec["task_id"]] = task.spec
                batch.append(task)
                cfg = Config.instance()
                if cfg.dispatch_fastlane_enabled:
                    # Deep-backlog coalescing only: extra tasks ride
                    # this worker's ONE task_batch pipe frame (they run
                    # serially on it), so grab them only while the
                    # queue is deeper than the pool could drain in
                    # parallel anyway — a shallow queue keeps the exact
                    # one-task-per-lease concurrency.
                    extra = min(cfg.dispatch_batch_max - 1,
                                len(self._task_queue)
                                - 2 * self.pool.size)
                    while extra > 0 and self._task_queue:
                        cand = self._task_queue[0]
                        if not self._try_allocate(
                                cand.spec.get("resources") or {}):
                            break
                        self._task_queue.popleft()
                        self._running[cand.spec["task_id"]] = cand.spec
                        batch.append(cand)
                        extra -= 1
            try:
                if len(batch) == 1:
                    self._execute(task.spec)
                else:
                    with self._stats_lock:
                        self.num_exec_batches += 1
                        self.num_exec_batch_rows += len(batch)
                    self._execute_batch(batch)
            finally:
                for t in batch:
                    self._free(t.spec.get("resources") or {})
                with self._queue_cv:
                    for t in batch:
                        self._running.pop(t.spec["task_id"], None)
                    self._queue_cv.notify_all()

    def _same_host_handoff(self, object_id: bytes):
        """Zero-copy consumption of a same-host peer's object: pin it in
        the HOLDER's segment (C-store refcount, process-shared; deletes
        defer while pinned) and return (seg, key, path) for a
        StoredObjectArg — no replica, no copy; the worker reads the
        holder's pages in place. This is plasma's one-store-per-host
        model recovered for colocated raylet processes; cross-host
        objects still go through the chunked pull. Returns None when no
        same-host shm holder exists."""
        try:
            reply = self.gcs.call("object_locations",
                                  object_id=object_id, timeout=10.0)
        except (RpcConnectionError, TimeoutError):
            return None
        for loc in reply["locations"]:
            if loc["node_id"] == self.node_id:
                continue
            try:
                info = self._peer(loc["address"]).call(
                    "get_object_info", object_id=object_id, timeout=10.0)
            except (RpcConnectionError, TimeoutError, OSError):
                continue
            if not info.get("present") or info.get("is_error"):
                continue  # error payloads raise in the raylet: pull path
            path = info.get("shm_path")
            if not path:
                continue
            seg = self._attach_peer_shm(path)
            if seg is None:
                continue
            key = shm_key(object_id)
            try:
                region = seg.pin_region(key)  # the pin
            except Exception:
                region = None
            if region is None:
                continue
            off, size = region
            # a trailer-bearing entry (integrity plane) is 8 bytes
            # longer than the logical object; the worker reads only
            # the logical bytes either way
            if size not in (info["size"],
                            info["size"] + integrity.TRAILER_SIZE):
                seg.release(key)
                continue
            with self._stats_lock:
                self.num_zero_copy_handoffs += 1
            return seg, key, path, off, info["size"]
        return None

    def _resolve_args(self, packed, pinned: Optional[list] = None) -> Any:
        """("v", bytes) -> loads; ("ref", oid) -> pull + pin + loads.
        Stored errors propagate to the task as the reference does when a
        dependency failed (task fails with the dependency's error).
        Resolved refs are PINNED in the store (appended to ``pinned``;
        the caller unpins after the task finishes) so a concurrent
        put's reclaim cannot evict an argument between its pull and its
        use — the DependencyManager/plasma-pin contract."""
        kind, payload = packed
        if kind == "v":
            return protocol.loads(payload)
        if (pinned is not None and self.pool.shm_path
                and not self.store.contains(payload)
                and Config.instance().same_host_zero_copy_reads):
            handoff = self._same_host_handoff(payload)
            if handoff is not None:
                seg, key, path, off, size = handoff
                pinned.append(("peer", seg, key))
                return protocol.StoredObjectArg(key, path, off, size)
        corrupt_seen = False
        for attempt in range(4):
            # a replica eviction, a transient peer failure, or a
            # DISCARDED CORRUPT REPLICA can race the pull; each retry
            # re-resolves locations from the directory
            if not self._pull_object(payload):
                time.sleep(0.05 * attempt)
                continue
            meta = self.store.pin(payload)
            if meta is None:
                time.sleep(0.05 * attempt)
                continue
            keep_pin = False
            try:
                if (pinned is not None and not meta["is_error"]
                        and meta["where"] == "shm"
                        and self.pool.shm_path):
                    # zero-copy handoff: the worker reads the pinned
                    # segment entry itself; only the 20-byte key
                    # crosses the pipe. The pin (held until the task
                    # ends) blocks eviction and spill for the read
                    # window.
                    spath = meta.get("shm_path")
                    if spath and spath != self.store.shm_path:
                        # ADOPTED replica: the bytes sit in the OWNER's
                        # segment — hand the worker that segment's
                        # (path, offset, size) like a peer handoff; our
                        # store pin (which rides the owner's refcount)
                        # keeps the block alive for the read window
                        from ray_tpu.cluster.byte_store import attach_shm
                        seg = attach_shm(spath)
                        region = None
                        if seg is not None:
                            try:
                                # pin_region returns (offset, size)
                                # metadata, not a handle: the pin itself
                                # is keyed and recorded in `pinned`
                                # below, released by run_task's unwind
                                # raycheck: disable=RC12 — pin keyed in `pinned`, released at task end
                                region = seg.pin_region(shm_key(payload))
                            except Exception:
                                region = None
                        if region is not None:
                            off, rsize = region
                            keep_pin = True
                            pinned.append(("own", payload))
                            pinned.append(("peer", seg,
                                           shm_key(payload)))
                            return protocol.StoredObjectArg(
                                shm_key(payload), spath, off,
                                meta["size"])
                        # fall through to the copy path below
                    else:
                        keep_pin = True
                        pinned.append(("own", payload))
                        return protocol.StoredObjectArg(shm_key(payload))
                try:
                    entry = self.store.get(payload)
                except ObjectCorruptedError as e:
                    # the local replica failed its spill digest and
                    # discarded itself: re-pull from another holder
                    corrupt_seen = True
                    logger.warning(
                        "dependency %s corrupt locally (%s); "
                        "re-pulling", payload.hex()[:8], e.seam)
                    continue
                if entry is None:  # explicitly deleted under us
                    raise WorkerCrashedError(
                        f"dependency {payload.hex()[:8]} unavailable")
                is_error, data = entry
                value = protocol.loads_flat(data)
                if is_error:
                    raise value if isinstance(value, BaseException) \
                        else RuntimeError(str(value))
                if pinned is not None:
                    keep_pin = True
                    pinned.append(("own", payload))
                return value
            finally:
                if not keep_pin:
                    self.store.unpin(payload)
        raise WorkerCrashedError(
            f"dependency {payload.hex()[:8]} unavailable"
            + (" (corrupt replicas discarded)" if corrupt_seen else ""))

    def _stage_py_modules(self, runtime_env) -> None:
        """Pre-stage pymod:// archives into the host cache THROUGH THE
        RAYLET'S GCS KV before dispatch: worker processes have no GCS
        client, so the node-level agent does the fetch (reference: the
        per-node runtime-env agent downloads packages, workers only
        read the cache)."""
        entries = []
        if runtime_env is not None:
            try:
                entries = list(runtime_env.get("py_modules") or [])
            except AttributeError:
                return
        uris = [e for e in entries
                if isinstance(e, str) and e.startswith("pymod://")]
        if not uris:
            return
        from ray_tpu._private.runtime_env_packaging import (
            KV_NAMESPACE,
            default_py_modules_manager,
        )

        def fetch(key: bytes):
            return self.gcs.call("kv_get", ns=KV_NAMESPACE, key=key,
                                 timeout=30.0)

        manager = default_py_modules_manager()
        for uri in uris:
            try:
                manager.ensure_local(uri, fetch=fetch)
            except Exception:  # noqa: BLE001 — surface at import time
                logger.warning("py_modules stage failed for %s", uri,
                               exc_info=True)

    def _execute(self, spec: dict) -> None:
        task_id = spec["task_id"]
        return_id = spec["return_id"]
        # Sampled traces carry their context inside the spec (stamped by
        # ClusterClient.submit), so the execution span parents to the
        # driver's submit span across two process hops.
        wire_trace = spec.get("trace_context")
        if wire_trace is not None:
            # raycheck: disable=RC02 — wall-clock span timestamp for cross-process trace correlation, not deadline arithmetic
            exec_wall = time.time()
        exec_t0 = time.monotonic()
        pinned: list = []
        try:
            func = protocol.loads(spec["func"])
            args = [self._resolve_args(a, pinned)
                    for a in spec.get("args", [])]
            kwargs = {k: self._resolve_args(v, pinned)
                      for k, v in (spec.get("kwargs") or {}).items()}
            self._stage_py_modules(spec.get("runtime_env"))
            result = self.pool.run(
                func, tuple(args), kwargs,
                runtime_env=spec.get("runtime_env"),
                result_key=shm_key(return_id))
            if isinstance(result, protocol.StoredResult):
                # worker wrote the payload into the segment: adopt it —
                # the result never crossed the pipe
                if not self.store.adopt_shm(return_id, result.nbytes):
                    raise WorkerCrashedError(
                        "stored task result vanished from the segment")
                self._register_location(return_id, result.nbytes)
            elif isinstance(result, protocol.FlatPayload):
                # already in stored-object format: store verbatim (the
                # result is serialized exactly once, worker-side)
                self.store.put(return_id, result.body, is_error=False)
                self._register_location(return_id, len(result.body))
            else:
                payload = protocol.dumps_flat(result)
                self.store.put(return_id, payload, is_error=False)
                self._register_location(return_id, len(payload))
            state = "done"
        except BaseException as e:  # noqa: BLE001 — becomes a stored error
            payload = protocol.dumps_flat(protocol.restore_exception(
                *protocol.format_exception(e)))
            self.store.put(return_id, payload, is_error=True)
            self._register_location(return_id, len(payload))
            state = "failed"
            logger.info("task %s failed: %r", task_id[:8], e)
        finally:
            for entry in pinned:
                if entry[0] == "own":
                    self.store.unpin(entry[1])
                else:  # ("peer", seg, key): drop the peer-segment pin
                    try:
                        entry[1].release(entry[2])
                    except Exception as e:
                        # holder process may have died mid-task; its
                        # segment (and refcount) died with it
                        logger.debug("peer-segment unpin of %s failed: "
                                     "%r", entry[2].hex()[:8], e)
        if wire_trace is not None:
            try:
                from ray_tpu.util import tracing
                tracing.record_remote_span(
                    "task.execute", wire_trace, exec_wall,
                    exec_wall + (time.monotonic() - exec_t0),
                    attributes={"task_id": str(task_id)[:16],
                                "dst_kind": "raylet"},
                    status="OK" if state == "done" else "ERROR")
            except Exception as e:
                logger.debug("task execution span failed: %r", e)
        with self._queue_cv:
            self._done[task_id] = state
            while len(self._done) > self._done_cap:
                self._done.popitem(last=False)
            self._queue_cv.notify_all()

    def _adopt_result(self, return_id: bytes, result: Any) -> None:
        """Land one worker-produced result in the local store (the
        three transports ``_execute`` handles: shm adoption, verbatim
        flat payload, inline value)."""
        if isinstance(result, protocol.StoredResult):
            if not self.store.adopt_shm(return_id, result.nbytes):
                raise WorkerCrashedError(
                    "stored task result vanished from the segment")
            self._register_location(return_id, result.nbytes)
        elif isinstance(result, protocol.FlatPayload):
            self.store.put(return_id, result.body, is_error=False)
            self._register_location(return_id, len(result.body))
        else:
            payload = protocol.dumps_flat(result)
            self.store.put(return_id, payload, is_error=False)
            self._register_location(return_id, len(payload))

    def _finish_batch_row(self, spec: dict, exc: Optional[BaseException],
                          pinned: list, exec_wall: Optional[float],
                          exec_t0: float) -> None:
        """Terminal bookkeeping for one fast-lane batch row — the
        stored-error path, pin release, execution span, and the _done
        transition ``_execute`` performs for a serial task."""
        task_id = spec["task_id"]
        if exc is None:
            state = "done"
        else:
            return_id = spec["return_id"]
            payload = protocol.dumps_flat(protocol.restore_exception(
                *protocol.format_exception(exc)))
            self.store.put(return_id, payload, is_error=True)
            self._register_location(return_id, len(payload))
            state = "failed"
            logger.info("task %s failed: %r", task_id[:8], exc)
        for entry in pinned:
            if entry[0] == "own":
                self.store.unpin(entry[1])
            else:  # ("peer", seg, key)
                try:
                    entry[1].release(entry[2])
                except Exception as e:
                    logger.debug("peer-segment unpin of %s failed: %r",
                                 entry[2].hex()[:8], e)
        wire_trace = spec.get("trace_context")
        if wire_trace is not None and exec_wall is not None:
            try:
                from ray_tpu.util import tracing
                tracing.record_remote_span(
                    "task.execute", wire_trace, exec_wall,
                    exec_wall + (time.monotonic() - exec_t0),
                    attributes={"task_id": str(task_id)[:16],
                                "dst_kind": "raylet",
                                "batched": "1"},
                    status="OK" if state == "done" else "ERROR")
            except Exception as e:
                logger.debug("task execution span failed: %r", e)
        with self._queue_cv:
            self._done[task_id] = state
            while len(self._done) > self._done_cap:
                self._done.popitem(last=False)
            self._queue_cv.notify_all()

    def _execute_batch(self, tasks: List[_QueuedTask]) -> None:
        """Fast-lane execution of N dispatched tasks as ONE
        ``task_batch`` pipe frame on ONE leased worker: args resolve
        raylet-side per row (pins held for the batch's duration, same
        contract as ``_execute``), the worker runs the rows serially,
        and all N results return in one reply frame. Per-row failures
        (arg resolution, user exceptions) become that row's stored
        error; only a worker death fails every remaining row."""
        # raycheck: disable=RC02 — wall-clock span timestamp for cross-process trace correlation, not deadline arithmetic
        exec_wall = time.time() if any(
            t.spec.get("trace_context") is not None for t in tasks) \
            else None
        exec_t0 = time.monotonic()
        rows: List[Tuple[dict, list, dict]] = []  # (spec, pinned, item)
        for t in tasks:
            spec = t.spec
            pinned: list = []
            try:
                func = protocol.loads(spec["func"])
                args = [self._resolve_args(a, pinned)
                        for a in spec.get("args", [])]
                kwargs = {k: self._resolve_args(v, pinned)
                          for k, v in (spec.get("kwargs") or {}).items()}
                self._stage_py_modules(spec.get("runtime_env"))
                rows.append((spec, pinned, {
                    "func": func, "args": tuple(args), "kwargs": kwargs,
                    "runtime_env": spec.get("runtime_env"),
                    "result_key": shm_key(spec["return_id"])}))
            except BaseException as e:  # noqa: BLE001 — stored error
                self._finish_batch_row(spec, e, pinned, exec_wall,
                                       exec_t0)
        if not rows:
            return
        try:
            results = self.pool.run_batch([item for _, _, item in rows])
        except BaseException as e:  # noqa: BLE001 — worker death
            for spec, pinned, _ in rows:
                self._finish_batch_row(spec, e, pinned, exec_wall,
                                       exec_t0)
            return
        for (spec, pinned, _), (status, body) in zip(rows, results):
            exc: Optional[BaseException] = None
            if status == "ok":
                try:
                    self._adopt_result(spec["return_id"], body)
                except BaseException as e:  # noqa: BLE001
                    exc = e
            else:
                exc = body
            self._finish_batch_row(spec, exc, pinned, exec_wall,
                                   exec_t0)

    # ---------------------------------------------------------------- actors
    def create_actor(self, actor_id: str, cls_bytes: bytes,
                     args_bytes: bytes, resources: Dict[str, float],
                     incarnation: int = 0) -> dict:
        from ray_tpu.observability.metrics import actor_create_latency_ms

        t0 = time.monotonic()
        try:
            cls = protocol.loads(cls_bytes)
        except Exception as e:  # noqa: BLE001 — deterministic: bad class
            raise ActorInitError(
                f"actor {actor_id[:8]} class failed to deserialize: "
                f"{e!r}") from e
        args, kwargs = protocol.loads(args_bytes)
        args = [self._resolve_args(a) if isinstance(a, tuple)
                and len(a) == 2 and a[0] in ("v", "ref") else a
                for a in args]
        if not self._try_allocate(resources or {}):
            raise RuntimeError(
                f"node {self.node_id[:8]} lacks resources for actor")
        try:
            proxy = self.pool.create_actor_process(cls, tuple(args), kwargs)
        except WorkerCrashedError:
            # infra death (OOM kill, fork crash): the GCS may retry on
            # another node
            self._free(resources or {})
            raise
        except BaseException as e:
            self._free(resources or {})
            if isinstance(e, ActorInitError):
                raise
            # the worker ran user __init__ and it raised: DETERMINISTIC
            # — typed so the GCS marks the actor DEAD with the error
            # instead of burning placement retries on other nodes
            raise ActorInitError(
                f"actor {actor_id[:8]} __init__ failed: {e!r}") from e
        with self._actor_lock:
            self._actors[actor_id] = {
                "proxy": proxy, "incarnation": incarnation,
                "resources": dict(resources or {}),
            }
        actor_create_latency_ms.observe((time.monotonic() - t0) * 1e3)
        return {"ok": True, "incarnation": incarnation}

    def actor_call(self, actor_id: str, method_name: str,
                   args_bytes: bytes) -> bytes:
        with self._actor_lock:
            rec = self._actors.get(actor_id)
        if rec is None:
            raise KeyError(f"actor {actor_id[:8]} not on node "
                           f"{self.node_id[:8]}")
        args, kwargs = protocol.loads(args_bytes)
        args = [self._resolve_args(a) if isinstance(a, tuple)
                and len(a) == 2 and a[0] in ("v", "ref") else a
                for a in args]
        try:
            result = getattr(rec["proxy"], method_name)(*args, **kwargs)
        except WorkerCrashedError:
            # actor process died (not the node): report so the GCS can
            # restart it, then surface the death to the caller
            with self._actor_lock:
                self._actors.pop(actor_id, None)
            self._free(rec["resources"])
            try:
                # token: one restart per OBSERVED death — a duplicated
                # or retried report must not burn two restarts
                self.gcs.call("report_actor_failure", actor_id=actor_id,
                              token=os.urandom(8).hex(), timeout=10.0)
            except (RpcConnectionError, TimeoutError) as e:
                # GCS unreachable: node-death detection (or the next
                # caller's report) restarts the actor instead
                logger.debug("actor-failure report for %s failed: %r",
                             actor_id[:8], e)
            raise
        return protocol.dumps(result)

    def kill_actor(self, actor_id: str) -> dict:
        with self._actor_lock:
            rec = self._actors.pop(actor_id, None)
        if rec is None:
            return {"ok": False}
        try:
            rec["proxy"].__ray_on_kill__()
        except Exception as e:
            # kill is best-effort; terminate() escalates to SIGKILL
            logger.debug("actor %s kill hook failed: %r",
                         actor_id[:8], e)
        self._free(rec["resources"])
        return {"ok": True}

    # raycheck: disable=RC11 — kill rows are idempotent: killing an already-dead actor is a no-op (each kill re-checks the live-actor map), so a replayed frame changes nothing; the GCS-side actor_kill_batch holds the row tokens
    def kill_actor_batch(self, actor_ids: List[str]) -> dict:
        """One frame kills a node's whole share of an actor_kill_batch
        (GCS fan-out). Each kill is independent but NOT free — a clean
        warm-pool return is an actor_reset pipe round trip (worker-side
        gc.collect()), a dirty one a terminate wait — so the loop fans
        out over a bounded work-stealing thread set instead of paying
        those round trips serially (2000 kills must land in seconds)."""
        ok: Dict[str, bool] = {}
        ok_lock = threading.Lock()
        idx = itertools.count()

        def drain():
            while True:
                i = next(idx)
                if i >= len(actor_ids):
                    return
                aid = actor_ids[i]
                good = bool(self.kill_actor(aid).get("ok"))
                with ok_lock:
                    ok[aid] = good

        width = min(16, len(actor_ids))
        if width <= 1:
            drain()
        else:
            workers = [self._threads.spawn(
                drain, f"raylet-kill-batch-{t}") for t in range(width)]
            # budgeted join (RC17): a worker wedged on one actor's
            # terminate must not hang the whole batch RPC forever
            deadline = (time.monotonic()
                        + Config.instance().batch_fanout_join_timeout_s)
            for t in workers:
                t.join(max(0.0, deadline - time.monotonic()))
                if t.is_alive():
                    logger.warning("kill_actor_batch: worker %s still "
                                   "busy past join budget", t.name)
        return {"results": [{"actor_id": aid, "ok": ok.get(aid, False)}
                            for aid in actor_ids]}

    # ------------------------------------------------------------- PG 2PC
    # All three phases are IDEMPOTENT keyed by (pg_id, bundle_index)
    # (reference: placement_group_resource_manager.h's bundle state
    # table): a duplicated frame or a GCS retry after a lost ack must
    # not double-reserve, double-apply shadow resources, or double-free.
    def prepare_bundle(self, pg_id: str, bundle_index: int,
                       bundle: Dict[str, float]) -> bool:
        key = (pg_id, bundle_index)
        with self._avail_lock:
            if key in self._committed_bundles:
                return True  # retried prepare after the commit landed
            if key in self._prepared_bundles:
                # duplicated/retried prepare: reservation exists —
                # refresh its lease instead of allocating again
                self._prepared_at[key] = time.monotonic()
                return True
            if not self._try_allocate(bundle):
                return False
            self._prepared_bundles[key] = dict(bundle)
            self._prepared_at[key] = time.monotonic()
            return True

    def commit_bundle(self, pg_id: str, bundle_index: int,
                      bundle: Dict[str, float]) -> dict:
        from ray_tpu.scheduler.placement_group import (
            shadow_resources_for_bundle,
        )

        key = (pg_id, bundle_index)
        with self._avail_lock:
            if key in self._committed_bundles:
                return {"ok": True, "duplicate": True}
            if key not in self._prepared_bundles:
                # prepare never landed here (or its lease expired and
                # the reservation was returned): applying shadow
                # capacity with no base reservation would oversubscribe
                # the node — tell the GCS to re-prepare
                return {"ok": False, "reason": "not prepared"}
            shadow = shadow_resources_for_bundle(bundle, pg_id,
                                                 bundle_index)
            for name, amount in shadow.items():
                self.resources[name] = self.resources.get(name, 0.0) + amount
                self.available[name] = self.available.get(name, 0.0) + amount
            self._committed_bundles.add(key)
            self._prepared_at.pop(key, None)  # lease is for the gap only
        return {"ok": True}

    def return_bundle(self, pg_id: str, bundle_index: int,
                      bundle: Dict[str, float],
                      committed: bool = False) -> dict:
        from ray_tpu.scheduler.placement_group import (
            shadow_resources_for_bundle,
        )

        key = (pg_id, bundle_index)
        with self._avail_lock:
            if committed and key in self._committed_bundles:
                shadow = shadow_resources_for_bundle(bundle, pg_id,
                                                     bundle_index)
                for name in shadow:
                    self.resources.pop(name, None)
                    self.available.pop(name, None)
            self._committed_bundles.discard(key)
            self._prepared_at.pop(key, None)
            if self._prepared_bundles.pop(key, None) is not None:
                self._free(bundle)
        return {"ok": True}

    def _expire_prepared_bundles(self) -> None:
        """Reclaim prepared-but-uncommitted bundles whose GCS vanished
        mid-2PC (reference: ReleaseUnusedBundles on GCS restart) — the
        lease keeps a dead coordinator from leaking node capacity
        forever. Runs on the heartbeat cadence."""
        lease = Config.instance().pg_prepare_lease_s
        if lease <= 0:
            return
        now = time.monotonic()
        with self._avail_lock:
            for key, t0 in list(self._prepared_at.items()):
                if key in self._committed_bundles:
                    self._prepared_at.pop(key, None)
                    continue
                if now - t0 < lease:
                    continue
                bundle = self._prepared_bundles.pop(key, None)
                self._prepared_at.pop(key, None)
                if bundle is not None:
                    self._free(bundle)
                    logger.warning(
                        "prepared bundle %s expired uncommitted after "
                        "%.0fs; reservation returned", key, lease)

    # ------------------------------------------------------------ stats
    def node_stats(self) -> dict:
        with self._avail_lock:
            avail = dict(self.available)
            totals = dict(self.resources)
        with self._stats_lock:
            dispatch = {"exec_batches": self.num_exec_batches,
                        "exec_batch_rows": self.num_exec_batch_rows}
            fetches = {"shm": self.num_shm_fetches,
                       "stream": self.num_stream_fetches,
                       "zero_copy": self.num_zero_copy_handoffs,
                       "push_shm_in": self.num_push_shm_in,
                       "push_stream_in": self.num_push_stream_in,
                       "chunks_in": self.num_chunks_in,
                       "chunks_forwarded": self.num_chunks_forwarded,
                       "push_teardowns": self.num_push_teardowns,
                       "tree_failovers": self.num_tree_failovers,
                       "cut_through_overlap_pct": (
                           100.0 * self.ct_overlap_sum
                           / self.ct_overlap_n
                           if self.ct_overlap_n else None)}
        with self._actor_lock:
            num_actors = len(self._actors)
        with self._drain_lock:
            draining = self._draining
        with self._queue_cv:
            queued = len(self._task_queue)
            running = len(self._running)
            # per-demand queue introspection for the autoscaler
            # (reference: raylets report resource_load_by_shape in
            # their resource reports; gcs_resource_report_poller.cc
            # relays it into LoadMetrics) — capped so a deep queue
            # doesn't bloat the stats RPC
            queued_demands = [dict(t.spec.get("resources") or {})
                              for t in list(self._task_queue)[:256]]
        return {
            "node_id": self.node_id,
            "resources": totals,
            "available": avail,
            "queued": queued,
            "queued_demands": queued_demands,
            "running": running,
            "dispatch": dispatch,
            "store": self.store.stats(),
            "fetches": fetches,
            "push": self.push_manager.stats(),
            "pool": self.pool.stats(),
            "actors": num_actors,
            "agent": _process_stats(),
            "overload": self._overload_stats(),
            "integrity": self._integrity_stats(),
            "serve": self._serve_stats(),
            # live background threads by root-function label (the
            # naming raycheck RC16/RC17 reports share) for cli status
            "threads": self._threads.roots(),
            # drain plane: GCS-confirmed draining state + seconds left
            # on a pending preemption notice (None if none)
            "draining": draining,
            "preempt_notice_s": self._preempt_remaining(),
        }

    def perf_dump(self) -> dict:
        """Observability plane: this node's flight-recorder snapshot —
        recent spans/events from the bounded ring, the drop count, and
        the heartbeat-measured clock offset — for the GCS's
        collect_timeline fan-out (`cli.py timeline`)."""
        from ray_tpu.observability import flight_recorder

        snap = flight_recorder.global_recorder.snapshot()
        snap["node_id"] = self.node_id
        # live background threads by root-function label — the same
        # naming raycheck RC16/RC17 reports use (threads.root_label),
        # so a timeline lane and a data-race report line up by name
        snap["thread_roots"] = self._threads.roots()
        return snap

    def _integrity_stats(self) -> dict:
        """This node's integrity-plane counters: detected corruptions,
        discarded replicas, verified bytes (process-wide metric sums)
        plus the store's own drop/adopt counts. Rides heartbeats so
        `cli.py status` shows them cluster-wide."""
        out = integrity.snapshot()
        out["corrupt_dropped"] = self.store.num_corrupt_dropped
        out["orphans_adopted"] = self.store.num_orphans_adopted
        return out

    def _serve_stats(self) -> dict:
        """This process's serve-resilience counters (unhealthy
        replicas, completed drains, router exclusions, backpressured
        requests) — process-wide metric sums, riding heartbeats so
        `cli.py status` shows the serving layer's health cluster-wide
        next to the overload/integrity planes."""
        from ray_tpu.observability.metrics import get_metric

        out = {}
        for short, name in (
                ("replicas_unhealthy", "ray_tpu_serve_replicas_unhealthy"),
                ("drains_completed", "ray_tpu_serve_drains_completed"),
                ("router_excluded", "ray_tpu_serve_router_excluded"),
                ("requests_backpressured",
                 "ray_tpu_serve_requests_backpressured")):
            m = get_metric(name)
            out[short] = sum(m.series().values()) if m is not None else 0
        return out

    def _worker_pool_stats(self) -> dict:
        """This node's warm-pool counters (hits/misses/returns/reaps,
        idle depth) plus the local actor-create latency p50. Rides the
        heartbeat so `cli.py status` shows the actor fast path
        cluster-wide next to the overload/integrity/serve planes."""
        from ray_tpu.observability.metrics import actor_create_latency_ms

        out = {k: v for k, v in self.pool.stats().items()
               if k.startswith("warm_")}
        p50 = actor_create_latency_ms.percentile(50)
        if p50 is not None:
            out["create_ms_p50"] = p50
        return out

    def _overload_stats(self) -> dict:
        """This node's overload-plane counters: RPC admission sheds,
        task-queue backpressure, outbound-push sheds, and the states of
        the process's per-destination retry budgets / breakers (the
        raylet's own clients, e.g. its GCS channel). Rides the
        heartbeat so `cli.py status` can show it cluster-wide."""
        from ray_tpu.cluster import overload

        with self._stats_lock:
            shed = self.num_tasks_shed
        out = {"tasks_shed": shed,
               "push_shed": self.push_manager.stats().get("num_shed", 0)}
        if self.server is not None:
            out["rpc"] = self.server.overload_stats()
        out.update(overload.snapshot())
        return out


def _process_stats() -> dict:
    """Per-node agent stats (reference: dashboard/agent.py's reporter
    module) from stdlib sources — rss from /proc, 1-min load, uptime."""
    import os
    import resource

    stats = {
        "pid": os.getpid(),
        "rss_kb": resource.getrusage(resource.RUSAGE_SELF).ru_maxrss,
        "uptime_s": round(time.monotonic() - _PROC_START, 1),
    }
    try:
        stats["load_1m"] = os.getloadavg()[0]
    except OSError:  # pragma: no cover
        stats["load_1m"] = None
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        stats["rss_kb"] = pages * (os.sysconf("SC_PAGE_SIZE") // 1024)
    except (OSError, ValueError, IndexError) as e:
        # non-Linux: keep getrusage peak rss
        logger.debug("/proc/self/statm unavailable: %r", e)
    return stats


_PROC_START = time.monotonic()


def main(argv: Optional[List[str]] = None) -> None:
    import argparse
    import json

    parser = argparse.ArgumentParser()
    parser.add_argument("--gcs", required=True)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--resources", default='{"CPU": 2}')
    parser.add_argument("--num-workers", type=int, default=2)
    parser.add_argument("--node-id", default=None)
    parser.add_argument("--object-store-memory", type=int, default=None)
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    # arm the crash-dump hooks (SIGUSR2 / uncaught exception → JSONL)
    from ray_tpu.observability import flight_recorder
    flight_recorder.install()
    server = RayletServer(
        args.gcs, resources=json.loads(args.resources),
        num_workers=args.num_workers, node_id=args.node_id,
        object_store_memory=args.object_store_memory)
    srv = server.serve(args.host, args.port)
    print(f"RAYLET_ADDRESS {srv.address} NODE_ID {server.node_id}",
          flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        server.shutdown()


if __name__ == "__main__":
    main()
