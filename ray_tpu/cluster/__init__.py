"""Multiprocess execution tier: OS-process workers behind the same
interfaces as the in-process thread workers.

The reference runs every worker as a separate OS process forked by the
raylet's WorkerPool (src/ray/raylet/worker_pool.h:144) and moves objects
between them through the plasma shared-memory store
(src/ray/object_manager/plasma/). This package is the TPU build's
equivalent:

  - ``ProcessWorkerPool``   — a pool of leased worker processes that
    execute normal tasks (worker_pool.h PopWorker/PushWorker semantics:
    a raylet worker thread leases a process, pipelines the task onto it,
    returns it to the idle pool).
  - ``ActorProcess``        — one dedicated process per actor holding the
    live instance (the reference gives every actor its own worker
    process; direct_actor_transport pushes calls to it).
  - shm transport           — pickle protocol-5 out-of-band buffers are
    carried through the native C++ shared-memory store
    (ray_tpu/_native/shm_store.cpp), not the control pipe, so large
    numpy/bytes payloads move zero-copy through shm exactly like plasma.

Process death is detected on the pipe (EOF/EPIPE) and surfaces as
``WorkerCrashedError`` — the same signal the reference's owner gets when
a leased worker dies — which drives task retries
(TaskManager::RetryTaskIfPossible) and actor restarts
(GcsActorManager::ReconstructActor).

Enable with ``ray_tpu.init(worker_mode="process")``.

Known v1 limitation (documented, reference-parity gap): worker processes
do not embed a full peer runtime, so user code running inside a process
worker cannot itself call ``ray_tpu.remote`` (nested task submission
requires ``worker_mode="thread"`` or routing through the client server in
ray_tpu/util/client).
"""

from ray_tpu.cluster.process_pool import (  # noqa: F401
    ActorProcess,
    ProcessActorProxy,
    ProcessWorkerPool,
    WorkerProcess,
)
