"""Job submission over the process cluster.

Reference: dashboard/modules/job/ (JobSubmissionClient, job_manager.py)
— submit a shell entrypoint to the cluster, track PENDING/RUNNING/
SUCCEEDED/FAILED/STOPPED status, fetch logs, stop it. Here the job runs
inside a worker process on some node; status and logs live in the GCS
KV (namespace `_job`), so any client connected to the GCS can observe
them; stop routes a signal task to the job's node.
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional

JOB_NS = "_job"


def _run_job_entrypoint(job_id: str, entrypoint: str, gcs_address: str,
                        env_vars: Optional[Dict[str, str]] = None) -> int:
    """Executes ON A WORKER PROCESS: runs the entrypoint as a shell
    subprocess in its own process group, streaming status+logs to the
    GCS KV."""
    import os
    import subprocess

    from ray_tpu.cluster.rpc import ReconnectingRpcClient

    gcs = ReconnectingRpcClient(gcs_address)

    def put(key: str, value: bytes) -> None:
        gcs.call("kv_put", ns=JOB_NS, key=key.encode(), value=value,
                 timeout=10.0)

    def set_status(status: str, **extra) -> None:
        # job rows carry user-facing wall-clock timestamps (listed and
        # sorted across processes; monotonic values from different
        # hosts are not comparable)
        row = {"job_id": job_id, "status": status,
               "entrypoint": entrypoint,
               "timestamp": time.time(),  # raycheck: disable=RC02
               **extra}
        put(f"status/{job_id}", json.dumps(row).encode())

    env = dict(os.environ)
    env.update(env_vars or {})
    env["RAY_TPU_JOB_ID"] = job_id
    try:
        proc = subprocess.Popen(
            entrypoint, shell=True, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            start_new_session=True)  # its own pgid: stop kills the tree
        set_status("RUNNING", pid=proc.pid, pgid=proc.pid,
                   node_id=os.environ.get("RAY_TPU_NODE_ID", ""),
                   hostpid=os.getpid())
        lines: List[str] = []
        for raw in iter(proc.stdout.readline, b""):
            lines.append(raw.decode("utf-8", "replace"))
            if len(lines) % 20 == 0:  # stream logs incrementally
                put(f"logs/{job_id}", "".join(lines).encode())
        rc = proc.wait()
        put(f"logs/{job_id}", "".join(lines).encode())
        if rc == 0:
            set_status("SUCCEEDED", returncode=0)
        elif rc < 0:
            set_status("STOPPED", returncode=rc)
        else:
            set_status("FAILED", returncode=rc)
        return rc
    except Exception as e:  # noqa: BLE001 — the job row must say why
        set_status("FAILED", error=repr(e))
        raise
    finally:
        gcs.close()


def _signal_job(pgid: int, sig: int) -> bool:
    """Executes on the job's node: signal the entrypoint's process
    group."""
    import os
    import signal as _signal

    try:
        os.killpg(pgid, sig or _signal.SIGTERM)
        return True
    except ProcessLookupError:
        return False


def list_job_rows(kv_keys_fn, kv_get_fn) -> List[dict]:
    """Shared job-table listing over any KV transport — the client SDK
    and the dashboard head must not drift on key layout/row schema."""
    out = []
    for key in kv_keys_fn(b"status/"):
        raw = kv_get_fn(key)
        if raw is not None:
            out.append(json.loads(raw))
    return sorted(out, key=lambda r: r.get("timestamp", 0))


class JobSubmissionClient:
    """reference: dashboard/modules/job/sdk.py JobSubmissionClient —
    the same verbs over the process cluster's GCS."""

    def __init__(self, gcs_address: str):
        from ray_tpu.cluster.process_cluster import ClusterClient

        self.gcs_address = gcs_address
        self._client = ClusterClient(gcs_address)
        self._refs: Dict[str, Any] = {}  # job_id -> driver-side ref

    # ----------------------------------------------------------- submit
    def submit_job(self, *, entrypoint: str,
                   job_id: Optional[str] = None,
                   runtime_env: Optional[dict] = None) -> str:
        import os

        job_id = job_id or f"raysubmit_{os.urandom(6).hex()}"
        if self.get_job_status(job_id) is not None:
            raise ValueError(f"job {job_id!r} already exists")
        env_vars = (runtime_env or {}).get("env_vars")
        # user-facing wall-clock row timestamp (see set_status above)
        row = {"job_id": job_id, "status": "PENDING",
               "entrypoint": entrypoint,
               "timestamp": time.time()}  # raycheck: disable=RC02
        self._client.kv_put(f"status/{job_id}".encode(),
                            json.dumps(row).encode(), ns=JOB_NS)
        ref = self._client.submit(
            _run_job_entrypoint,
            (job_id, entrypoint, self.gcs_address, env_vars))
        self._refs[job_id] = ref
        return job_id

    # ------------------------------------------------------------ status
    def get_job_status(self, job_id: str) -> Optional[str]:
        info = self.get_job_info(job_id)
        return None if info is None else info["status"]

    def get_job_info(self, job_id: str) -> Optional[dict]:
        raw = self._client.kv_get(f"status/{job_id}".encode(), ns=JOB_NS)
        return None if raw is None else json.loads(raw)

    def get_job_logs(self, job_id: str) -> str:
        raw = self._client.kv_get(f"logs/{job_id}".encode(), ns=JOB_NS)
        return "" if raw is None else raw.decode("utf-8", "replace")

    def list_jobs(self) -> List[dict]:
        return list_job_rows(
            lambda prefix: self._client.kv_keys(prefix, ns=JOB_NS),
            lambda key: self._client.kv_get(key, ns=JOB_NS))

    def wait_until_finish(self, job_id: str, timeout: float = 60.0
                          ) -> Optional[str]:
        deadline = time.monotonic() + timeout
        terminal = {"SUCCEEDED", "FAILED", "STOPPED"}
        while time.monotonic() < deadline:
            status = self.get_job_status(job_id)
            if status in terminal:
                return status
            time.sleep(0.1)
        return self.get_job_status(job_id)

    # -------------------------------------------------------------- stop
    def stop_job(self, job_id: str, sig: int = 0) -> bool:
        """SIGTERM the entrypoint's process group on its node
        (reference: job_manager stop_job)."""
        info = self.get_job_info(job_id)
        if info is None or info["status"] not in ("RUNNING", "PENDING"):
            return False
        pgid = info.get("pgid")
        node_id = info.get("node_id") or None
        if pgid is None:
            return False
        ref = self._client.submit(_signal_job, (pgid, sig),
                                  node_id=node_id)
        return bool(self._client.get(ref, timeout=30.0))

    def close(self) -> None:
        self._client.close()
