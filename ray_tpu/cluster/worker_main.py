"""Worker-process entry point (``python -m ray_tpu.cluster.worker_main``).

The process equivalent of the reference's
python/ray/workers/default_worker.py: connect back to the parent, then
loop executing pushed work until told to shut down
(CoreWorker::RunTaskExecutionLoop, core_worker.cc:2018).

Protocol (see cluster/protocol.py) rides the original stdin/stdout pipe
pair; user ``print``s are re-routed to stderr so they cannot corrupt
frames. Messages:

  ("task",        {func, args, kwargs, runtime_env}) -> ("ok", result) | ("err", ...)
  ("task_batch",  {items: [task payloads]})          -> ("ok", [row, ...])
  ("actor_create",{cls, args, kwargs, runtime_env})  -> ("ok", None)   | ("err", ...)
  ("actor_call",  {method, args, kwargs})            -> ("ok", result) | ("err", ...)
  ("actor_reset", {})                                -> ("ok", {clean}) | ("err", ...)
  ("ping",        {})                                -> ("ok", pid)
  ("shutdown",    {})                                -> process exits 0

``actor_reset`` tears the live actor instance down so a WARM-POOL
worker can return to its pool after a kill (process_pool.py). The
reply's ``clean`` is False when the actor's life polluted process
state the reset cannot undo (a runtime_env held for the actor's whole
life) — the parent reaps such workers instead of reusing them.
"""

from __future__ import annotations

import argparse
import asyncio
import inspect
import logging
import os
import sys

from ray_tpu.cluster import protocol

logger = logging.getLogger(__name__)


def _resolve_stored_args(args, kwargs, shm, held_keys):
    """Swap StoredObjectArg markers for values deserialized IN PLACE
    from the node's shm segment: numpy buffers become read-only views of
    the mapped pages — zero copies, and only the pages the task actually
    touches ever fault in (the plasma worker-mmap read contract). Each
    resolved key is pinned (C-store refcount) and appended to
    ``held_keys``; the caller releases them after the reply is sent.
    The raylet additionally holds its own pin for the task's duration.
    Retaining a view beyond the task (e.g. stashing the array in a
    global) is undefined once both pins drop — the reference makes the
    same immutable/zero-copy trade for plasma-backed arrays."""
    def resolve(a):
        if not isinstance(a, protocol.StoredObjectArg):
            return a
        if a.path is not None:
            # same-host PEER segment (plasma one-store-per-host: the
            # neighbour raylet's object is readable in place). The
            # RAYLET holds the pin and shipped the block's
            # (offset, size): read the region directly — no state
            # lookup, so a concurrent spill/delete on the owner (which
            # defers while pinned) cannot fail this read.
            from ray_tpu.cluster.byte_store import attach_shm

            seg = attach_shm(a.path)
            if seg is None:
                raise RuntimeError(
                    f"peer shm segment {a.path} unreachable")
            return protocol.loads_flat(seg.region(a.offset, a.size))
        if shm is None:
            raise RuntimeError(
                "task argument lives in the shm store but this worker "
                "has no segment attached")
        # the pin is keyed into held_keys two lines down and released
        # by the task-end unwind in _serve_one; buf is a borrowed view
        # raycheck: disable=RC12 — pin recorded in held_keys, released at task end
        buf = shm.get_buffer(a.key)
        if buf is None:
            raise RuntimeError(
                "stored task argument missing from the shm segment")
        held_keys.append((shm, a.key))
        return protocol.loads_flat(buf)

    return ([resolve(a) for a in args],
            {k: resolve(v) for k, v in kwargs.items()})


def _store_result(result, result_key, shm):
    """Large results are serialized DIRECTLY into the node's shm segment
    under the return key (create -> write flat layout -> seal; no
    intermediate joined buffer) and only a size marker rides the pipe —
    the plasma write path, where workers create+seal in the store and
    the raylet merely pins. Falls back to the inline reply when the
    segment is full or the key already exists (e.g. a retry)."""
    if result_key is None:
        return ("ok", result)
    header, bufs = protocol.flat_parts(result)
    total = protocol.flat_size(header, bufs)
    if total < protocol.SHM_THRESHOLD or shm is None:
        # small result: ship the flat payload itself — the raylet
        # stores it verbatim, so the value is serialized exactly once
        out = bytearray(total)
        protocol.write_flat(out, header, bufs)
        return ("ok", protocol.FlatPayload(bytes(out)))
    from ray_tpu.cluster import integrity

    trailer_size = integrity.TRAILER_SIZE if integrity.enabled() else 0
    try:
        # integrity plane: the segment entry is created logical-size +
        # trailer; the digest of the flat payload rides after it, so
        # the raylet verifies the bytes at adopt_shm — a worker
        # SIGKILLed mid-write (or a scribbled page) can never become
        # the node's primary copy
        dest = shm.create(result_key, total + trailer_size)
        try:
            body = dest[:total] if trailer_size else dest
            try:
                protocol.write_flat(body, header, bufs)
                if trailer_size:
                    dest[total:] = integrity.pack_trailer(
                        integrity.checksum(body))
            finally:
                if body is not dest:
                    body.release()
        finally:
            dest.release()
        shm.seal(result_key)
    except Exception:
        out = bytearray(total)
        protocol.write_flat(out, header, bufs)
        return ("ok", protocol.FlatPayload(bytes(out)))
    return ("ok", protocol.StoredResult(total))


def _execute(func, args, kwargs, runtime_env):
    if runtime_env is not None:
        with runtime_env.applied():
            result = func(*args, **kwargs)
    else:
        result = func(*args, **kwargs)
    if inspect.isawaitable(result):
        # async actor methods are awaited worker-side; the parent's
        # ordering queue only sees the final value
        result = asyncio.run(_consume(result))
    return result


async def _consume(awaitable):
    return await awaitable


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--shm", default="", help="shm store path (optional)")
    parser.add_argument("--protocol-version", type=int, default=None,
                        help="parent's pipe-protocol version; refuse on "
                             "mismatch instead of mis-parsing frames")
    parser.add_argument("--preimport", default="",
                        help="comma-separated modules to import at boot "
                             "(warm-pool amortization: the import cost is "
                             "paid before the worker is ever leased)")
    ns = parser.parse_args()
    if (ns.protocol_version is not None
            and ns.protocol_version != protocol.PIPE_PROTOCOL_VERSION):
        print(f"worker: pipe protocol v{ns.protocol_version} != "
              f"v{protocol.PIPE_PROTOCOL_VERSION}; refusing to start",
              file=sys.stderr)
        return 2

    # Claim the protocol fds, then point fd1 (and Python's sys.stdout) at
    # stderr so user code can't write into the frame stream.
    # raycheck: disable=RC12 — process-lifetime protocol fd; exit reclaims
    proto_in = os.fdopen(os.dup(0), "rb", buffering=0)
    # raycheck: disable=RC12 — process-lifetime protocol fd; exit reclaims
    proto_out = os.fdopen(os.dup(1), "wb", buffering=0)
    os.dup2(2, 1)
    sys.stdout = sys.stderr
    sys.stdin = open(os.devnull, "r")

    shm = None
    if ns.shm:
        try:
            from ray_tpu._native.shm_store import ShmStore

            # raycheck: disable=RC12 — process-lifetime segment mapping; exit reclaims
            shm = ShmStore.open(ns.shm)
        except Exception as e:  # noqa: BLE001
            print(f"worker: shm store unavailable ({e}); inline transport",
                  file=sys.stderr)

    os.environ["RAY_TPU_WORKER_PROCESS"] = "1"
    if ns.preimport:
        import importlib

        for mod in ns.preimport.split(","):
            mod = mod.strip()
            if not mod:
                continue
            try:
                importlib.import_module(mod)
            except Exception as e:  # noqa: BLE001 — best-effort warmup
                print(f"worker: preimport of {mod} failed: {e!r}",
                      file=sys.stderr)
    actor_instance = None
    actor_env = None

    while True:
        held_keys: list = []  # segment pins released after the reply
        try:
            msg_type, payload = protocol.recv(proto_in, shm)
        except protocol.PipeClosedError:
            return 0
        if msg_type == "shutdown":
            return 0
        try:
            if msg_type == "ping":
                reply = ("ok", os.getpid())
            elif msg_type == "task":
                args, kwargs = _resolve_stored_args(
                    payload["args"], payload["kwargs"], shm, held_keys)
                result = _execute(payload["func"], args, kwargs,
                                  payload.get("runtime_env"))
                reply = _store_result(result, payload.get("result_key"),
                                      shm)
            elif msg_type == "task_batch":
                # dispatch fast lane: N task frames per pipe write —
                # one recv, N executions, one reply frame. Rows are
                # independent: a row's exception becomes that row's
                # ("err", ...) entry instead of failing the frame, so
                # siblings in the batch still return their results.
                rows = []
                for item in payload["items"]:
                    try:
                        args, kwargs = _resolve_stored_args(
                            item["args"], item["kwargs"], shm,
                            held_keys)
                        result = _execute(item["func"], args, kwargs,
                                          item.get("runtime_env"))
                        rows.append(_store_result(
                            result, item.get("result_key"), shm))
                    except BaseException as e:  # noqa: BLE001
                        if isinstance(e, SystemExit):
                            raise
                        rows.append(
                            ("err", protocol.format_exception(e)))
                reply = ("ok", rows)
            elif msg_type == "actor_create":
                actor_env = payload.get("runtime_env")
                if actor_env is not None:
                    # the env holds for the actor's whole life (reference:
                    # runtime envs are per worker process)
                    actor_env.__enter__ctx = actor_env.applied()
                    actor_env.__enter__ctx.__enter__()
                actor_instance = payload["cls"](*payload["args"],
                                                **payload["kwargs"])
                reply = ("ok", None)
            elif msg_type == "actor_call":
                if actor_instance is None:
                    raise RuntimeError("actor_call before actor_create")
                method = getattr(actor_instance, payload["method"])
                result = _execute(method, payload["args"], payload["kwargs"],
                                  None)
                reply = ("ok", result)
            elif msg_type == "actor_reset":
                # a runtime_env held for the actor's life may have
                # mutated process state (env vars, cwd) in ways user
                # code already observed; exiting the ctx restores the
                # env but the worker is conservatively unfit for reuse
                clean = actor_env is None
                if actor_env is not None:
                    try:
                        actor_env.__enter__ctx.__exit__(None, None, None)
                    except Exception as e:  # noqa: BLE001
                        print(f"worker: runtime_env teardown failed: "
                              f"{e!r}", file=sys.stderr)
                    actor_env = None
                actor_instance = None
                import gc

                gc.collect()  # run the instance's __del__ before reuse
                reply = ("ok", {"clean": clean})
            else:
                raise RuntimeError(f"unknown message type {msg_type!r}")
        except BaseException as e:  # noqa: BLE001
            if isinstance(e, SystemExit):
                raise
            reply = ("err", protocol.format_exception(e))
        try:
            protocol.send(proto_out, reply, shm)
        except protocol.PipeClosedError:
            return 0
        except Exception as e:  # noqa: BLE001 — unpicklable result
            protocol.send(
                proto_out,
                ("err", protocol.format_exception(
                    TypeError(f"task result is not serializable: {e}"))),
                shm)
        finally:
            # the reply (which may reference arg views) is fully
            # serialized and flushed: safe to drop the segment pins
            del reply
            for seg, key in held_keys:
                try:
                    seg.release(key)
                except Exception as e:
                    # the owning raylet may have torn the segment down
                    logger.debug("worker: releasing shm arg pin %s "
                                 "failed: %r", key.hex()[:8], e)


if __name__ == "__main__":
    sys.exit(main())
