"""Worker-process entry point (``python -m ray_tpu.cluster.worker_main``).

The process equivalent of the reference's
python/ray/workers/default_worker.py: connect back to the parent, then
loop executing pushed work until told to shut down
(CoreWorker::RunTaskExecutionLoop, core_worker.cc:2018).

Protocol (see cluster/protocol.py) rides the original stdin/stdout pipe
pair; user ``print``s are re-routed to stderr so they cannot corrupt
frames. Messages:

  ("task",        {func, args, kwargs, runtime_env}) -> ("ok", result) | ("err", ...)
  ("actor_create",{cls, args, kwargs, runtime_env})  -> ("ok", None)   | ("err", ...)
  ("actor_call",  {method, args, kwargs})            -> ("ok", result) | ("err", ...)
  ("ping",        {})                                -> ("ok", pid)
  ("shutdown",    {})                                -> process exits 0
"""

from __future__ import annotations

import argparse
import asyncio
import inspect
import os
import sys

from ray_tpu.cluster import protocol


def _execute(func, args, kwargs, runtime_env):
    if runtime_env is not None:
        with runtime_env.applied():
            result = func(*args, **kwargs)
    else:
        result = func(*args, **kwargs)
    if inspect.isawaitable(result):
        # async actor methods are awaited worker-side; the parent's
        # ordering queue only sees the final value
        result = asyncio.run(_consume(result))
    return result


async def _consume(awaitable):
    return await awaitable


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--shm", default="", help="shm store path (optional)")
    ns = parser.parse_args()

    # Claim the protocol fds, then point fd1 (and Python's sys.stdout) at
    # stderr so user code can't write into the frame stream.
    proto_in = os.fdopen(os.dup(0), "rb", buffering=0)
    proto_out = os.fdopen(os.dup(1), "wb", buffering=0)
    os.dup2(2, 1)
    sys.stdout = sys.stderr
    sys.stdin = open(os.devnull, "r")

    shm = None
    if ns.shm:
        try:
            from ray_tpu._native.shm_store import ShmStore

            shm = ShmStore.open(ns.shm)
        except Exception as e:  # noqa: BLE001
            print(f"worker: shm store unavailable ({e}); inline transport",
                  file=sys.stderr)

    os.environ["RAY_TPU_WORKER_PROCESS"] = "1"
    actor_instance = None
    actor_env = None

    while True:
        try:
            msg_type, payload = protocol.recv(proto_in, shm)
        except protocol.PipeClosedError:
            return 0
        if msg_type == "shutdown":
            return 0
        try:
            if msg_type == "ping":
                reply = ("ok", os.getpid())
            elif msg_type == "task":
                result = _execute(payload["func"], payload["args"],
                                  payload["kwargs"],
                                  payload.get("runtime_env"))
                reply = ("ok", result)
            elif msg_type == "actor_create":
                actor_env = payload.get("runtime_env")
                if actor_env is not None:
                    # the env holds for the actor's whole life (reference:
                    # runtime envs are per worker process)
                    actor_env.__enter__ctx = actor_env.applied()
                    actor_env.__enter__ctx.__enter__()
                actor_instance = payload["cls"](*payload["args"],
                                                **payload["kwargs"])
                reply = ("ok", None)
            elif msg_type == "actor_call":
                if actor_instance is None:
                    raise RuntimeError("actor_call before actor_create")
                method = getattr(actor_instance, payload["method"])
                result = _execute(method, payload["args"], payload["kwargs"],
                                  None)
                reply = ("ok", result)
            else:
                raise RuntimeError(f"unknown message type {msg_type!r}")
        except BaseException as e:  # noqa: BLE001
            if isinstance(e, SystemExit):
                raise
            reply = ("err", protocol.format_exception(e))
        try:
            protocol.send(proto_out, reply, shm)
        except protocol.PipeClosedError:
            return 0
        except Exception as e:  # noqa: BLE001 — unpicklable result
            protocol.send(
                proto_out,
                ("err", protocol.format_exception(
                    TypeError(f"task result is not serializable: {e}"))),
                shm)


if __name__ == "__main__":
    sys.exit(main())
