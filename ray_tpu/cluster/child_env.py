"""Child-process environment sanitizing — ONE implementation for every
spawner (worker pools, raylet/GCS process spawns, command providers).

The problem (observed live on tunneled-TPU hosts): site hooks on
PYTHONPATH (a ``sitecustomize.py``) can eagerly register a
remote-accelerator JAX plugin at interpreter start. In a child process
that is the worst of both worlds — the child must never own the
parent's accelerator, the plugin's native init can wedge the child
outright, and the hook may also export ``JAX_PLATFORMS=<plugin>`` into
the inherited environment, which dangles (unknown backend) once the
hook is stripped. So every spawner must do BOTH: drop the hook from
PYTHONPATH and force ``JAX_PLATFORMS`` to a resolvable backend.

Only hook directories that look accelerator-related are stripped (their
``sitecustomize.py`` mentions jax/xla/an accelerator plugin): a user's
PYTHONPATH dir that happens to carry a benign sitecustomize next to
their own modules keeps working in workers."""

from __future__ import annotations

import os
from typing import Dict, Optional

_HOOK_MARKERS = (b"jax", b"xla", b"tpu", b"accelerator")


def _is_accelerator_hook_dir(path: str) -> bool:
    hook = os.path.join(path, "sitecustomize.py")
    try:
        with open(hook, "rb") as f:
            content = f.read(65536).lower()
    except OSError:
        return False
    return any(m in content for m in _HOOK_MARKERS)


def _pkg_root() -> str:
    import ray_tpu

    return os.path.dirname(os.path.dirname(
        os.path.abspath(ray_tpu.__file__)))


def sanitized_env(pin_pythonpath: bool = False,
                  base: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """Environment for a spawned child.

    pin_pythonpath=True (control-plane processes: raylets, GCS,
    command-provider nodes) replaces PYTHONPATH with just the package
    root — these processes import only ray_tpu and must start fast and
    hook-free. pin_pythonpath=False (task/actor workers) keeps the
    user's PYTHONPATH entries (their code must import in workers) minus
    accelerator hook dirs, with the package root appended last so user
    entries keep their shadowing priority."""
    env = dict(base if base is not None else os.environ)
    # FORCE, not setdefault: the hook may have exported its own platform
    # name, which no longer resolves in a hook-free child
    env["JAX_PLATFORMS"] = env.get("RAY_TPU_WORKER_JAX_PLATFORMS", "cpu")
    # Belt-and-braces to the PYTHONPATH strip below: even if an
    # accelerator hook is reachable some other way, its trigger var is
    # gone, so it no-ops instead of dialing the parent's tunnel.
    env.pop("PALLAS_AXON_POOL_IPS", None)
    root = _pkg_root()
    if pin_pythonpath:
        env["PYTHONPATH"] = root
        return env
    entries = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
               if p and not _is_accelerator_hook_dir(p)]
    if root not in entries:
        entries.append(root)
    env["PYTHONPATH"] = os.pathsep.join(entries)
    return env
