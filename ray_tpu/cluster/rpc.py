"""Generic framed-TCP RPC substrate for the process-separated cluster.

The reference routes all control traffic through gRPC service stubs
(src/ray/rpc/grpc_server.h, grpc_client.h). This is the equivalent seam
for the process tier: a threaded ``RpcServer`` dispatching named methods,
and an ``RpcClient`` holding one persistent connection with pipelined
request ids, so many threads can issue calls over a single socket.

Wire format per message (both directions):
    8-byte big-endian length | cloudpickle body
    body = (seq: int, kind: str, payload)
      request : (seq, method_name, kwargs_dict)
      reply   : (seq, "ok", result) | (seq, "err", (pickled_exc, tb, repr))

The payloads use cluster/protocol.py's pickle-5 codec, so numpy arrays
travel zero-copy into the frame without an extra pickle copy.
"""

from __future__ import annotations

import contextlib
import logging
import socket
import socketserver
import struct
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Optional, Tuple  # noqa: F401

from ray_tpu.cluster import fault_plane as _fault
from ray_tpu.cluster import protocol
from ray_tpu.exceptions import RetryLaterError
from ray_tpu.util import tracing as _tracing

logger = logging.getLogger(__name__)

_LEN = struct.Struct(">Q")


class RpcConnectionError(ConnectionError):
    """The peer is gone (process died or socket closed)."""


class RpcVersionError(RpcConnectionError):
    """The peer speaks a different wire-protocol version."""


# --------------------------------------------------------------------------
# Wire versioning (reference: src/ray/protobuf/ gives every message a
# schema; cross-version processes refuse to talk rather than mis-parse).
# Every connection opens with a 5-byte hello — 4 magic bytes + 1 version
# byte — in BOTH directions; a mismatch raises RpcVersionError instead
# of feeding unversioned pickles to the wrong parser. Schema rules for
# the frames themselves live in cluster/schema.py.
#
# Version history (bump on any incompatible frame-layout change):
#   1: initial versioned protocol — pickled (seq, method, kwargs)
#      request frames, (seq, kind, payload) reply frames, raw "R"
#      chunk frames.
#   2: requests may carry the reserved ``_deadline_s`` kwarg — the
#      caller's remaining timeout budget, stripped before dispatch and
#      re-established as the handler thread's deadline so nested RPCs
#      inherit the budget instead of re-minting their own. A v1
#      receiver would hand the unknown kwarg to unschema'd handlers.
#   3: requests may carry the reserved ``_trace`` kwarg — the caller's
#      sampled trace context (trace_id, span_id, sampled), stripped
#      before dispatch and recorded as a server-side handler span
#      parented to the caller's span (util/tracing.record_remote_span).
#      A v2 receiver would hand the unknown kwarg to unschema'd
#      handlers.
#   4: REQUESTS may be raw data frames (the b"R" marker, previously
#      reply-direction only): b"R" + seq + header-length + pickled
#      (method, kwargs) header + unpickled payload bytes, received via
#      recv_into straight into their final destination (the data
#      plane's single-copy chunk path). A v3 receiver would feed the
#      raw body to the pickle parser.
# --------------------------------------------------------------------------
PROTOCOL_MAGIC = b"RTPU"
PROTOCOL_VERSION = 4

# reserved request kwarg carrying the caller's remaining budget (v2)
_DEADLINE_KW = "_deadline_s"
# reserved request kwarg carrying the caller's trace context (v3)
_TRACE_KW = "_trace"


def _plane_enabled() -> bool:
    from ray_tpu._private.config import Config
    return Config.instance().observability_plane_enabled


class Deadline:
    """Thread-local RPC deadline budget (reference: gRPC deadline
    propagation — a caller's deadline rides the wire and bounds every
    nested call, so one slow hop cannot spend a budget the caller no
    longer has).

    ``Deadline.budget(seconds)`` establishes (or tightens — budgets only
    ever shrink) the current thread's absolute deadline; every
    ``RpcClient.call`` clamps its timeout to the remaining budget and
    forwards the remainder in the request frame, where the server
    re-establishes it around the handler."""

    _local = threading.local()

    @classmethod
    def current(cls) -> Optional[float]:
        """Absolute monotonic deadline, or None when unbounded."""
        return getattr(cls._local, "value", None)

    @classmethod
    def remaining(cls) -> Optional[float]:
        v = cls.current()
        return None if v is None else max(0.0, v - time.monotonic())

    @classmethod
    def clamp(cls, timeout: Optional[float]) -> Optional[float]:
        """min(timeout, remaining budget), None-aware."""
        rem = cls.remaining()
        if rem is None:
            return timeout
        return rem if timeout is None else min(timeout, rem)

    @classmethod
    @contextlib.contextmanager
    def budget(cls, seconds: Optional[float]):
        if seconds is None:
            yield
            return
        prev = cls.current()
        new = time.monotonic() + seconds
        if prev is not None:
            new = min(prev, new)  # budgets only shrink
        cls._local.value = new
        try:
            yield
        finally:
            cls._local.value = prev


def _send_hello(sock: socket.socket) -> None:
    sock.sendall(PROTOCOL_MAGIC + bytes([PROTOCOL_VERSION]))


def _check_hello(sock: socket.socket, who: str,
                 timeout: Optional[float] = 10.0) -> None:
    """Read and validate the peer's hello. Runs before any framed
    traffic, under a bounded timeout so a silent peer cannot park the
    reader forever."""
    old = sock.gettimeout()
    try:
        sock.settimeout(timeout)
        hello = bytes(_recv_exact(sock, len(PROTOCOL_MAGIC) + 1))
    except socket.timeout:
        raise RpcVersionError(
            f"{who} sent no protocol hello within {timeout}s") from None
    finally:
        try:
            sock.settimeout(old)
        except OSError as e:
            # socket died during the hello; the next recv/send raises
            logger.debug("restoring socket timeout after hello "
                         "failed: %r", e)
    if hello[:len(PROTOCOL_MAGIC)] != PROTOCOL_MAGIC:
        raise RpcVersionError(
            f"{who} is not a ray_tpu rpc peer (bad magic {hello[:4]!r})")
    if hello[-1] != PROTOCOL_VERSION:
        raise RpcVersionError(
            f"{who} speaks wire protocol v{hello[-1]}, this process "
            f"speaks v{PROTOCOL_VERSION}; refusing to exchange frames")


# --------------------------------------------------------------------------
# framing over sockets
# --------------------------------------------------------------------------


def _send_msg(sock: socket.socket, body: bytes) -> None:
    sock.sendall(_LEN.pack(len(body)) + body)


# Raw stream frames: payload bytes travel unpickled. Body layout is
# b"R" + 8-byte seq + raw payload; pickled bodies always start with
# 0x80 (the pickle PROTO opcode), so the marker cannot collide.
_RAW_MARKER = 0x52  # ord("R")
_U32 = struct.Struct(">I")


def _send_raw_chunk(sock: socket.socket, seq: int, payload) -> None:
    sock.sendall(_LEN.pack(9 + len(payload)) + b"R" + _LEN.pack(seq))
    sock.sendall(payload)


# Raw REQUEST data frames (wire v4): the client→server mirror of the
# raw stream reply, for payloads that must not round-trip through
# pickle. Body layout is b"R" + 8-byte seq + 4-byte header length +
# pickled (method, kwargs) header + raw payload — the header is tiny
# (ids + offsets), the payload is never copied into a pickle, and the
# receiving handler reads it with recv_into straight into its final
# destination (a preallocated shm offset on the push path). The same
# 0x52-vs-0x80 discrimination applies on the server's reader.


def _send_data_frame(sock: socket.socket, seq: int, header: bytes,
                     payload) -> None:
    sock.sendall(_LEN.pack(9 + 4 + len(header) + len(payload))
                 + b"R" + _LEN.pack(seq) + _U32.pack(len(header))
                 + header)
    sock.sendall(payload)


def _recv_exact(sock: socket.socket, n: int) -> bytearray:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], min(n - got, 4 * 1024 * 1024))
        if not r:
            raise RpcConnectionError(
                f"socket closed with {n - got}/{n} bytes outstanding")
        got += r
    return buf


def _recv_msg(sock: socket.socket) -> bytearray:
    (length,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    return _recv_exact(sock, length)


# --------------------------------------------------------------------------
# server
# --------------------------------------------------------------------------


class _DispatchPool:
    """Bounded dispatch pool — the server side of the overload plane
    (reference: gRPC server thread caps; Ray's num_server_call_thread).

    Threaded (non-inline) requests queue here instead of each spawning
    an unbounded thread. Admission is a hard bound: when every worker
    is busy, no new worker may spawn, and the queue is at depth, the
    request is SHED — the caller gets a typed :class:`RetryLaterError`
    with a backoff hint instead of a silently growing queue. Workers
    spawn on demand up to ``max_threads`` and exit when the pool stops.
    """

    def __init__(self, run: Callable, max_threads: int,
                 queue_depth: int, name: str):
        self._run = run
        self._max = max(1, int(max_threads))
        self._depth = max(1, int(queue_depth))
        self._name = name
        self._cv = threading.Condition()
        # raycheck: disable=RC10 — bounded by submit()'s admission check (queue_depth): over-bound requests return False and are shed with RetryLaterError by the caller
        self._queue: deque = deque()
        self._idle = 0
        self._num_threads = 0
        self._spawned = 0
        self._stopped = False

    def submit(self, item) -> bool:
        """True = admitted (a worker will run it); False = shed."""
        with self._cv:
            if self._stopped:
                return False
            if (len(self._queue) >= self._depth
                    and self._num_threads >= self._max
                    and self._idle == 0):
                return False
            self._queue.append(item)
            if self._idle == 0 and self._num_threads < self._max:
                self._num_threads += 1
                self._spawned += 1
                # raycheck: disable=RC09 — pool workers are daemon threads whose lifetime is bounded by the pool: stop() drains idle workers via the condition, busy ones exit after their current handler; joining them would block teardown on long-poll handlers
                threading.Thread(
                    target=self._worker, daemon=True,
                    name=f"{self._name}-{self._spawned}").start()
            else:
                self._cv.notify()
            depth = len(self._queue)
        from ray_tpu.observability.metrics import rpc_dispatch_queue_depth

        rpc_dispatch_queue_depth.set(depth)
        return True

    def _worker(self) -> None:
        while True:
            with self._cv:
                self._idle += 1
                while not self._queue and not self._stopped:
                    self._cv.wait(1.0)
                self._idle -= 1
                if not self._queue:
                    self._num_threads -= 1
                    return  # stopped and drained
                item = self._queue.popleft()
            try:
                self._run(item)
            except Exception:
                logger.exception("rpc dispatch worker failed")

    def depth(self) -> int:
        with self._cv:
            return len(self._queue)

    def stats(self) -> dict:
        with self._cv:
            return {"queued": len(self._queue),
                    "threads": self._num_threads,
                    "idle": self._idle,
                    "max_threads": self._max,
                    "queue_depth": self._depth}

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            self._cv.notify_all()


class RpcServer:
    """Threaded TCP server dispatching named methods.

    Handlers are ``fn(**kwargs) -> result``; raising propagates the
    exception to the caller (restored via protocol.restore_exception).
    A handler may also be registered as a *stream* producer returning an
    iterator of chunks; each chunk is sent as its own reply frame with
    kind "chunk", terminated by an "ok" frame (used by object transfer).
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 max_dispatch_threads: Optional[int] = None,
                 queue_depth: Optional[int] = None):
        self._handlers: Dict[str, Callable] = {}
        self._stream_handlers: Dict[str, Callable] = {}
        self._data_handlers: Dict[str, Callable] = {}
        self._inline: set = set()  # known-fast methods: no thread
        # overload counters (admission control + reply path); the lock
        # also guards the per-method shed map
        self._overload_lock = threading.Lock()
        self._shed_counts: Dict[str, int] = {}  # method -> sheds
        self.num_shed_queue_full = 0
        self.num_shed_deadline = 0
        self.num_dispatched = 0
        self.num_replies_dropped = 0
        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self):  # one reader thread per connection
                sock = self.request
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                # versioned hello both ways before any framed traffic
                try:
                    _send_hello(sock)
                    _check_hello(sock, "client")
                except RpcVersionError:
                    try:
                        sock.close()
                    except OSError as e:
                        logger.debug("closing version-mismatched "
                                     "client socket failed: %r", e)
                    return
                except (ConnectionError, OSError):
                    return
                # Clients pipeline requests over one connection, so a
                # blocking handler (object_wait_location, wait_task,
                # actor_call) must not head-of-line-block the rest: those
                # run on their own thread, with a shared lock serializing
                # reply frames. Methods registered inline=True (pure
                # bookkeeping) skip the thread spawn — they are the hot
                # control path (heartbeats, submits, directory updates).
                send_lock = threading.Lock()
                try:
                    peer = "%s:%s" % self.client_address[:2]
                except Exception:
                    peer = ""
                try:
                    while True:
                        (length,) = _LEN.unpack(
                            bytes(_recv_exact(sock, _LEN.size)))
                        first = _recv_exact(sock, 1)[0]
                        if first == _RAW_MARKER:
                            # v4 raw data frame: the payload stays on
                            # the socket for the handler's recv_into —
                            # single copy into its final destination.
                            # Runs inline on this reader thread, so
                            # data frames keep their send order (the
                            # chunk stream's begin/chunk/end contract).
                            outer._dispatch_data(sock, send_lock,
                                                 length, peer)
                            continue
                        body = bytearray(length)
                        body[0] = first
                        if length > 1:
                            view = memoryview(body)
                            got = 1
                            while got < length:
                                r = sock.recv_into(
                                    view[got:],
                                    min(length - got, 4 * 1024 * 1024))
                                if not r:
                                    raise RpcConnectionError(
                                        f"socket closed with "
                                        f"{length - got}/{length} "
                                        f"bytes outstanding")
                                got += r
                        nbytes = len(body)
                        seq, method, kwargs = protocol.loads(body)
                        if method in outer._inline:
                            outer._dispatch(sock, send_lock, seq, method,
                                            kwargs, peer, nbytes=nbytes)
                        elif outer._pool is not None:
                            # admission control: a full pool + full
                            # queue sheds the request here, on the
                            # reader thread, with a typed retry-later
                            # reply — never an unbounded thread spawn
                            item = (sock, send_lock, seq, method,
                                    kwargs, peer, time.monotonic(),
                                    nbytes)
                            if not outer._pool.submit(item):
                                outer._shed(sock, send_lock, seq,
                                            method, peer, "queue_full")
                        else:
                            # overload plane disabled: legacy unbounded
                            # thread-per-request dispatch
                            # raycheck: disable=RC09 — per-request dispatch thread; its lifetime is the handler call itself and the reply path tolerates a closed socket, so there is no teardown to coordinate
                            threading.Thread(
                                target=outer._dispatch,
                                args=(sock, send_lock, seq, method,
                                      kwargs, peer),
                                kwargs={"nbytes": nbytes},
                                daemon=True).start()
                except (RpcConnectionError, ConnectionError, OSError) as e:
                    # client went away: normal connection teardown
                    logger.debug("connection reader for %s exiting: %r",
                                 peer, e)

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _Server((host, port), _Handler)
        self.host, self.port = self._server.server_address
        # Bounded dispatch pool (admission control). Explicit ctor args
        # force admission on; with neither given, the Config master
        # switch decides — off restores thread-per-request dispatch.
        from ray_tpu._private.config import Config

        cfg = Config.instance()
        if (max_dispatch_threads is None and queue_depth is None
                and not cfg.overload_enabled):
            self._pool: Optional[_DispatchPool] = None
        else:
            self._pool = _DispatchPool(
                self._run_queued,
                max_dispatch_threads
                or cfg.rpc_server_max_dispatch_threads,
                queue_depth or cfg.rpc_server_queue_depth,
                f"rpc-dispatch-{self.port}")
        # raycheck: disable=RC09 — the accept-loop thread is torn down by stop() via ThreadingTCPServer.shutdown(), which joins the serve_forever loop; a registry join on top would be redundant
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name=f"rpc-server-{self.port}")

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def register(self, name: str, fn: Callable,
                 inline: bool = False) -> None:
        self._handlers[name] = fn
        if inline:
            self._inline.add(name)

    def register_stream(self, name: str, fn: Callable) -> None:
        self._stream_handlers[name] = fn

    def register_data(self, name: str, fn: Callable) -> None:
        """Register a raw-data-frame handler (wire v4): ``fn(payload_len,
        recv_payload, **kwargs) -> result``. The handler calls
        ``recv_payload(writable_view)`` to land the frame's payload via
        ``recv_into`` — directly into a preallocated shm offset on the
        push path, the one copy the payload makes. Always dispatched
        inline on the connection's reader thread, so a client's data
        frames are processed in send order."""
        self._data_handlers[name] = fn

    def _dispatch_data(self, sock, send_lock, length: int,
                       peer: str) -> None:
        """Parse and dispatch one raw data frame whose b"R" marker has
        been consumed; LENGTH is the full body length (incl. marker).
        The payload is still on the socket — the handler pulls it with
        the recv_payload callback; whatever it leaves is drained so a
        failing handler cannot desync the frame stream."""
        prefix = bytes(_recv_exact(sock, 12))  # 8B seq + 4B header len
        (seq,) = _LEN.unpack(prefix[:8])
        (hlen,) = _U32.unpack(prefix[8:12])
        method, kwargs = protocol.loads(_recv_exact(sock, hlen))
        payload_len = length - 1 - 12 - hlen
        consumed = [0]

        def recv_payload(dst) -> int:
            view = memoryview(dst)
            if not view.contiguous or view.readonly:
                view.release()
                raise TypeError("recv_payload needs a writable "
                                "contiguous buffer")
            view = view.cast("B")
            need = len(view)
            if consumed[0] + need > payload_len:
                raise ValueError(
                    f"recv_payload over-read: {consumed[0]}+{need} "
                    f"> {payload_len}")
            got = 0
            while got < need:
                r = sock.recv_into(view[got:],
                                   min(need - got, 4 * 1024 * 1024))
                if not r:
                    raise RpcConnectionError(
                        f"socket closed with {need - got} payload "
                        f"bytes outstanding")
                got += r
            consumed[0] += need
            return need

        with self._overload_lock:
            self.num_dispatched += 1
        budget = kwargs.pop(_DEADLINE_KW, None) if kwargs else None
        if kwargs:
            kwargs.pop(_TRACE_KW, None)
        fn = self._data_handlers.get(method)
        try:
            if fn is None:
                raise AttributeError(f"no rpc data method {method!r}")
            from ray_tpu.cluster import schema

            kwargs = schema.validate(method, kwargs)
            with Deadline.budget(budget):
                frame = (seq, "ok", fn(payload_len, recv_payload,
                                       **kwargs))
        except BaseException as e:  # noqa: BLE001 — ship to caller
            frame = (seq, "err", protocol.format_exception(e))
        finally:
            # drain whatever the handler did not consume: the next
            # frame must start exactly at this frame's end
            left = payload_len - consumed[0]
            while left > 0:
                left -= len(_recv_exact(sock,
                                        min(left, 4 * 1024 * 1024)))
        try:
            body = protocol.dumps(frame)
            with send_lock:
                _send_msg(sock, body)
        except (ConnectionError, OSError) as e:
            with self._overload_lock:
                self.num_replies_dropped += 1
            logger.debug("data-frame reply to %s for %s (seq %d) "
                         "undeliverable: %r", peer, method, seq, e)

    # ------------------------------------------------- admission control
    def _run_queued(self, item) -> None:
        """Pool worker entry: queue-deadline shed, then dispatch. A
        request whose propagated ``_deadline_s`` budget expired while it
        sat in the queue is rejected BEFORE the handler runs — working
        on it would burn a pool slot producing an answer the caller has
        already abandoned (Dean & Barroso's tail amplification)."""
        sock, send_lock, seq, method, kwargs, peer, t_enq, nbytes = item
        budget = kwargs.get(_DEADLINE_KW) if kwargs else None
        if budget is not None and time.monotonic() - t_enq >= budget:
            self._shed(sock, send_lock, seq, method, peer,
                       "queue_deadline")
            return
        self._dispatch(sock, send_lock, seq, method, kwargs, peer,
                       t_enq=t_enq, nbytes=nbytes)

    def _shed(self, sock, send_lock, seq, method, peer: str,
              reason: str) -> None:
        """Reject a request with a typed RetryLaterError reply carrying
        a server-suggested backoff hint scaled by queue pressure."""
        from ray_tpu.observability.metrics import rpc_requests_shed

        qlen = self._pool.depth() if self._pool is not None else 0
        with self._overload_lock:
            if reason == "queue_full":
                self.num_shed_queue_full += 1
            else:
                self.num_shed_deadline += 1
            self._shed_counts[method] = \
                self._shed_counts.get(method, 0) + 1
        rpc_requests_shed.inc(tags={"reason": reason})
        hint = min(2.0, 0.05 + 0.01 * qlen)
        exc = RetryLaterError(
            f"rpc server {self.host}:{self.port} shed {method!r} "
            f"({reason}, {qlen} queued); retry in ~{hint:.2f}s",
            retry_after_s=hint)
        try:
            body = protocol.dumps(
                (seq, "err", protocol.format_exception(exc)))
            with send_lock:
                _send_msg(sock, body)
        except (ConnectionError, OSError) as e:
            with self._overload_lock:
                self.num_replies_dropped += 1
            logger.debug("shed reply to %s for %s undeliverable: %r",
                         peer, method, e)

    def overload_stats(self) -> dict:
        """Admission/shed counters for node_stats, cluster_view, and
        `cli.py status` (plus the Prometheus series)."""
        with self._overload_lock:
            out = {
                "shed_queue_full": self.num_shed_queue_full,
                "shed_deadline": self.num_shed_deadline,
                "dispatched": self.num_dispatched,
                "replies_dropped": self.num_replies_dropped,
                "shed_by_method": dict(self._shed_counts),
            }
        out["pool"] = (self._pool.stats() if self._pool is not None
                       else None)
        return out

    def _dispatch(self, sock, send_lock, seq, method, kwargs,
                  peer: str = "", t_enq: Optional[float] = None,
                  nbytes: Optional[int] = None) -> None:
        t_run = time.monotonic()
        plane = _fault.get_plane()
        if plane is not None:
            # Seeded server-side slowdown (the "stall" rule kind): the
            # sleep happens INSIDE the dispatch slot, after admission —
            # a stalled method builds a real queue. The decision stream
            # keys on the SERVER address (not the requesting peer): a
            # wedged server is slow for everyone, and a single stream
            # makes `count`-windowed storms deterministic in event
            # space regardless of how many clients are hammering it.
            stall = plane.decide("handler",
                                 f"{self.host}:{self.port}", method)
            if stall is not None and stall["action"] == "stall":
                time.sleep(stall["seconds"])
        with self._overload_lock:
            self.num_dispatched += 1

        def reply(frame) -> None:
            if plane is not None:
                fault = plane.decide("reply", peer, method)
                if fault is not None:
                    action = fault["action"]
                    if action in ("drop", "partition"):
                        return  # the ack vanishes: one-way partition
                    if action == "delay":
                        time.sleep(fault["seconds"])
                    elif action == "truncate":
                        body = protocol.dumps(frame)
                        cut = fault.get("truncate_bytes")
                        if cut is None:
                            cut = max(1, len(body) // 2)
                        with send_lock:
                            sock.sendall(_LEN.pack(len(body))
                                         + bytes(body[:cut]))
                            sock.close()  # die mid-frame
                        return
                    elif action == "duplicate":
                        body = protocol.dumps(frame)
                        with send_lock:
                            _send_msg(sock, body)
                            _send_msg(sock, body)
                        return
                    elif action == "corrupt":
                        body = _fault.apply_corruption(
                            protocol.dumps(frame), fault,
                            tail_bias=True)
                        with send_lock:
                            _send_msg(sock, bytes(body))
                        return
            body = protocol.dumps(frame)
            with send_lock:  # frames from concurrent handlers must not
                _send_msg(sock, body)  # interleave mid-frame

        # v2: the caller's remaining budget rides the request; it bounds
        # this handler's own nested RPCs (Deadline.clamp in call()).
        budget = kwargs.pop(_DEADLINE_KW, None) if kwargs else None
        # v3: the caller's trace context rides the request; popped (like
        # the deadline) before schema validation, so handlers and
        # schemas never see it. When present + sampled, this dispatch
        # records a handler span split into queue-wait vs handler time.
        wire_trace = kwargs.pop(_TRACE_KW, None) if kwargs else None
        obs = _plane_enabled()
        if obs and wire_trace is not None:
            # raycheck: disable=RC02 — wall-clock span timestamp for cross-process trace correlation, not deadline arithmetic
            wall_start = time.time()
        else:
            wall_start = 0.0
        # Run the handler first, catching EVERYTHING it raises — a
        # handler's own ConnectionError (e.g. it called a dead peer) must
        # become an err frame, or the caller would block forever on a
        # reply that never comes.
        frames = []
        try:
            with Deadline.budget(budget):
                if method in self._stream_handlers:
                    from ray_tpu.cluster import schema

                    kwargs = schema.validate(method, kwargs)
                    for chunk in self._stream_handlers[method](**kwargs):
                        if isinstance(chunk,
                                      (bytes, bytearray, memoryview)):
                            with send_lock:  # raw frame: unpickled
                                _send_raw_chunk(sock, seq, chunk)
                        else:
                            reply((seq, "chunk", chunk))
                    frames.append((seq, "ok", None))
                else:
                    fn = self._handlers.get(method)
                    if fn is None:
                        raise AttributeError(f"no rpc method {method!r}")
                    from ray_tpu.cluster import schema

                    kwargs = schema.validate(method, kwargs)
                    frames.append((seq, "ok", fn(**kwargs)))
        except BaseException as e:  # noqa: BLE001 — ship to caller
            frames = [(seq, "err", protocol.format_exception(e))]
        if obs:
            self._observe(method, t_run, t_enq, nbytes, wire_trace,
                          wall_start, peer, frames)
        try:
            for frame in frames:
                reply(frame)
        except (ConnectionError, OSError) as e:
            # Client went away (BrokenPipeError/EPIPE after the peer
            # gave up on a shed or slow request): count-and-drop — a
            # per-reply stack trace under overload would itself be an
            # amplification vector. Its reader thread will notice.
            from ray_tpu.observability.metrics import rpc_replies_dropped

            with self._overload_lock:
                self.num_replies_dropped += 1
            rpc_replies_dropped.inc()
            logger.debug("reply to %s for %s (seq %d) undeliverable: "
                         "%r", peer, method, seq, e)

    def start(self) -> "RpcServer":
        self._thread.start()
        return self

    def _observe(self, method: str, t_run: float,
                 t_enq: Optional[float], nbytes: Optional[int],
                 wire_trace, wall_start: float, peer: str,
                 frames) -> None:
        """Observability plane: per-method latency/queue/size histograms
        tagged (method, dst_kind), plus — for sampled wire traces — a
        handler span parented to the caller's span over the wire."""
        try:
            dt_s = time.monotonic() - t_run
            queue_s = (t_run - t_enq) if t_enq is not None else 0.0
            role = _fault.process_role()
            tags = {"method": method, "dst_kind": role}
            from ray_tpu.observability.metrics import (
                rpc_request_bytes,
                rpc_server_latency_ms,
                rpc_server_queue_ms,
            )

            rpc_server_latency_ms.observe(dt_s * 1e3, tags)
            rpc_server_queue_ms.observe(queue_s * 1e3, tags)
            if nbytes is not None:
                rpc_request_bytes.observe(nbytes, tags)
            if wire_trace is not None:
                ok = bool(frames) and frames[0][1] == "ok"
                _tracing.record_remote_span(
                    f"rpc.{method}", wire_trace,
                    wall_start, wall_start + dt_s,
                    queue_wait_s=queue_s,
                    attributes={"method": method, "dst_kind": role,
                                "peer": peer,
                                "nbytes": nbytes or 0},
                    status="OK" if ok else "ERROR")
        except Exception as e:
            logger.debug("rpc observability for %s failed: %r",
                         method, e)

    def stop(self) -> None:
        if self._pool is not None:
            self._pool.stop()
        try:
            self._server.shutdown()
            self._server.server_close()
        except Exception as e:
            logger.debug("rpc server %s stop raced: %r",
                         self.address, e)


# --------------------------------------------------------------------------
# client
# --------------------------------------------------------------------------


class RpcClient:
    """One persistent connection; thread-safe pipelined calls.

    A dedicated reader thread demultiplexes replies by seq id, so N
    threads can have calls in flight concurrently (the reference's
    completion-queue client, rpc/client_call.h, by other means).
    """

    def __init__(self, address: str, connect_timeout: float = 10.0):
        self.address = address
        host, port_s = address.rsplit(":", 1)
        plane = _fault.get_plane()
        fault = (plane.decide("connect", address)
                 if plane is not None else None)
        if fault is not None:
            if fault["action"] == "refuse" or (
                    fault["action"] in ("drop", "partition")
                    and fault.get("phase") != "post-hello"):
                raise RpcConnectionError(
                    f"cannot connect to {address}: "
                    f"[fault-injected refuse]")
            if fault["action"] == "delay":
                time.sleep(fault["seconds"])
        try:
            self._sock = socket.create_connection(
                (host, int(port_s)), timeout=connect_timeout)
        except OSError as e:
            raise RpcConnectionError(
                f"cannot connect to {address}: {e}") from None
        self._sock.settimeout(None)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # reject-on-mismatch handshake precedes the reader thread: a
        # version skew surfaces here as RpcVersionError, synchronously
        try:
            _send_hello(self._sock)
            _check_hello(self._sock, f"server {address}",
                         timeout=connect_timeout)
        except RpcVersionError:
            self._sock.close()
            raise
        except (ConnectionError, OSError) as e:
            self._sock.close()
            raise RpcConnectionError(
                f"handshake with {address} failed: {e}") from None
        if fault is not None and fault["action"] in ("drop", "partition") \
                and fault.get("phase") == "post-hello":
            # half-open peer: the handshake completed, then it died
            self._sock.close()
            raise RpcConnectionError(
                f"connection to {address} dropped post-hello "
                f"[fault-injected]")
        self._send_lock = threading.Lock()
        self._pending: Dict[int, "_Call"] = {}
        self._pending_lock = threading.Lock()
        self._seq = 0
        self._closed = False
        # raycheck: disable=RC09 — the reader's lifetime is the socket's: close() aborts the blocking recv and the loop exits through _fail_all; it cannot outlive the connection it demultiplexes
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True,
            name=f"rpc-client-{address}")
        self._reader.start()

    # -- plumbing ----------------------------------------------------------
    def _next_seq(self) -> int:
        with self._pending_lock:
            self._seq += 1
            return self._seq

    def _read_loop(self) -> None:
        try:
            while True:
                body = _recv_msg(self._sock)
                if body and body[0] == _RAW_MARKER:
                    (seq,) = _LEN.unpack(bytes(body[1:9]))
                    kind, payload = "chunk", memoryview(body)[9:]
                else:
                    seq, kind, payload = protocol.loads(body)
                with self._pending_lock:
                    call = self._pending.get(seq)
                if call is None:
                    continue  # cancelled
                call.feed(kind, payload)
                if kind != "chunk":
                    with self._pending_lock:
                        self._pending.pop(seq, None)
        except (RpcConnectionError, ConnectionError, OSError) as e:
            self._fail_all(e)

    def _fail_all(self, exc: Exception) -> None:
        self._closed = True
        with self._pending_lock:
            pending, self._pending = self._pending, {}
        for call in pending.values():
            call.feed("conn_err", (None, "", repr(exc)))

    # -- API ---------------------------------------------------------------
    def call(self, method: str, timeout: Optional[float] = None,
             **kwargs) -> Any:
        """Blocking unary call. The timeout is clamped to the thread's
        remaining Deadline budget (a nested RPC never waits longer than
        its caller is still willing to), and the effective budget rides
        the request so the handler's own RPCs inherit it."""
        timeout = Deadline.clamp(timeout)
        call = self._start(method, kwargs, budget=timeout)
        return call.result(timeout)

    def call_async(self, method: str, **kwargs) -> "_Call":
        """Returns a handle; .result(timeout) joins it."""
        return self._start(method, kwargs)

    def call_stream(self, method: str, on_chunk: Callable[[Any], None],
                    timeout: Optional[float] = None, **kwargs) -> None:
        """Invoke a stream method; on_chunk fires (on the reader thread)
        per chunk; returns when the terminating ok/err frame arrives."""
        timeout = Deadline.clamp(timeout)
        call = self._start(method, kwargs, on_chunk=on_chunk,
                           budget=timeout)
        call.result(timeout)

    def call_data_async(self, method: str, payload,
                        **kwargs) -> "_Call":
        """Send a raw data frame (wire v4): the pickled (method,
        kwargs) header plus PAYLOAD's bytes verbatim — the payload is
        handed to the kernel straight from the caller's buffer (a
        pinned shm view on the push path), never copied into a pickle.
        Returns a handle; .result(timeout) joins the server's ack.
        Data frames share the connection's framing with ordinary
        calls, so they interleave safely and arrive in send order."""
        if self._closed:
            raise RpcConnectionError(
                f"connection to {self.address} closed")
        plane = _fault.get_plane()
        fault = (plane.decide("request", self.address, method)
                 if plane is not None else None)
        seq = self._next_seq()
        call = _Call(self.address, None)
        with self._pending_lock:
            self._pending[seq] = call
        if fault is not None and fault["action"] in ("drop", "partition"):
            return call  # silently lost: caller times out
        if fault is not None and fault["action"] == "delay":
            time.sleep(fault["seconds"])
        try:
            header = protocol.dumps((method, kwargs))
            if fault is not None and fault["action"] == "corrupt":
                # flip seeded payload bytes in flight — the data-plane
                # analog of _start's frame corruption; tail-biased into
                # the chunk bytes, which only the integrity plane's
                # fused crc can catch (the framing stays intact)
                payload = _fault.apply_corruption(
                    bytearray(payload), fault, tail_bias=True)
            if fault is not None and fault["action"] == "truncate":
                with self._send_lock:
                    self._sock.sendall(
                        _LEN.pack(9 + 4 + len(header) + len(payload))
                        + b"R" + _LEN.pack(seq)
                        + _U32.pack(len(header)) + header)
                    self._sock.sendall(bytes(payload[:len(payload) // 2]))
                    self._sock.close()  # die mid-frame
                raise RpcConnectionError(
                    f"send to {self.address} truncated mid-frame "
                    f"[fault-injected]")
            with self._send_lock:
                _send_data_frame(self._sock, seq, header, payload)
        except (ConnectionError, OSError) as e:
            with self._pending_lock:
                self._pending.pop(seq, None)
            self._closed = True
            raise RpcConnectionError(
                f"send to {self.address} failed: {e}") from None
        except RpcConnectionError:
            with self._pending_lock:
                self._pending.pop(seq, None)
            self._closed = True
            raise
        return call

    def _start(self, method: str, kwargs: dict,
               on_chunk: Optional[Callable] = None,
               budget: Optional[float] = None) -> "_Call":
        if self._closed:
            raise RpcConnectionError(f"connection to {self.address} closed")
        # v2: ship the effective budget — the already-clamped per-call
        # timeout when there is one, else the thread's ambient remaining
        # budget; the server re-establishes it around the handler so
        # nested hops keep shrinking it. A small reply margin is shaved
        # off so a handler that spends its whole budget still gets its
        # answer back before the caller abandons the call.
        if budget is None:
            budget = Deadline.remaining()
        if budget is not None:
            kwargs = dict(kwargs)
            kwargs[_DEADLINE_KW] = max(
                0.0, budget - min(0.5, 0.1 * budget))
        # v3: a sampled trace context rides the frame, so the server can
        # parent its handler span to the caller's current span. The
        # enabled() bool is the only cost when tracing is off; unsampled
        # traces propagate nothing (head-based sampling: a trace is
        # recorded everywhere or nowhere).
        if _tracing.enabled():
            ctx = _tracing.current_context()
            if (ctx is not None and ctx.sampled
                    and _TRACE_KW not in kwargs and _plane_enabled()):
                kwargs = dict(kwargs)
                kwargs[_TRACE_KW] = ctx.to_dict()
        plane = _fault.get_plane()
        fault = (plane.decide("request", self.address, method)
                 if plane is not None else None)
        seq = self._next_seq()
        call = _Call(self.address, on_chunk)
        with self._pending_lock:
            self._pending[seq] = call
        if fault is not None and fault["action"] in ("drop", "partition"):
            # the frame is silently lost — the caller sees exactly what
            # a one-way partition produces: a timeout, not a conn error
            return call
        if fault is not None and fault["action"] == "delay":
            time.sleep(fault["seconds"])
        try:
            body = protocol.dumps((seq, method, kwargs))
            if fault is not None and fault["action"] == "corrupt":
                # silent data corruption: one seeded byte of the frame
                # flips in flight; tail-biased so a big chunk frame
                # corrupts payload bytes (caught by the integrity
                # plane's checksums), not the pickle framing (which
                # would fail loudly on its own)
                body = _fault.apply_corruption(body, fault,
                                               tail_bias=True)
            if fault is not None and fault["action"] == "truncate":
                cut = fault.get("truncate_bytes")
                if cut is None:
                    cut = max(1, len(body) // 2)
                with self._send_lock:
                    self._sock.sendall(_LEN.pack(len(body))
                                       + bytes(body[:cut]))
                    self._sock.close()  # cut mid-frame
                raise RpcConnectionError(
                    f"send to {self.address} truncated mid-frame "
                    f"[fault-injected]")
            with self._send_lock:
                self._sock.sendall(_LEN.pack(len(body)) + body)
                if fault is not None and fault["action"] == "duplicate":
                    self._sock.sendall(_LEN.pack(len(body)) + body)
        except (ConnectionError, OSError) as e:
            with self._pending_lock:
                self._pending.pop(seq, None)
            self._closed = True
            raise RpcConnectionError(
                f"send to {self.address} failed: {e}") from None
        except RpcConnectionError:
            with self._pending_lock:
                self._pending.pop(seq, None)
            self._closed = True
            raise
        return call

    @property
    def closed(self) -> bool:
        return self._closed

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.close()
        except Exception as e:
            logger.debug("closing rpc socket to %s failed: %r",
                         self.address, e)


class ResilientRpcClient:
    """RpcClient wrapper that survives server restarts and transient
    partitions: a call that hits a dead connection reconnects and
    retries under **capped exponential backoff with full jitter**
    (reference: GCS client reconnect/retry on GCS failover,
    gcs_rpc_client.h retryable channels; the backoff discipline is the
    AWS full-jitter recipe, so N clients waking from the same partition
    don't stampede the recovering server in lockstep). Only for
    idempotent control-plane calls — the GCS surface (heartbeats,
    directory updates, KV, pubsub) is, and the mutation RPCs carry
    request tokens (gcs_server.py) so a retried create/kill cannot
    double-apply.

    The retry window honors, in order of tightness: the configured
    window, the caller's per-call timeout, and the thread's propagated
    Deadline budget — a retry never spends time the original caller no
    longer has.

    Overload plane (cluster/overload.py): retries additionally spend a
    per-destination token-bucket **retry budget** (replenished by
    successes, so aggregate retry traffic is capped at a fixed fraction
    of goodput — the defense against metastable retry storms), and a
    per-destination **circuit breaker** opens after K consecutive
    failures, fails fast while open, and half-open-probes its way
    closed, honoring the backoff hint of a server's
    :class:`RetryLaterError` shed reply. Both are shared by every
    client in the process talking to the same address."""

    def __init__(self, address: str, connect_timeout: Optional[float] = None,
                 retry_window_s: Optional[float] = None,
                 base_backoff_s: Optional[float] = None,
                 max_backoff_s: Optional[float] = None,
                 retry_budget=None, breaker=None,
                 overload: Optional[bool] = None):
        from ray_tpu._private.config import Config
        from ray_tpu.cluster import overload as _overload

        cfg = Config.instance()
        self.address = address
        self._connect_timeout = (connect_timeout
                                 if connect_timeout is not None
                                 else cfg.rpc_connect_timeout_s)
        self._retry_window_s = (retry_window_s
                                if retry_window_s is not None
                                else cfg.rpc_retry_window_s)
        self._base_backoff_s = (base_backoff_s
                                if base_backoff_s is not None
                                else cfg.rpc_retry_base_ms / 1000.0)
        self._max_backoff_s = (max_backoff_s
                               if max_backoff_s is not None
                               else cfg.rpc_retry_max_backoff_ms / 1000.0)
        # budget + breaker: explicit instances win (tests); else the
        # process-wide per-destination registries, unless the plane is
        # off (`overload=False`, or the Config master switch)
        on = _overload.enabled() if overload is None else bool(overload)
        self._budget = retry_budget if retry_budget is not None else (
            _overload.budget_for(address) if on else None)
        self._breaker = breaker if breaker is not None else (
            _overload.breaker_for(address) if on else None)
        self._lock = threading.Lock()
        self._client: Optional[RpcClient] = None
        self._closed = False
        # explicit jitter stream: under an active fault plan the
        # backoff schedule replays from the plan's single seed
        self._rng = _fault.derive_rng(f"rpc-backoff|{address}")

    def _get(self) -> RpcClient:
        with self._lock:
            if self._closed:
                raise RpcConnectionError(
                    f"client to {self.address} is closed")
            if self._client is None or self._client.closed:
                self._client = RpcClient(self.address,
                                         self._connect_timeout)
            return self._client

    def call(self, method: str, timeout: Optional[float] = None,
             **kwargs) -> Any:
        # never retry past the caller's own timeout contract, nor past
        # the deadline budget propagated from an upstream caller
        window = self._retry_window_s
        if timeout is not None:
            window = min(window, timeout)
        window = Deadline.clamp(window)
        deadline = time.monotonic() + window
        attempt = 0
        last_exc: Optional[BaseException] = None
        while True:
            # breaker gate: while open, no attempt reaches the wire —
            # wait out the cool-down (fail fast once the window would
            # outlive the caller's own retry window, re-raising the
            # error type the caller already handles when there is one)
            if self._breaker is not None and not self._breaker.allow():
                wait = max(self._breaker.remaining_s(), 0.02)
                now = time.monotonic()
                if self._closed or now + wait >= deadline:
                    if last_exc is not None:
                        raise last_exc
                    raise RetryLaterError(
                        f"circuit to {self.address} is open "
                        f"({self._breaker.snapshot()})",
                        retry_after_s=wait)
                time.sleep(wait)
                continue
            try:
                result = self._get().call(method, timeout=timeout,
                                          **kwargs)
            except RpcConnectionError as e:
                last_exc = e
                if self._breaker is not None:
                    self._breaker.record_failure()
                if not self._retry_admitted(deadline, attempt):
                    raise
                attempt += 1
            except RetryLaterError as e:
                # a shed reply: the server is alive but overloaded —
                # honor its backoff hint, and let the breaker fail
                # fast if sheds keep coming
                last_exc = e
                if self._breaker is not None:
                    self._breaker.record_failure(hint_s=e.retry_after_s)
                if not self._retry_admitted(deadline, attempt,
                                            hint=e.retry_after_s):
                    raise
                attempt += 1
            else:
                if self._breaker is not None:
                    self._breaker.record_success()
                if self._budget is not None:
                    self._budget.on_success()
                return result

    def _retry_admitted(self, deadline: float, attempt: int,
                        hint: float = 0.0) -> bool:
        """May one more attempt go to the wire? Checks the retry window
        and spends one retry-budget token, then sleeps the backoff
        (capped exponential, full jitter, floored so a refused loop
        cannot hot-spin, and never below the server's hint)."""
        now = time.monotonic()
        if self._closed or now >= deadline:
            return False
        if self._budget is not None and not self._budget.try_spend():
            # budget empty: retrying would amplify the overload — give
            # up and surface the failure to the caller instead
            return False
        cap = min(self._max_backoff_s,
                  self._base_backoff_s * (2 ** attempt))
        sleep = max(self._rng.uniform(0.0, cap),
                    self._base_backoff_s / 4.0, 0.005, hint)
        sleep = min(sleep, max(deadline - now, 0.0))
        if sleep > 0:
            time.sleep(sleep)
        return True

    @property
    def closed(self) -> bool:
        return self._closed

    def close(self) -> None:
        self._closed = True
        with self._lock:
            if self._client is not None:
                self._client.close()


# Back-compat name (pre-fault-plane callers); same class, the retry
# policy just generalized from fixed 0.2 s sleeps to jittered backoff.
ReconnectingRpcClient = ResilientRpcClient


def fetch_object(client: "RpcClient", object_id: bytes,
                 timeout: float = 120.0) -> Optional[Tuple[bool, bytes]]:
    """Pull one object over a raylet's chunked ``get_object`` stream.

    Returns (is_error, payload) or None when the holder is gone, doesn't
    have the object, or the transfer was truncated. Shared by the driver
    and the raylet-to-raylet transfer plane so the reassembly protocol
    has exactly one implementation."""
    meta: Dict[str, Any] = {}
    state = {"buf": bytearray(), "view": None, "off": 0}

    def on_chunk(chunk):
        if isinstance(chunk, dict):
            meta.update(chunk)
            if meta.get("size"):  # preallocate: one write per chunk
                state["buf"] = bytearray(meta["size"])
                state["view"] = memoryview(state["buf"])
            return
        n = len(chunk)
        off = state["off"]
        view = state["view"]
        if view is not None and off + n <= len(state["buf"]):
            view[off:off + n] = chunk
        else:  # size-less or overflowing stream: fall back to append
            state["view"] = None
            if off and len(state["buf"]) != off:
                del state["buf"][off:]
            state["buf"].extend(chunk)
        state["off"] = off + n

    try:
        client.call_stream("get_object", on_chunk, timeout=timeout,
                           object_id=object_id)
    except Exception:
        return None
    state["view"] = None
    buf = state["buf"]
    if len(buf) > state["off"]:
        del buf[state["off"]:]
    if "size" in meta and len(buf) != meta["size"]:
        return None
    # integrity plane: the stream's header frame carries the holder's
    # digest — verify the reassembled payload at pull completion. A
    # mismatch reads as a failed holder (return None): the caller
    # tries the next replica, which is exactly the corruption-
    # triggered re-pull contract.
    crc = meta.get("crc")
    if crc is not None:
        from ray_tpu.cluster import integrity
        from ray_tpu.exceptions import ObjectCorruptedError

        try:
            integrity.verify(buf, crc, "pull_stream", bytes(object_id))
        except ObjectCorruptedError:
            logger.warning("pulled payload of %s failed its digest; "
                           "trying another holder",
                           bytes(object_id).hex()[:8])
            return None
    return bool(meta.get("is_error", False)), buf


class _Call:
    __slots__ = ("_event", "_kind", "_payload", "_on_chunk", "_address")

    def __init__(self, address: str, on_chunk: Optional[Callable] = None):
        self._event = threading.Event()
        self._kind: Optional[str] = None
        self._payload: Any = None
        self._on_chunk = on_chunk
        self._address = address

    def feed(self, kind: str, payload) -> None:
        if kind == "chunk":
            if self._on_chunk is not None:
                try:
                    self._on_chunk(payload)
                except Exception:
                    logger.exception("stream chunk callback failed")
            return
        self._kind = kind
        self._payload = payload
        self._event.set()

    def result(self, timeout: Optional[float] = None) -> Any:
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"rpc to {self._address} timed out after {timeout}s")
        if self._kind == "ok":
            return self._payload
        if self._kind == "conn_err":
            raise RpcConnectionError(
                f"connection to {self._address} lost: {self._payload[2]}")
        raise protocol.restore_exception(*self._payload)

    def done(self) -> bool:
        return self._event.is_set()
