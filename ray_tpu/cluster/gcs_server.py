"""GCS server — the cluster-global control plane, as its own process.

Process-tier equivalent of the reference's gcs_server
(src/ray/gcs/gcs_server/gcs_server.cc:121-165 composition root;
gcs_server_main.cc:36 entry): node table + heartbeat failure detection
(gcs_heartbeat_manager.cc, num_heartbeats_timeout), internal KV
(gcs_kv_manager.cc), object directory (the GCS fallback of
ownership_based_object_directory.cc), actor management with
restart-on-node-death (gcs_actor_manager.cc:945 ReconstructActor), and
placement-group packing + 2PC driving raylet processes
(gcs_placement_group_scheduler.cc).

Run as ``python -m ray_tpu.cluster.gcs_server --port N``; raylet
processes register over the framed-TCP RPC substrate (cluster/rpc.py).
"""

from __future__ import annotations

import functools
import logging
import threading
import time
from typing import Any, Dict, List, Optional, Set, Tuple

import numpy as np

from ray_tpu._private.config import Config
from ray_tpu.cluster.rpc import RpcClient, RpcConnectionError, RpcServer
from ray_tpu.cluster.threads import ThreadRegistry
from ray_tpu.exceptions import ActorInitError

logger = logging.getLogger(__name__)


def token_deduped(fn):
    """Wrap a GCS mutation RPC handler with the request-token dedupe
    path (reference: the GCS dedupes retried RPCs by request id). The
    wrapper owns the reserved ``token`` kwarg: a client retry after a
    lost ack — or a fault-plane frame duplication — replays the cached
    reply instead of double-applying the mutation (double-counted actor
    restarts, twice-killed actors, double-placed PGs). Handlers declare
    only their domain arguments. raycheck RC04 enforces that every
    registered mutation handler carries this decorator."""

    @functools.wraps(fn)
    def wrapper(self, *args, token: str = "", **kwargs):
        cached = self._token_seen(token)
        if cached is not None:
            return cached
        return self._token_store(token, fn(self, *args, **kwargs))

    wrapper.__raycheck_token_deduped__ = True
    return wrapper


class _NodeRecord:
    __slots__ = ("node_id", "address", "resources", "available", "alive",
                 "last_heartbeat", "missed", "overload", "integrity",
                 "serve", "worker_pool", "threads", "draining",
                 "drain_deadline", "drain_reason")

    def __init__(self, node_id: str, address: str,
                 resources: Dict[str, float]):
        self.node_id = node_id
        self.address = address
        self.resources = dict(resources)
        self.available = dict(resources)
        self.alive = True
        self.last_heartbeat = time.monotonic()
        self.missed = 0
        # drain plane: DRAINING lifecycle state (alive but leaving —
        # placement solves exclude it, actors migrate, sole-copy
        # objects re-replicate; _mark_node_dead finishes the exit)
        self.draining = False
        self.drain_deadline = 0.0  # monotonic; hard-kill fallback past it
        self.drain_reason = ""
        # latest overload-plane counters the node heartbeated (sheds,
        # backpressure, breaker states) — surfaced via cluster_view
        self.overload: Dict = {}
        # latest integrity-plane counters (corruption detections,
        # discarded replicas, verified bytes) — same surfacing
        self.integrity: Dict = {}
        # latest serve-resilience counters (unhealthy replicas,
        # completed drains, router exclusions, backpressure) — same
        self.serve: Dict = {}
        # latest warm worker-pool counters (idle size, warm hits and
        # misses, returns, reaps, create-latency p50) — same
        self.worker_pool: Dict = {}
        # live daemon-thread roots the node last heartbeated
        # ({thread name -> root function label}) — cluster_view
        # carries them so `cli.py status` can show per-node threads
        self.threads: Dict = {}


class _ActorRecord:
    __slots__ = ("actor_id", "name", "cls_bytes", "args_bytes", "resources",
                 "max_restarts", "restarts_used", "state", "node_id",
                 "incarnation", "owner", "placing", "init_error")

    def __init__(self, actor_id: str, cls_bytes: bytes, args_bytes: bytes,
                 resources: Dict[str, float], max_restarts: int,
                 name: str = ""):
        self.actor_id = actor_id
        self.name = name
        self.cls_bytes = cls_bytes
        self.args_bytes = args_bytes
        self.resources = dict(resources)
        self.max_restarts = max_restarts
        self.restarts_used = 0
        self.state = "PENDING"  # PENDING|ALIVE|RESTARTING|DEAD
        self.node_id: Optional[str] = None
        self.incarnation = 0
        self.owner = ""
        self.placing = False  # a placement RPC is in flight
        # deterministic creation failure (class unpickle or __init__
        # raised): the actor is DEAD with this message instead of
        # burning placement retries on other nodes
        self.init_error = ""

    def view(self) -> dict:
        return {
            "actor_id": self.actor_id, "name": self.name,
            "state": self.state, "node_id": self.node_id,
            "incarnation": self.incarnation,
            "restarts_used": self.restarts_used,
            "max_restarts": self.max_restarts,
            "init_error": self.init_error,
        }


class _PgRecord:
    __slots__ = ("pg_id", "bundles", "strategy", "placements", "state",
                 "placing")

    def __init__(self, pg_id: str, bundles: List[Dict[str, float]],
                 strategy: str):
        self.pg_id = pg_id
        self.bundles = bundles
        self.strategy = strategy
        # bundle_index -> node_id
        self.placements: Dict[int, str] = {}
        self.state = "PENDING"  # PENDING|CREATED|RESCHEDULING|REMOVED
        self.placing = False  # a pack/2PC attempt is in flight

    def view(self) -> dict:
        return {"pg_id": self.pg_id, "state": self.state,
                "placements": dict(self.placements),
                "bundles": self.bundles, "strategy": self.strategy}


class GcsService:
    def __init__(self, heartbeat_period_ms: Optional[int] = None,
                 num_heartbeats_timeout: Optional[int] = None,
                 storage_path: Optional[str] = None):
        from ray_tpu.cluster import fault_plane

        fault_plane.set_process_role("gcs")
        cfg = Config.instance()
        self.heartbeat_period_s = (
            heartbeat_period_ms or cfg.raylet_heartbeat_period_ms) / 1000.0
        self.num_heartbeats_timeout = (
            num_heartbeats_timeout or cfg.num_heartbeats_timeout)
        self._lock = threading.RLock()
        # Request-token dedupe for mutation RPCs (reference: the GCS
        # dedupes retried RPCs by request ids). A client retry after a
        # lost ack — or a fault-plane frame duplication — replays the
        # cached reply instead of double-applying the mutation
        # (double-counted actor restarts, twice-killed actors, ...).
        from collections import OrderedDict

        self._request_tokens: "OrderedDict[str, Any]" = OrderedDict()
        self._request_token_cap = 10_000
        self._nodes: Dict[str, _NodeRecord] = {}
        self._kv: Dict[Tuple[str, bytes], bytes] = {}
        # object directory: object_id -> {node_id}; sizes tracked once
        self._locations: Dict[bytes, Set[str]] = {}
        self._object_sizes: Dict[bytes, int] = {}
        self._location_cv = threading.Condition(self._lock)
        # actor_wait long-poll: waiters block here until a state
        # transition is published (shares self._lock, like the
        # location cv, so the wait predicate reads _actors safely)
        self._actor_cv = threading.Condition(self._lock)
        self._actors: Dict[str, _ActorRecord] = {}
        self._named_actors: Dict[str, str] = {}
        self._pgs: Dict[str, _PgRecord] = {}
        self._change_seq = 0
        # raylet-client cache: get-or-create races between concurrent
        # handler/loop threads would leak duplicate open connections —
        # every read/insert holds _client_lock, with the blocking
        # connect itself outside it (RC01)
        self._clients: Dict[str, RpcClient] = {}  # address -> client
        self._client_lock = threading.Lock()
        # check-and-set under self._lock: detector vs finishing sweep
        self._sweep_running = False
        # nodes whose preemption notice already spawned a drain worker
        # but whose _begin_drain has not run yet — the inline heartbeat
        # handler must not spawn one worker per 100 ms heartbeat
        self._preempt_pending: Set[str] = set()
        # GCS-hosted pubsub channels (reference:
        # gcs_server/pubsub_handler.cc over pubsub/publisher.cc)
        import os as _os

        from ray_tpu.pubsub import Publisher

        # fresh per process: raylets detect a GCS restart by watching
        # this token change in heartbeat replies and re-report state the
        # restarted GCS cannot restore (object locations)
        self.instance_id = _os.urandom(8).hex()
        self.publisher = Publisher()
        # pluggable table storage (reference: gcs_table_storage.h over
        # store_client/); a durable backend makes the GCS restartable
        from ray_tpu.gcs.table_storage import open_table_storage

        self.storage = open_table_storage(storage_path)
        self._restore_from_storage()
        self._stop = threading.Event()
        # every background thread (detector, retry sweeps) spawns
        # through the registry so stop() joins them BY NAME instead of
        # leaking a sweep that is still issuing placement RPCs
        self._threads = ThreadRegistry("gcs")
        self._detector: Optional[threading.Thread] = None
        self.server: Optional[RpcServer] = None

    # ------------------------------------------------------------- serving
    def serve(self, host: str = "127.0.0.1", port: int = 0) -> RpcServer:
        srv = RpcServer(host, port)
        fast = {  # pure bookkeeping: dispatch inline, no thread spawn
            "register_node", "heartbeat", "cluster_view",
            "kv_put", "kv_get", "kv_del", "kv_keys",
            "object_add_location", "object_add_locations",
            "object_remove_location",
            "object_locations", "actor_get", "actor_by_name",
            "actor_list", "pg_get", "job_view", "ping",
            "pubsub_subscribe", "pubsub_unsubscribe", "pubsub_publish",
        }
        for name in (
            "register_node", "heartbeat", "cluster_view", "drain_node",
            "kv_put", "kv_get", "kv_del", "kv_keys",
            "object_add_location", "object_add_locations",
            "object_remove_location",
            "object_locations", "object_wait_location",
            "actor_create", "actor_get", "actor_by_name", "actor_kill",
            "actor_create_batch", "actor_kill_batch",
            "actor_wait",  # long-poll: MUST dispatch on its own thread
            "actor_list", "report_actor_failure",
            "pg_create", "pg_get", "pg_remove", "pg_pending",
            "job_view", "ping",
            "pubsub_subscribe", "pubsub_unsubscribe", "pubsub_publish",
            "pubsub_poll",  # long-poll: MUST dispatch on its own thread
            "collect_timeline",  # fans RPCs to raylets: own thread
        ):
            srv.register(name, getattr(self, name), inline=name in fast)
        srv.start()
        self.server = srv
        self._detector = self._threads.spawn(self._detector_loop,
                                             "gcs-detector")
        # drains interrupted by a GCS restart resume here: the restored
        # record carries the remaining deadline budget, and the worker
        # re-runs migration/re-replication idempotently (already-moved
        # actors are off the node; already-replicated objects have >1
        # location and are no longer sole-copy)
        with self._lock:
            resumable = [nid for nid, rec in self._nodes.items()
                         if rec.alive and rec.draining]
        for nid in resumable:
            self._threads.spawn(
                functools.partial(self._resume_drain, nid),
                f"gcs-drain-resume-{nid[:8]}")
        return srv

    def stop(self) -> None:
        self._stop.set()
        if self.server is not None:
            self.server.stop()
        with self._client_lock:
            clients = list(self._clients.values())
        for c in clients:
            c.close()
        # the detector/sweep threads issue persistence writes: join
        # them (by name, surfacing any hung one) before closing the
        # sqlite connection under them
        self._threads.join_all(timeout=10.0)
        self.storage.close()

    def ping(self) -> str:
        return "pong"

    # -------------------------------------------------- request-token dedupe
    def _token_seen(self, token: str) -> Optional[Any]:
        """Cached reply for a duplicated/retried mutation, or None."""
        if not token:
            return None
        with self._lock:
            return self._request_tokens.get(token)

    def _token_store(self, token: str, reply: Any) -> Any:
        if token:
            with self._lock:
                self._request_tokens[token] = reply
                while len(self._request_tokens) > self._request_token_cap:
                    self._request_tokens.popitem(last=False)
        return reply

    def _row_tokens_resolve(self, rows: List[dict],
                            method: str) -> Dict[int, Any]:
        """Batched per-row dedupe lookup for a ``*_batch`` frame: one
        lock hold resolves every row's ``token`` against the request-
        token cache. Returns {row index: cached result} for rows whose
        mutation already applied — a RETRIED frame (lost ack, client
        reconnect, fault-plane duplication) replays exactly the rows it
        already acked and re-runs only the rest, which is the partial-
        application recovery contract: a frame interrupted mid-fanout
        stored tokens only for the rows that finished."""
        replayed: Dict[int, Any] = {}
        with self._lock:
            for i, row in enumerate(rows):
                tok = row.get("token") or ""
                if tok:
                    cached = self._request_tokens.get(tok)
                    if cached is not None:
                        replayed[i] = cached
        if replayed:
            from ray_tpu.observability import metrics

            metrics.batch_rows_deduped.inc(
                len(replayed), tags={"method": method})
        return replayed

    def _row_tokens_store(self, pairs: List[Tuple[str, Any]]) -> None:
        """Batched store of (row token, row result) pairs under one
        lock hold, AFTER each row's mutation fully applied (rows that
        never finished store nothing, so a retry re-runs them)."""
        pairs = [(t, r) for t, r in pairs if t]
        if not pairs:
            return
        with self._lock:
            for tok, result in pairs:
                self._request_tokens[tok] = result
            while len(self._request_tokens) > self._request_token_cap:
                self._request_tokens.popitem(last=False)

    # -------------------------------------------------------------- pubsub
    # Reference: gcs_server/pubsub_handler.cc — the GCS hosts the
    # cluster-wide channels; clients long-poll over the RPC substrate.
    def pubsub_subscribe(self, subscriber_id: str, channel: str,
                         key: Optional[str] = None) -> dict:
        return self.publisher.subscribe(subscriber_id, channel, key)

    def pubsub_unsubscribe(self, subscriber_id: str,
                           channel: Optional[str] = None,
                           key: Optional[str] = None) -> dict:
        return self.publisher.unsubscribe(subscriber_id, channel, key)

    def pubsub_publish(self, channel: str, key: str, message) -> dict:
        return {"reached": self.publisher.publish(channel, key, message)}

    def pubsub_poll(self, subscriber_id: str,
                    timeout_s: float = 30.0) -> dict:
        return self.publisher.poll(subscriber_id, timeout_s)

    def _publish_actor(self, rec: "_ActorRecord") -> None:
        """Actor state transitions fan out on the ACTOR channel AND
        write through to table storage (reference: gcs_actor_manager
        publishes + persists ActorTableData on every transition)."""
        from ray_tpu.pubsub import ACTOR_CHANNEL

        self.publisher.publish(ACTOR_CHANNEL, rec.actor_id, rec.view())
        self._persist_actor(rec)
        # callers hold self._lock (== the cv's lock): wake actor_wait
        # long-polls so clients see the transition without hot-polling
        self._actor_cv.notify_all()

    # ------------------------------------------------------- table storage
    def _persist_actor(self, rec: "_ActorRecord") -> None:
        import cloudpickle

        from ray_tpu.gcs.table_storage import ACTOR_TABLE

        if rec.state == "DEAD":
            # reclaim the row — dead actors must not accumulate in the
            # table nor re-materialize on restart
            self.storage.delete(ACTOR_TABLE, rec.actor_id.encode())
            return
        self.storage.put(ACTOR_TABLE, rec.actor_id.encode(),
                         cloudpickle.dumps({
                             s: getattr(rec, s) for s in rec.__slots__}))

    def _persist_pg(self, rec: "_PgRecord") -> None:
        import cloudpickle

        from ray_tpu.gcs.table_storage import PG_TABLE

        self.storage.put(PG_TABLE, rec.pg_id.encode(),
                         cloudpickle.dumps({
                             s: getattr(rec, s) for s in rec.__slots__}))

    def _persist_node(self, rec: "_NodeRecord") -> None:
        import cloudpickle

        from ray_tpu.gcs.table_storage import NODE_TABLE

        row = {"node_id": rec.node_id,
               "address": rec.address,
               "resources": rec.resources}
        if rec.draining:
            # persist the drain (with its REMAINING budget) so a GCS
            # restart resumes it instead of stranding a half-migrated
            # node; non-draining rows keep the legacy shape byte-for-
            # byte (drain-plane-off parity)
            row["draining"] = True
            row["drain_reason"] = rec.drain_reason
            row["drain_remaining_s"] = max(
                0.0, rec.drain_deadline - time.monotonic())
        self.storage.put(NODE_TABLE, rec.node_id.encode(),
                         cloudpickle.dumps(row))

    def _restore_from_storage(self) -> None:
        """Rebuild state after a GCS restart (reference:
        gcs_init_data.cc loading every table before serving). Restored
        nodes get a full heartbeat grace window; truly dead ones fall to
        the detector, which then drives actor/PG recovery as usual."""
        import cloudpickle

        from ray_tpu.gcs.table_storage import (
            ACTOR_TABLE,
            KV_TABLE,
            NODE_TABLE,
            PG_TABLE,
        )

        for blob in self.storage.all(NODE_TABLE).values():
            row = cloudpickle.loads(blob)
            rec = _NodeRecord(
                row["node_id"], row["address"], row["resources"])
            if row.get("draining"):
                # resume the interrupted drain (serve() respawns its
                # worker); grant a minimum budget so a restart landing
                # right at the deadline still attempts migration
                rec.draining = True
                rec.drain_reason = row.get("drain_reason", "")
                rec.drain_deadline = time.monotonic() + max(
                    1.0, float(row.get("drain_remaining_s", 0.0)))
            self._nodes[row["node_id"]] = rec
        for blob in self.storage.all(ACTOR_TABLE).values():
            row = cloudpickle.loads(blob)
            if row["state"] == "DEAD":
                continue  # tombstone from an older storage format
            rec = _ActorRecord(row["actor_id"], row["cls_bytes"],
                               row["args_bytes"], row["resources"],
                               row["max_restarts"], row["name"])
            for slot in ("restarts_used", "state", "node_id",
                         "incarnation", "owner"):
                setattr(rec, slot, row[slot])
            rec.placing = False  # in-flight RPCs did not survive
            if rec.state == "RESTARTING":
                # the placement that was in flight died with the old
                # GCS; PENDING puts it back in the retry sweep's set
                rec.state = "PENDING"
            self._actors[rec.actor_id] = rec
            if rec.name:
                self._named_actors[rec.name] = rec.actor_id
        for blob in self.storage.all(PG_TABLE).values():
            row = cloudpickle.loads(blob)
            rec = _PgRecord(row["pg_id"], row["bundles"], row["strategy"])
            rec.placements = dict(row["placements"])
            rec.state = row["state"]
            self._pgs[rec.pg_id] = rec
        for key, value in self.storage.all(KV_TABLE).items():
            ns, k = cloudpickle.loads(key)
            self._kv[(ns, k)] = value
        if self._actors or self._kv or self._pgs or self._nodes:
            logger.info(
                "restored from table storage: %d nodes, %d actors, "
                "%d pgs, %d kv entries", len(self._nodes),
                len(self._actors), len(self._pgs), len(self._kv))

    # ------------------------------------------------------- raylet clients
    def _client_for(self, address: str) -> RpcClient:
        with self._client_lock:
            c = self._clients.get(address)
        if c is not None and not c.closed:
            return c
        # connect OUTSIDE the lock (RC01: the TCP dial blocks); on a
        # lost race the loser closes its own dial instead of leaking it
        fresh = RpcClient(address)
        with self._client_lock:
            cur = self._clients.get(address)
            if cur is not None and not cur.closed:
                c = cur
            else:
                self._clients[address] = fresh
                c = fresh
        if c is not fresh:
            fresh.close()
        return c

    def _client_for_node(self, node_id: str) -> Optional[RpcClient]:
        with self._lock:
            rec = self._nodes.get(node_id)
            if rec is None or not rec.alive:
                return None
            address = rec.address
        try:
            return self._client_for(address)
        except (RpcConnectionError, OSError):
            return None

    # ----------------------------------------------------------- node table
    def register_node(self, node_id: str, address: str,
                      resources: Dict[str, float]) -> dict:
        from ray_tpu.pubsub import NODE_CHANNEL

        with self._lock:
            rec = _NodeRecord(node_id, address, resources)
            old = self._nodes.get(node_id)
            if old is not None and old.draining:
                # a draining node re-announcing itself (reconcile after
                # a GCS restart mid-drain) stays draining: the resumed
                # drain worker reads this record, and a fresh one would
                # silently re-admit the node to placement
                rec.draining = True
                rec.drain_deadline = old.drain_deadline
                rec.drain_reason = old.drain_reason
            self._nodes[node_id] = rec
            self._change_seq += 1
            self.publisher.publish(NODE_CHANNEL, node_id, {
                "alive": True, "address": address, "resources": resources})
            self._persist_node(rec)
        logger.info("node %s registered at %s %s", node_id[:8], address,
                    resources)
        return {"heartbeat_period_ms": self.heartbeat_period_s * 1000,
                "num_heartbeats_timeout": self.num_heartbeats_timeout}

    def heartbeat(self, node_id: str,
                  available: Optional[Dict[str, float]] = None,
                  resources: Optional[Dict[str, float]] = None,
                  overload: Optional[Dict] = None,
                  integrity: Optional[Dict] = None,
                  serve: Optional[Dict] = None,
                  worker_pool: Optional[Dict] = None,
                  preempt_notice_s: Optional[float] = None,
                  threads: Optional[Dict] = None) -> dict:
        start_drain = False
        with self._lock:
            rec = self._nodes.get(node_id)
            if rec is None:
                return {"registered": False}
            rec.last_heartbeat = time.monotonic()
            rec.missed = 0
            if available is not None:
                rec.available = dict(available)
            if resources is not None:
                # totals change when PG bundles commit shadow resources
                rec.resources = dict(resources)
            if overload is not None:
                rec.overload = dict(overload)
            if integrity is not None:
                rec.integrity = dict(integrity)
            if serve is not None:
                rec.serve = dict(serve)
            if worker_pool is not None:
                rec.worker_pool = dict(worker_pool)
            if threads is not None:
                rec.threads = dict(threads)
            was_dead = not rec.alive
            rec.alive = True
            if was_dead:
                self._change_seq += 1
            # drain plane: a raylet-reported preemption notice starts a
            # graceful drain inside the notice window. Heartbeat runs
            # INLINE on the reader thread, so the drain itself goes to
            # a registry worker; _preempt_pending dedupes the spawn
            # across the per-100ms heartbeats until _begin_drain flips
            # rec.draining.
            if (preempt_notice_s is not None
                    and Config.instance().drain_plane_enabled
                    and not rec.draining
                    and node_id not in self._preempt_pending):
                self._preempt_pending.add(node_id)
                start_drain = True
            draining = rec.draining or start_drain
        if start_drain:
            from ray_tpu.observability import metrics

            metrics.preemption_notices.inc(tags={"role": "gcs"})
            self._threads.spawn(
                functools.partial(self._drain_for_preemption, node_id,
                                  float(preempt_notice_s)),
                f"gcs-preempt-drain-{node_id[:8]}")
        reply = {"registered": not was_dead,
                 "gcs_instance": self.instance_id,
                 # the raylet pairs this with its heartbeat RTT to
                 # estimate per-node clock offset (`cli.py timeline`
                 # merges every node's spans onto the GCS clock)
                 # raycheck: disable=RC02 — wall-clock sample for cross-node clock correlation, not deadline arithmetic
                 "server_time": time.time()}
        if draining:
            # only present while draining, so the drain-plane-off reply
            # stays byte-identical to the legacy shape
            reply["draining"] = True
        return reply

    def cluster_view(self) -> dict:
        with self._lock:
            view = {
                "seq": self._change_seq,
                "nodes": {
                    nid: {
                        "address": r.address,
                        "resources": dict(r.resources),
                        "available": dict(r.available),
                        "alive": r.alive,
                        # lifecycle: ALIVE -> (DRAINING) -> DEAD; with
                        # the drain plane off, draining never sets, so
                        # state is a pure function of `alive`
                        "state": ("DEAD" if not r.alive else
                                  "DRAINING" if r.draining else "ALIVE"),
                        "overload": dict(r.overload),
                        "integrity": dict(r.integrity),
                        "serve": dict(r.serve),
                        "worker_pool": dict(r.worker_pool),
                        "threads": dict(r.threads),
                    }
                    for nid, r in self._nodes.items()
                },
            }
            draining_now = sum(1 for r in self._nodes.values()
                               if r.alive and r.draining)
        # the GCS's own admission/shed counters ride the same view so
        # `cli.py status` shows overload cluster-wide in one call
        if self.server is not None:
            view["overload"] = self.server.overload_stats()
        # batched actor-lifecycle counters (these metrics live in the
        # GCS process, so the view is the only way clients see them)
        from ray_tpu.observability import metrics

        view["actor_batch"] = {
            "creates_batched": sum(
                metrics.actor_creates_batched.series().values()),
            "kills_batched": sum(
                metrics.actor_kills_batched.series().values()),
        }
        # drain/preemption counters live in the GCS process too; the
        # view is how `cli.py status` and the tests read them
        view["drain"] = {
            "nodes_draining": draining_now,
            "drains_completed": sum(
                metrics.drains_completed.series().values()),
            "preemption_notices": sum(
                metrics.preemption_notices.series().values()),
            "objects_rereplicated": sum(
                metrics.objects_rereplicated.series().values()),
        }
        return view

    def collect_timeline(self, per_node_timeout_s: float = 5.0) -> dict:
        """Observability plane: pull every alive node's flight-recorder
        ring (perf_dump) plus the GCS's own, for the clock-offset-
        corrected merge in `cli.py timeline` (reference: `ray timeline`
        rendering the GCS profile table). A dead or slow node becomes
        an error entry instead of stalling the whole collection."""
        from ray_tpu.observability import flight_recorder

        gcs_snap = flight_recorder.global_recorder.snapshot()
        gcs_snap["node_id"] = "gcs"
        # the GCS wall clock is the merge's reference clock
        gcs_snap["clock_offset_s"] = 0.0
        dumps: List[dict] = [gcs_snap]
        with self._lock:
            alive = [nid for nid, rec in self._nodes.items()
                     if rec.alive]
        for nid in alive:
            client = self._client_for_node(nid)
            if client is None:
                dumps.append({"node_id": nid, "error": "unreachable"})
                continue
            try:
                snap = client.call("perf_dump",
                                   timeout=per_node_timeout_s)
                snap.setdefault("node_id", nid)
                dumps.append(snap)
            except Exception as e:  # noqa: BLE001 — per-node isolation
                dumps.append({"node_id": nid, "error": repr(e)})
        return {"dumps": dumps}

    @token_deduped
    def drain_node(self, node_id: str, reason: str = "",
                   deadline_s: Optional[float] = None) -> dict:
        """Explicit graceful removal (ray stop / scale-down /
        preemption). Drain plane ON: DRAINING state + actor migration +
        sole-copy re-replication, bounded by ``deadline_s`` (default
        Config.drain_deadline_s), then deregistration — the handler is
        registered THREADED, so blocking here until the drain finishes
        is the synchronization callers like ProcessCluster.remove_node
        rely on. OFF: the legacy immediate hard-kill recovery.
        Token-deduped (reference: the DrainNode RPC is idempotent): a
        retried frame after a lost ack replays the cached reply instead
        of re-running the migration fan-out."""
        if not Config.instance().drain_plane_enabled:
            self._mark_node_dead(node_id, reason="drained")
            return {"ok": True}
        return self._drain_node_graceful(node_id, reason, deadline_s)

    # ------------------------------------------------- graceful node drain
    def _drain_node_graceful(self, node_id: str, reason: str = "",
                             deadline_s: Optional[float] = None) -> dict:
        cfg = Config.instance()
        budget = cfg.drain_deadline_s if deadline_s is None \
            else float(deadline_s)
        if self._begin_drain(node_id, reason, budget):
            return {"ok": True, "outcome": self._run_drain(node_id)}
        with self._lock:
            rec = self._nodes.get(node_id)
            if rec is None or not rec.alive:
                return {"ok": True, "outcome": "already_dead"}
        # a drain is already in flight (e.g. a preemption notice beat a
        # scale-down request to the same node): join it instead of
        # racing it, so this caller's "drain returned" still means the
        # node is gone
        join_deadline = time.monotonic() + budget + 5.0
        while time.monotonic() < join_deadline:
            with self._lock:
                rec = self._nodes.get(node_id)
                if rec is None or not rec.alive:
                    return {"ok": True, "outcome": "joined"}
            time.sleep(0.05)
        return {"ok": False, "outcome": "join_timeout"}

    def _begin_drain(self, node_id: str, reason: str,
                     deadline_s: float) -> bool:
        """Move NODE to DRAINING: placement solves exclude it from here
        on (pick/pack/batch-assign all test rec.draining), the change is
        published and persisted (a GCS restart resumes the drain), and
        the deadline arms the hard-kill fallback. Returns False if the
        node is unknown, dead, or already draining."""
        from ray_tpu.observability import metrics
        from ray_tpu.pubsub import NODE_CHANNEL

        with self._lock:
            rec = self._nodes.get(node_id)
            if rec is None or not rec.alive or rec.draining:
                return False
            rec.draining = True
            rec.drain_reason = reason or "drain"
            rec.drain_deadline = time.monotonic() + deadline_s
            self._change_seq += 1
            self.publisher.publish(NODE_CHANNEL, node_id, {
                "alive": True, "draining": True,
                "reason": rec.drain_reason})
            self._persist_node(rec)
            draining_now = sum(1 for r in self._nodes.values()
                               if r.alive and r.draining)
        metrics.nodes_draining.set(draining_now)
        logger.info("node %s DRAINING (%s, deadline %.1fs)",
                    node_id[:8], rec.drain_reason, deadline_s)
        return True

    def _run_drain(self, node_id: str) -> str:
        """Execute a drain whose record is already DRAINING: migrate
        actors off (kill-first, so the old incarnation never runs
        concurrently with its replacement), re-replicate sole-copy
        objects to survivors over the data plane, then deregister via
        the ordinary death path. Every step is bounded by the drain
        deadline; whatever is left when it lapses falls to
        _mark_node_dead's recovery (restart + location drop), so a
        wedged drain degrades to hard-kill semantics instead of
        stranding the cluster."""
        from ray_tpu.observability import metrics

        with self._lock:
            rec = self._nodes.get(node_id)
            if rec is None or not rec.alive or not rec.draining:
                return "lost"
            deadline = rec.drain_deadline
            drain_addr = rec.address
            actors = [a for a in self._actors.values()
                      if a.node_id == node_id and a.state == "ALIVE"]
        width = Config.instance().actor_batch_fanout

        def migrate(actor: "_ActorRecord") -> None:
            if time.monotonic() >= deadline:
                return  # leftover: _mark_node_dead restarts it
            client = self._client_for_node(node_id)
            if client is not None:
                try:
                    client.call(
                        "kill_actor", actor_id=actor.actor_id,
                        timeout=max(0.5, min(
                            5.0, deadline - time.monotonic())))
                except Exception as e:
                    # the node is leaving either way; a lost kill frame
                    # means the process dies with the node
                    logger.debug("drain kill of %s on %s failed: %r",
                                 actor.actor_id[:8], node_id[:8], e)
            with self._lock:
                if actor.state != "ALIVE" or actor.node_id != node_id:
                    return  # killed or moved concurrently
                # detach from the draining node BEFORE restarting, so
                # _mark_node_dead's sweep below cannot collect it again
                # and burn a second restart for one migration
                actor.node_id = None
            self._restart_actor(actor, dead_node=node_id)

        self._parallel_each("gcs-drain-migrate", actors, migrate,
                            width=width)
        # quiesce: let the raylet's queued/running tasks finish inside
        # the deadline — their results are objects born DURING the
        # drain, and deregistering while they're in flight would drop
        # the only copy and force a lineage re-execution (a duplicate
        # side effect the exactly-once probe would catch)
        quiesce_client = self._client_for_node(node_id)
        while quiesce_client is not None and \
                time.monotonic() < deadline:
            try:
                stats = quiesce_client.call(
                    "node_stats",
                    timeout=max(0.5, min(5.0,
                                         deadline - time.monotonic())))
            except Exception:
                break  # raylet already gone: nothing left to wait on
            if not stats.get("queued") and not stats.get("running"):
                break
            time.sleep(0.05)
        # sole-copy re-replication: an object whose ONLY replica sits
        # on the draining node would be lost at deregistration — direct
        # a survivor to pull it (chunk-tree data plane underneath)
        # while the holder is still up
        with self._lock:
            sole = [oid for oid, nodes in self._locations.items()
                    if nodes == {node_id}]
            targets = [nid for nid, r in self._nodes.items()
                       if r.alive and not r.draining]
        moved: List[bytes] = []  # list.append is atomic under the GIL
        pairs = ([(oid, targets[i % len(targets)])
                  for i, oid in enumerate(sole)] if targets else [])

        def rereplicate(pair: Tuple[bytes, str]) -> None:
            oid, target = pair
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return
            client = self._client_for_node(target)
            if client is None:
                return
            try:
                reply = client.call("pull_object", object_id=oid,
                                    from_address=drain_addr,
                                    timeout=max(0.5, remaining))
            except Exception as e:
                logger.debug("drain re-replication of %s -> %s failed: "
                             "%r", oid.hex()[:8], target[:8], e)
                return
            if isinstance(reply, dict) and not reply.get("ok", True):
                return
            moved.append(oid)

        self._parallel_each("gcs-drain-replicate", pairs, rereplicate,
                            width=width)
        if moved:
            metrics.objects_rereplicated.inc(len(moved))
        graceful = (time.monotonic() < deadline
                    and len(moved) == len(sole))
        outcome = "graceful" if graceful else "deadline"
        metrics.drains_completed.inc(tags={"outcome": outcome})
        if not graceful:
            logger.warning(
                "drain of %s hit its deadline (%d/%d sole-copy objects "
                "moved); falling back to hard-kill recovery",
                node_id[:8], len(moved), len(sole))
        self._mark_node_dead(node_id, reason="drained")
        return outcome

    def _drain_for_preemption(self, node_id: str, notice_s: float) -> None:
        """Heartbeat-reported preemption notice -> graceful drain inside
        the notice window (never longer: the node is gone at eviction)."""
        try:
            budget = min(max(0.5, notice_s),
                         Config.instance().drain_deadline_s)
            self._drain_node_graceful(node_id, reason="preempted",
                                      deadline_s=budget)
        except Exception:
            logger.exception("preemption drain of %s failed", node_id[:8])
        finally:
            with self._lock:
                self._preempt_pending.discard(node_id)

    def _resume_drain(self, node_id: str) -> None:
        """Finish a drain interrupted by a GCS restart (the restored
        node record carries the remaining deadline budget)."""
        from ray_tpu.observability import metrics

        try:
            if not Config.instance().drain_plane_enabled:
                # the plane was disabled across the restart: finish the
                # exit the pre-plane way rather than strand the node
                self._mark_node_dead(node_id, reason="drained")
                return
            with self._lock:
                draining_now = sum(1 for r in self._nodes.values()
                                   if r.alive and r.draining)
            metrics.nodes_draining.set(draining_now)
            # the restarted GCS boots with an EMPTY location directory;
            # raylets re-report their objects on their next heartbeat's
            # reconcile — wait for the draining node's re-report (its
            # heartbeat landing post-boot) before snapshotting sole
            # copies, else the re-replication pass sees nothing to move
            boot = time.monotonic()
            settle_until = boot + min(
                2.0, max(0.5, 10 * self.heartbeat_period_s))
            while time.monotonic() < settle_until:
                with self._lock:
                    rec = self._nodes.get(node_id)
                    heard = (rec is not None
                             and rec.last_heartbeat >= boot)
                if heard:
                    # one more beat of grace: the reconcile's location
                    # re-report follows the heartbeat that tripped this
                    time.sleep(2 * self.heartbeat_period_s)
                    break
                time.sleep(0.05)
            self._run_drain(node_id)
        except Exception:
            logger.exception("resumed drain of %s failed", node_id[:8])

    # ------------------------------------------------------ failure detector
    def _detector_loop(self) -> None:
        """Reference: gcs_heartbeat_manager.cc — tick once per heartbeat
        period; a node missing num_heartbeats_timeout consecutive periods
        is declared dead and its recovery fans out."""
        ticks = 0
        while not self._stop.wait(self.heartbeat_period_s):
            now = time.monotonic()
            dead: List[str] = []
            with self._lock:
                for rec in self._nodes.values():
                    if not rec.alive:
                        continue
                    gap = now - rec.last_heartbeat
                    rec.missed = int(gap / self.heartbeat_period_s)
                    if rec.missed >= self.num_heartbeats_timeout:
                        dead.append(rec.node_id)
            for nid in dead:
                self._mark_node_dead(nid, reason="heartbeat timeout")
            ticks += 1
            if ticks % 100 == 0:
                # abandoned subscribers (crashed drivers that never
                # closed) leak mailboxes: reap them periodically
                # (reference: Publisher::CheckDeadSubscribers)
                self.publisher.gc_dead_subscribers()
            if ticks % 10 == 0:
                # capacity may have appeared: retry placements on a
                # separate thread — a sweep can block on 60s create RPCs
                # and must never stall death detection. Check-and-set
                # atomically so a sweep finishing mid-check can't let
                # two sweeps run at once (RC16).
                with self._lock:
                    spawn_sweep = not self._sweep_running
                    if spawn_sweep:
                        self._sweep_running = True
                if spawn_sweep:
                    self._threads.spawn(self._sweep_thread_main,
                                        "gcs-pending-sweep")

    def _sweep_thread_main(self) -> None:
        try:
            self._retry_pending()
        except Exception:
            logger.exception("pending retry sweep failed")
        finally:
            with self._lock:
                self._sweep_running = False

    def _retry_pending(self) -> None:
        """Re-place PENDING actors and re-pack PENDING/RESCHEDULING
        placement groups — capacity appears when tasks finish, nodes
        join, or heartbeats refresh the availability view (reference:
        GcsActorManager retries pending actors on resource change)."""
        with self._lock:
            # _place_actor parks unplaceable actors (fresh or restarting)
            # back in PENDING, so PENDING is the full retry set
            actors = [a for a in self._actors.values()
                      if a.state == "PENDING"]
            pgs = [p for p in self._pgs.values()
                   if p.state in ("PENDING", "RESCHEDULING")]
        assignments = self._batch_assign_actors(actors)
        for rec in actors:
            self._place_actor(rec,
                              preferred_node=assignments.get(rec.actor_id))
        for pg in pgs:
            with self._lock:
                if pg.placing:
                    continue  # an attempt is already in flight
                pg.placing = True
            try:
                if pg.state == "PENDING":
                    placements = self._pack_bundles(pg.bundles,
                                                    pg.strategy)
                    if placements is not None and \
                            self._commit_bundles(pg, placements):
                        pg.state = "CREATED"
                        self._persist_pg(pg)
                else:  # RESCHEDULING: a previous attempt found no room
                    missing = [i for i, n in pg.placements.items()
                               if n not in self._nodes
                               or not self._nodes[n].alive]
                    if missing:
                        dead_node = pg.placements[missing[0]]
                        self._reschedule_pg(pg, dead_node)
            finally:
                pg.placing = False

    def _batch_assign_actors(self, actors) -> Dict[str, str]:
        """Vectorized placement of a pending-actor burst through the
        same policy seam the raylet tick uses: group identical demands
        into scheduling classes, solve all classes against the dense
        node matrix in one pass (fused jit solve + exact int64 repair
        above scheduler_device_solve_min_cells; numpy water-filling
        below), and hand each actor its assigned node. The per-actor
        create RPC stays the commit point — an RPC failure falls back to
        the sequential scorer with the node excluded.

        Reference seam: GcsResourceScheduler / LeastResourceScorer
        (gcs_resource_scheduler.cc:331) — replaced by the batched solve
        rather than an O(actors x nodes) python scan."""
        from ray_tpu.scheduler.policy import (
            SchedulingOptions,
            device_solve_available,
            shared_batched_policy,
        )
        from ray_tpu.scheduler.resources import to_fixed

        cfg = Config.instance()
        if len(actors) < cfg.scheduler_batch_threshold:
            return {}
        with self._lock:
            # draining nodes are alive but leaving: the batch solve
            # must not hand them fresh actors (same exclusion as
            # _pick_node / _pack_bundles)
            nodes = [(nid, dict(rec.resources), dict(rec.available))
                     for nid, rec in self._nodes.items()
                     if rec.alive and not rec.draining]
        if not nodes:
            return {}
        names = sorted({k for _, res, _ in nodes for k in res}
                       | {k for a in actors for k in a.resources})
        idx = {k: i for i, k in enumerate(names)}
        n, r = len(nodes), max(len(names), 1)
        total = np.zeros((n, r), dtype=np.int64)
        avail = np.zeros((n, r), dtype=np.int64)
        for s, (_, res, av) in enumerate(nodes):
            for k, v in res.items():
                total[s, idx[k]] = to_fixed(v)
            for k, v in av.items():
                avail[s, idx[k]] = to_fixed(v)
        classes: Dict[tuple, list] = {}
        for a in actors:
            key = tuple(sorted(a.resources.items()))
            classes.setdefault(key, []).append(a)
        class_list = list(classes.items())
        reqs = np.zeros((len(class_list), r), dtype=np.int64)
        for c, (key, _) in enumerate(class_list):
            for k, v in key:
                reqs[c, idx[k]] = to_fixed(v)
        ks = np.array([len(members) for _, members in class_list],
                      dtype=np.int64)
        opts = SchedulingOptions(
            spread_threshold=cfg.scheduler_spread_threshold)
        alive = np.ones(n, dtype=bool)
        use_device = (
            cfg.scheduler_use_vectorized_policy
            and cfg.scheduler_device_solve_min_cells >= 0
            and n * len(class_list) >= cfg.scheduler_device_solve_min_cells
            and device_solve_available())
        policy = shared_batched_policy(use_jax=use_device)
        if use_device:
            counts_dev = policy.schedule_tick_fused(
                reqs, ks, total, avail, alive, -1, opts)
            counts = policy.repair_oversubscription(
                reqs, np.asarray(counts_dev), avail)
        else:
            counts = policy.schedule_classes(
                reqs, ks, total, avail, alive, -1, opts)
        out: Dict[str, str] = {}
        for (_, members), row in zip(class_list, counts):
            it = iter(members)
            for slot in np.flatnonzero(row):
                nid = nodes[slot][0]
                for _ in range(int(row[slot])):
                    try:
                        out[next(it).actor_id] = nid
                    except StopIteration:
                        break
        return out

    def _mark_node_dead(self, node_id: str, reason: str) -> None:
        with self._lock:
            rec = self._nodes.get(node_id)
            if rec is None or not rec.alive:
                return
            rec.alive = False
            if rec.draining:
                # the drain (graceful or deadline-forced) ends here;
                # gauge updates stay inside this guard so the drain-
                # plane-off death path is untouched
                rec.draining = False
                from ray_tpu.observability import metrics

                metrics.nodes_draining.set(
                    sum(1 for r in self._nodes.values()
                        if r.alive and r.draining))
            self._change_seq += 1
            # drop every object location on the dead node
            for oid, nodes in list(self._locations.items()):
                nodes.discard(node_id)
                if not nodes:
                    del self._locations[oid]
            self._location_cv.notify_all()
            affected_actors = [a for a in self._actors.values()
                               if a.node_id == node_id
                               and a.state in ("ALIVE", "PENDING")]
            affected_pgs = [p for p in self._pgs.values()
                            if node_id in p.placements.values()
                            and p.state == "CREATED"]
            from ray_tpu.pubsub import NODE_CHANNEL

            self.publisher.publish(NODE_CHANNEL, node_id,
                                   {"alive": False, "reason": reason})
            from ray_tpu.gcs.table_storage import NODE_TABLE

            self.storage.delete(NODE_TABLE, node_id.encode())
        logger.warning("node %s declared DEAD (%s); %d actors, %d pgs "
                       "affected", node_id[:8], reason,
                       len(affected_actors), len(affected_pgs))
        for actor in affected_actors:
            try:
                self._restart_actor(actor, dead_node=node_id)
            except Exception:
                logger.exception("actor %s restart failed",
                                 actor.actor_id[:8])
        for pg in affected_pgs:
            try:
                self._reschedule_pg(pg, dead_node=node_id)
            except Exception:
                logger.exception("pg %s reschedule failed", pg.pg_id[:8])

    # ------------------------------------------------------------------- KV
    def kv_put(self, ns: str, key: bytes, value: bytes) -> dict:
        import cloudpickle

        from ray_tpu.gcs.table_storage import KV_TABLE

        with self._lock:
            self._kv[(ns, key)] = value
            # write-through under the lock: an interleaved delete must
            # not persist in the opposite order it was applied
            self.storage.put(KV_TABLE, cloudpickle.dumps((ns, key)),
                             value)
        return {"ok": True}

    def kv_get(self, ns: str, key: bytes) -> Optional[bytes]:
        with self._lock:
            return self._kv.get((ns, key))

    def kv_del(self, ns: str, key: bytes) -> dict:
        import cloudpickle

        from ray_tpu.gcs.table_storage import KV_TABLE

        with self._lock:
            deleted = self._kv.pop((ns, key), None) is not None
            self.storage.delete(KV_TABLE, cloudpickle.dumps((ns, key)))
        return {"deleted": deleted}

    def kv_keys(self, ns: str, prefix: bytes = b"") -> List[bytes]:
        with self._lock:
            return [k for (n, k) in self._kv if n == ns
                    and k.startswith(prefix)]

    # ----------------------------------------------------- object directory
    def object_add_location(self, object_id: bytes, node_id: str,
                            size: int = 0) -> dict:
        self.object_add_locations(node_id, [(object_id, size)])
        return {"ok": True}

    def object_add_locations(self, node_id: str,
                             entries: List[tuple]) -> dict:
        """Batched location re-report: one RPC for a node's whole
        resident set (used after a GCS restart — per-object RPCs inside
        the heartbeat loop would stall liveness past the death
        threshold; see round-3 advisor finding)."""
        from ray_tpu.pubsub import OBJECT_LOCATION_CHANNEL

        with self._lock:
            for object_id, size in entries:
                self._locations.setdefault(object_id, set()).add(node_id)
                if size:
                    self._object_sizes[object_id] = size
                self.publisher.publish(OBJECT_LOCATION_CHANNEL,
                                       object_id.hex(),
                                       {"node_id": node_id, "added": True,
                                        "size": size})
            self._location_cv.notify_all()
        return {"ok": True, "count": len(entries)}

    def object_remove_location(self, object_id: bytes, node_id: str) -> dict:
        from ray_tpu.pubsub import OBJECT_LOCATION_CHANNEL

        with self._lock:
            nodes = self._locations.get(object_id)
            if nodes is not None:
                nodes.discard(node_id)
                if not nodes:
                    del self._locations[object_id]
            self.publisher.publish(OBJECT_LOCATION_CHANNEL,
                                   object_id.hex(),
                                   {"node_id": node_id, "added": False})
        return {"ok": True}

    def object_locations(self, object_id: bytes) -> dict:
        with self._lock:
            nodes = [nid for nid in self._locations.get(object_id, ())
                     if self._nodes.get(nid) and self._nodes[nid].alive]
            return {
                "locations": [
                    {"node_id": nid, "address": self._nodes[nid].address}
                    for nid in nodes],
                "size": self._object_sizes.get(object_id, 0),
            }

    def object_wait_location(self, object_id: bytes,
                             timeout_s: float = 30.0) -> dict:
        """Block until at least one live location exists (the directory
        subscription of ownership_based_object_directory.cc, by polling
        condition variable instead of pubsub)."""
        deadline = time.monotonic() + timeout_s
        with self._location_cv:
            while True:
                nodes = [nid for nid in self._locations.get(object_id, ())
                         if self._nodes.get(nid) and self._nodes[nid].alive]
                if nodes:
                    return {
                        "locations": [
                            {"node_id": nid,
                             "address": self._nodes[nid].address}
                            for nid in nodes],
                        "size": self._object_sizes.get(object_id, 0),
                    }
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return {"locations": [],
                            "size": self._object_sizes.get(object_id, 0)}
                self._location_cv.wait(min(remaining, 1.0))

    # ---------------------------------------------------------------- actors
    def _pick_node(self, resources: Dict[str, float],
                   exclude: Optional[Set[str]] = None) -> Optional[str]:
        """Least-loaded feasible node (LeastResourceScorer spirit,
        gcs_resource_scheduler.cc)."""
        exclude = exclude or set()
        best, best_score = None, None
        with self._lock:
            for nid, rec in self._nodes.items():
                # draining nodes are excluded like dead ones: a fresh
                # placement there would just migrate again in seconds
                if not rec.alive or rec.draining or nid in exclude:
                    continue
                if any(rec.resources.get(k, 0.0) < v
                       for k, v in resources.items()):
                    continue
                if any(rec.available.get(k, 0.0) < v
                       for k, v in resources.items()):
                    continue
                # fraction of critical resource left after placement
                score = min(
                    (rec.available.get(k, 0.0) - v)
                    / max(rec.resources.get(k, 1.0), 1e-9)
                    for k, v in resources.items()) if resources else 1.0
                if best_score is None or score > best_score:
                    best, best_score = nid, score
        return best

    @token_deduped
    def actor_create(self, actor_id: str, cls_bytes: bytes,
                     args_bytes: bytes, resources: Dict[str, float],
                     max_restarts: int = 0, name: str = "",
                     owner: str = "") -> dict:
        rec = _ActorRecord(actor_id, cls_bytes, args_bytes, resources,
                           max_restarts, name)
        rec.owner = owner
        with self._lock:
            existing = self._actors.get(actor_id)
            if existing is not None:
                # retried create (client lost the reply): ids are
                # client-generated, so same id = same logical create —
                # dedupe instead of double-placing
                return existing.view()
            if name:
                if name in self._named_actors:
                    raise ValueError(
                        f"actor name {name!r} is already taken")
                self._named_actors[name] = actor_id
            self._actors[actor_id] = rec
            self._persist_actor(rec)
        self._place_actor(rec)
        return rec.view()

    def _place_actor(self, rec: _ActorRecord,
                     exclude: Optional[Set[str]] = None,
                     preferred_node: Optional[str] = None) -> None:
        with self._lock:
            if rec.placing:
                # another thread (creation handler vs the pending retry
                # sweep) is already placing this actor; a duplicate
                # would spawn a second process
                return
            rec.placing = True
        try:
            self._place_actor_inner(rec, exclude, preferred_node)
        finally:
            rec.placing = False

    def _place_actor_inner(self, rec: _ActorRecord,
                           exclude: Optional[Set[str]] = None,
                           preferred_node: Optional[str] = None) -> None:
        def park() -> None:
            # back to PENDING until capacity appears — but never clobber
            # a concurrent kill (DEAD is terminal)
            with self._lock:
                if rec.state != "DEAD":
                    rec.state = "PENDING"

        # preferred_node comes from the batched placement solve; the
        # create RPC below is the commit point, and on failure we fall
        # back to the per-actor scorer with the node excluded.
        node_id = preferred_node or self._pick_node(rec.resources, exclude)
        if node_id is None:
            park()
            return
        client = self._client_for_node(node_id)
        if client is None:
            park()
            return
        try:
            client.call(
                "create_actor", actor_id=rec.actor_id,
                cls_bytes=rec.cls_bytes, args_bytes=rec.args_bytes,
                resources=rec.resources, incarnation=rec.incarnation,
                timeout=60.0)
        except ActorInitError as e:
            # DETERMINISTIC creation failure (class unpickle or user
            # __init__ raised) — it would fail identically on every
            # node, so mark DEAD with the error instead of burning the
            # whole cluster's placement retries (infra failures take
            # the branch below and stay retryable)
            with self._lock:
                if rec.state != "DEAD":
                    rec.state = "DEAD"
                    rec.init_error = str(e)
                    if rec.name:
                        self._named_actors.pop(rec.name, None)
                    self._change_seq += 1
                    self._publish_actor(rec)
            logger.warning("actor %s creation failed deterministically: "
                           "%s", rec.actor_id[:8], e)
            return
        except Exception:
            # conn loss, timeout, or a raylet-side allocation race: the
            # node is unusable for this actor right now — try the next.
            # Never let an exception escape: _place_actor runs on the
            # detector thread during node-death recovery.
            self._place_actor_inner(rec, (exclude or set()) | {node_id},
                                    preferred_node=None)
            return
        with self._lock:
            if rec.state == "DEAD":
                # killed while the create RPC was in flight: never
                # resurrect — tear the fresh process back down
                reap = self._client_for_node(node_id)
            else:
                rec.node_id = node_id
                rec.state = "ALIVE"
                self._change_seq += 1
                reap = None
                # publish under the same lock hold that mutated the
                # state: a publish outside it could interleave with a
                # concurrent kill's DEAD publish and invert the order
                self._publish_actor(rec)
        if reap is not None:
            try:
                reap.call("kill_actor", actor_id=rec.actor_id,
                          timeout=10.0)
            except Exception as e:
                # the raylet's own kill/GC path reaps the orphan when
                # this teardown RPC is lost
                logger.debug("reap of killed-mid-create actor %s on %s "
                             "failed: %r", rec.actor_id[:8], node_id[:8],
                             e)

    def _restart_actor(self, rec: _ActorRecord, dead_node: str) -> None:
        """gcs_actor_manager.cc:945 ReconstructActor with max_restarts
        (:961-971): infinite when -1, else bounded."""
        with self._lock:
            if rec.state == "DEAD":
                return
            unlimited = rec.max_restarts < 0
            if not unlimited and rec.restarts_used >= rec.max_restarts:
                rec.state = "DEAD"
                self._change_seq += 1
                logger.warning("actor %s is out of restarts -> DEAD",
                               rec.actor_id[:8])
                self._publish_actor(rec)
                return
            rec.restarts_used += 1
            rec.incarnation += 1
            rec.state = "RESTARTING"
            self._change_seq += 1
            self._publish_actor(rec)
        self._place_actor(rec, exclude={dead_node})

    @token_deduped
    def report_actor_failure(self, actor_id: str) -> dict:
        """Caller-observed actor-process death (e.g. worker crash without
        node death): restart in place or elsewhere. Token-deduped — a
        duplicated report must not burn two restarts for one death."""
        with self._lock:
            rec = self._actors.get(actor_id)
            if rec is None:
                return {"ok": False}
            node = rec.node_id or ""
        self._restart_actor(rec, dead_node="")
        return {"ok": True, "prev_node": node}

    def actor_get(self, actor_id: str) -> dict:
        with self._lock:
            rec = self._actors.get(actor_id)
            if rec is None:
                raise KeyError(f"no actor {actor_id}")
            view = rec.view()
            if rec.node_id and rec.node_id in self._nodes:
                view["address"] = self._nodes[rec.node_id].address
            return view

    def actor_wait(self, actor_id: str, timeout_s: float = 30.0) -> dict:
        """Long-poll until the actor leaves PENDING/RESTARTING limbo
        (ALIVE with a node, or DEAD) or the timeout lapses — the
        wait_object pattern applied to actor state, replacing the
        client's actor_get + sleep hot-poll. Registered THREADED (never
        inline): a waiter parks a dispatch thread, not the reader."""
        deadline = time.monotonic() + timeout_s
        with self._actor_cv:
            while True:
                rec = self._actors.get(actor_id)
                if rec is None:
                    raise KeyError(f"no actor {actor_id}")
                settled = (rec.state == "DEAD"
                           or (rec.state == "ALIVE" and rec.node_id))
                remaining = deadline - time.monotonic()
                if settled or remaining <= 0:
                    view = rec.view()
                    if rec.node_id and rec.node_id in self._nodes:
                        view["address"] = self._nodes[rec.node_id].address
                    return view
                # wake periodically even without a notify: a GCS restart
                # or missed transition must not park the waiter forever
                self._actor_cv.wait(min(remaining, 1.0))

    def actor_by_name(self, name: str) -> dict:
        with self._lock:
            actor_id = self._named_actors.get(name)
        if actor_id is None:
            raise KeyError(f"no actor named {name!r}")
        return self.actor_get(actor_id)

    def actor_list(self) -> List[dict]:
        with self._lock:
            return [a.view() for a in self._actors.values()]

    @token_deduped
    def actor_kill(self, actor_id: str, no_restart: bool = True) -> dict:
        # token-deduped: a duplicated kill-with-restart must not
        # consume two restarts
        return self._actor_kill_inner(actor_id, no_restart)

    def _actor_kill_inner(self, actor_id: str, no_restart: bool) -> dict:
        with self._lock:
            rec = self._actors.get(actor_id)
            if rec is None:
                return {"ok": False}
            node_id = rec.node_id
            if no_restart:
                rec.state = "DEAD"
                if rec.name:
                    self._named_actors.pop(rec.name, None)
                self._publish_actor(rec)
        client = self._client_for_node(node_id) if node_id else None
        if client is not None:
            try:
                client.call("kill_actor", actor_id=actor_id, timeout=10.0)
            except Exception as e:
                # actor record is already DEAD; an unreachable host node
                # means the process dies with it
                logger.debug("kill_actor %s on %s failed: %r",
                             actor_id[:8], node_id[:8], e)
        if not no_restart:
            # kill-with-restart recreates the actor (consuming a restart,
            # like any other death) so the record never points at a node
            # that no longer hosts it
            self._restart_actor(rec, dead_node="")
        return {"ok": True}

    # ------------------------------------------- batched actor lifecycle
    def _parallel_each(self, name: str, items: List, fn,
                       width: int) -> None:
        """Fan ``fn(item)`` across up to WIDTH registry threads and join
        them — the parallel replacement for the serial per-record loops
        in the batch handlers. Exceptions are logged, never propagated:
        per-record outcomes are read from the records afterwards."""
        import itertools

        if not items:
            return
        if width <= 1 or len(items) == 1:
            for item in items:
                try:
                    fn(item)
                except Exception:
                    logger.exception("%s: batch entry failed", name)
            return
        counter = itertools.count()  # .__next__ is atomic in CPython

        def drain() -> None:
            while True:
                i = next(counter)
                if i >= len(items):
                    return
                try:
                    fn(items[i])
                except Exception:
                    logger.exception("%s: batch entry failed", name)

        workers = [self._threads.spawn(drain, f"{name}-{t}")
                   for t in range(min(width, len(items)))]
        # budgeted join (RC17): a worker wedged on one record's RPC
        # must not hang the whole batch handler forever
        deadline = (time.monotonic()
                    + Config.instance().batch_fanout_join_timeout_s)
        for w in workers:
            w.join(max(0.0, deadline - time.monotonic()))
            if w.is_alive():
                logger.warning("%s: worker %s still busy past join "
                               "budget", name, w.name)

    @token_deduped
    def actor_create_batch(self, creates: List[dict]) -> dict:
        """Coalesced creates: register every record under ONE lock
        hold, solve placement for the whole batch in one pass, then fan
        the create RPCs across raylets in parallel — the serial
        register->place->ack chain is what capped creation at a few
        actors per second. The reply carries one result row per input
        row (rec.view() + error), so partial failure is typed per
        actor, never a batch-wide exception. One token dedupes the
        whole frame; each row's own ``token`` dedupes that row across
        frames, so a retry after a lost ack re-runs only the rows this
        server never finished."""
        from ray_tpu.observability import metrics

        # On an exception mid-frame the reply is never acked, so leaving
        # the rows' tokens unstored is load-bearing: the sender's retry
        # must re-apply exactly the rows this pass never finished.
        # raycheck: disable=RC12 — tokens intentionally unstored on error
        replayed = self._row_tokens_resolve(creates, "actor_create_batch")
        todo = [row for i, row in enumerate(creates) if i not in replayed]
        rows_by_id: Dict[str, dict] = {}
        fresh: List[_ActorRecord] = []
        with self._lock:
            for row in todo:
                actor_id = row["actor_id"]
                existing = self._actors.get(actor_id)
                if existing is not None:
                    # retried batch row: same dedupe-by-id contract as
                    # the serial actor_create
                    rows_by_id[actor_id] = existing.view()
                    continue
                name = row.get("name", "")
                if name and name in self._named_actors:
                    rows_by_id[actor_id] = {
                        "actor_id": actor_id, "state": "ERROR",
                        "error": f"actor name {name!r} is already taken"}
                    continue
                rec = _ActorRecord(actor_id, row["cls_bytes"],
                                   row["args_bytes"],
                                   row.get("resources") or {},
                                   row.get("max_restarts", 0), name)
                rec.owner = row.get("owner", "")
                if name:
                    self._named_actors[name] = actor_id
                self._actors[actor_id] = rec
                self._persist_actor(rec)
                fresh.append(rec)
        assignments = self._batch_assign_actors(fresh)
        self._parallel_each(
            "gcs-batch-place", fresh,
            lambda rec: self._place_actor(
                rec, preferred_node=assignments.get(rec.actor_id)),
            width=Config.instance().actor_batch_fanout)
        metrics.actor_creates_batched.inc(len(creates))
        with self._lock:
            for rec in fresh:
                view = rec.view()
                if rec.init_error:
                    view["error"] = rec.init_error
                rows_by_id[rec.actor_id] = view
        results: List[dict] = []
        store: List[Tuple[str, Any]] = []
        for i, row in enumerate(creates):
            if i in replayed:
                results.append(replayed[i])
                continue
            res = rows_by_id[row["actor_id"]]
            results.append(res)
            store.append((row.get("token") or "", res))
        self._row_tokens_store(store)
        return {"results": results}

    @token_deduped
    def actor_kill_batch(self, kills: List[dict]) -> dict:
        """Coalesced kills: mark every record DEAD under ONE lock hold,
        then send each hosting raylet ONE kill_actor_batch frame (fanned
        in parallel across nodes) instead of a serial 10s-timeout RPC
        per actor — the path that took minutes to tear down a few
        thousand actors. Per-row results; one token per frame, plus a
        per-row ``token`` so a retried frame replays the rows it
        already applied instead of double-killing (a kill-with-restart
        row applied twice would consume TWO restarts)."""
        from ray_tpu.observability import metrics

        # On an exception mid-frame the reply is never acked; unstored
        # tokens make the sender's retry re-apply the unfinished rows
        # (exactly-once by re-execution).
        # raycheck: disable=RC12 — tokens intentionally unstored on error
        replayed = self._row_tokens_resolve(kills, "actor_kill_batch")
        by_node: Dict[str, List[str]] = {}
        restart_recs: List[_ActorRecord] = []
        rows_out: Dict[int, dict] = {}
        with self._lock:
            for i, row in enumerate(kills):
                if i in replayed:
                    continue
                actor_id = row["actor_id"]
                no_restart = row.get("no_restart", True)
                rec = self._actors.get(actor_id)
                if rec is None:
                    rows_out[i] = {"actor_id": actor_id, "ok": False}
                    continue
                if rec.node_id:
                    by_node.setdefault(rec.node_id, []).append(actor_id)
                if no_restart:
                    rec.state = "DEAD"
                    if rec.name:
                        self._named_actors.pop(rec.name, None)
                    self._change_seq += 1
                    self._publish_actor(rec)
                else:
                    restart_recs.append(rec)
                rows_out[i] = {"actor_id": actor_id, "ok": True}

        def kill_on_node(entry: Tuple[str, List[str]]) -> None:
            node_id, actor_ids = entry
            client = self._client_for_node(node_id)
            if client is None:
                return  # node dead: its processes die with it
            try:
                client.call("kill_actor_batch", actor_ids=actor_ids,
                            timeout=30.0)
            except Exception as e:
                # records are already DEAD; the raylet's own GC reaps
                # orphans if this teardown frame is lost
                logger.debug("kill_actor_batch on %s failed: %r",
                             node_id[:8], e)

        self._parallel_each("gcs-batch-kill", list(by_node.items()),
                            kill_on_node,
                            width=Config.instance().actor_batch_fanout)
        for rec in restart_recs:
            # kill-with-restart keeps the serial semantics: consume a
            # restart and re-place (rare path, not worth batching)
            self._restart_actor(rec, dead_node="")
        metrics.actor_kills_batched.inc(len(kills))
        results = []
        store: List[Tuple[str, Any]] = []
        for i, row in enumerate(kills):
            if i in replayed:
                results.append(replayed[i])
                continue
            results.append(rows_out[i])
            store.append((row.get("token") or "", rows_out[i]))
        self._row_tokens_store(store)
        return {"results": results}

    # -------------------------------------------------------- placement grp
    def pg_pending(self) -> dict:
        """Bundle demands of placement groups not yet placed — the
        autoscaler's PG demand feed (reference: pending PG bundles ride
        the resource reports into LoadMetrics.pending_placement_groups).
        """
        with self._lock:
            return {"pending": [[dict(b) for b in p.bundles]
                                for p in self._pgs.values()
                                if p.state == "PENDING"]}

    @token_deduped
    def pg_create(self, pg_id: str, bundles: List[Dict[str, float]],
                  strategy: str = "PACK") -> dict:
        rec = _PgRecord(pg_id, bundles, strategy)
        rec.placing = True  # registered mid-flight: sweep must not race
        with self._lock:
            existing = self._pgs.get(pg_id)
            if existing is not None:
                # retried create: dedupe by id
                return existing.view()
            self._pgs[pg_id] = rec
        try:
            placements = self._pack_bundles(bundles, strategy)
            if placements is None:
                rec.state = "PENDING"
                return rec.view()
            ok = self._commit_bundles(rec, placements)
            rec.state = "CREATED" if ok else "PENDING"
            return rec.view()
        finally:
            rec.placing = False
            self._persist_pg(rec)

    def _pack_bundles(self, bundles: List[Dict[str, float]], strategy: str,
                      exclude: Optional[Set[str]] = None
                      ) -> Optional[Dict[int, str]]:
        """Greedy scored packing over the live resource view (the
        GcsScheduleStrategy family, gcs_placement_group_scheduler.cc).
        Returns bundle_index -> node_id, or None if infeasible."""
        exclude = exclude or set()
        with self._lock:
            avail = {nid: dict(r.available) for nid, r in self._nodes.items()
                     if r.alive and not r.draining and nid not in exclude}
        placements: Dict[int, str] = {}
        order = sorted(range(len(bundles)),
                       key=lambda i: -sum(bundles[i].values()))
        for i in order:
            demand = bundles[i]
            candidates = [
                nid for nid, a in avail.items()
                if all(a.get(k, 0.0) >= v for k, v in demand.items())]
            if strategy in ("SPREAD", "STRICT_SPREAD"):
                unused = [n for n in candidates if n not in
                          placements.values()]
                if strategy == "STRICT_SPREAD":
                    candidates = unused
                elif unused:
                    candidates = unused
            elif strategy == "STRICT_PACK":
                if placements:
                    first = next(iter(placements.values()))
                    candidates = [n for n in candidates if n == first]
            else:  # PACK: prefer nodes already used
                used = [n for n in candidates if n in placements.values()]
                if used:
                    candidates = used
            if not candidates:
                return None
            # least-loaded first among candidates
            nid = max(candidates, key=lambda n: min(
                (avail[n].get(k, 0.0) - v) / max(v, 1e-9)
                for k, v in demand.items()) if demand else 0.0)
            placements[i] = nid
            for k, v in demand.items():
                avail[nid][k] = avail[nid].get(k, 0.0) - v
        return placements

    def _commit_bundles(self, rec: _PgRecord,
                        placements: Dict[int, str]) -> bool:
        """2PC against raylet processes: prepare everywhere, then commit;
        roll back prepared bundles if any prepare fails (the raylet-side
        contract of placement_group_resource_manager.h).

        Both phases are idempotent on the raylet (keyed by
        (pg_id, bundle_index)), so commits are RETRIED on transient
        failures instead of fire-and-forgotten — a dropped commit frame
        must not leave a PG marked CREATED with a bundle whose shadow
        resources never applied (lost placement). A commit that finds
        its prepare lease expired re-prepares and tries again; a commit
        that cannot land within its window rolls the whole attempt back
        (return_bundle everywhere, also idempotent) and reports failure
        so the pending sweep re-packs from a clean slate."""
        prepared: List[Tuple[int, str]] = []
        for index, node_id in placements.items():
            client = self._client_for_node(node_id)
            ok = False
            if client is not None:
                try:
                    ok = client.call(
                        "prepare_bundle", pg_id=rec.pg_id,
                        bundle_index=index, bundle=rec.bundles[index],
                        timeout=30.0)
                except Exception:
                    ok = False
            if not ok:
                self._rollback_bundles(rec, prepared)
                return False
            prepared.append((index, node_id))
        for index, node_id in placements.items():
            if not self._commit_one(rec, index, node_id):
                self._rollback_bundles(rec, list(placements.items()))
                return False
        with self._lock:
            rec.placements = dict(placements)
        return True

    def _commit_one(self, rec: _PgRecord, index: int, node_id: str,
                    window_s: float = 10.0) -> bool:
        """Land one commit_bundle, retrying through connection loss and
        re-preparing if the raylet's prepare lease expired meanwhile.
        Safe because commit is idempotent raylet-side."""
        bundle = rec.bundles[index]
        deadline = time.monotonic() + window_s
        attempt = 0
        while True:
            client = self._client_for_node(node_id)
            reply = None
            if client is not None:
                try:
                    reply = client.call(
                        "commit_bundle", pg_id=rec.pg_id,
                        bundle_index=index, bundle=bundle, timeout=10.0)
                except Exception:
                    reply = None
            if isinstance(reply, dict) and reply.get("ok", True):
                return True
            if isinstance(reply, dict) and not reply.get("ok", True):
                # prepare lease expired under us: re-reserve, then retry
                try:
                    if client is None or not client.call(
                            "prepare_bundle", pg_id=rec.pg_id,
                            bundle_index=index, bundle=bundle,
                            timeout=10.0):
                        return False  # capacity is gone: full rollback
                except Exception as e:
                    # transient: the surrounding loop re-attempts the
                    # commit until its window closes
                    logger.debug("re-prepare of %s[%d] on %s failed: "
                                 "%r", rec.pg_id[:8], index,
                                 node_id[:8], e)
            if time.monotonic() >= deadline:
                return False
            time.sleep(min(0.05 * (2 ** attempt), 1.0))
            attempt += 1

    def _rollback_bundles(self, rec: _PgRecord,
                          entries: List[Tuple[int, str]]) -> None:
        """Best-effort return of prepared/committed bundles after a
        failed 2PC attempt (idempotent raylet-side; unreachable nodes
        are backstopped by the prepare-lease expiry)."""
        for index, node_id in entries:
            client = self._client_for_node(node_id)
            if client is None:
                continue
            try:
                client.call("return_bundle", pg_id=rec.pg_id,
                            bundle_index=index,
                            bundle=rec.bundles[index],
                            committed=True, timeout=30.0)
            except Exception as e:
                # best-effort: the raylet's prepare-lease expiry
                # backstops a rollback that cannot reach the node
                logger.debug("2PC rollback of %s[%d] on %s failed: %r",
                             rec.pg_id[:8], index, node_id[:8], e)

    def _reschedule_pg(self, rec: _PgRecord, dead_node: str) -> None:
        """Bundles on a dead node move; surviving bundles stay put
        (gcs_placement_group_manager.cc node-death path). Callers other
        than the sweep (which claims rec.placing itself) run from
        _mark_node_dead, where a concurrent sweep attempt on the same PG
        is blocked by the placing flag check below."""
        with self._lock:
            if rec.placing and rec.state == "RESCHEDULING":
                return  # another reschedule is already in flight
            rec.state = "RESCHEDULING"
            lost = {i: n for i, n in rec.placements.items()
                    if n == dead_node}
        lost_sorted = sorted(lost)
        lost_bundles = [rec.bundles[i] for i in lost_sorted]
        repacked = self._pack_bundles(lost_bundles, rec.strategy,
                                      exclude={dead_node})
        if repacked is None:
            logger.warning("pg %s cannot reschedule %d bundles",
                           rec.pg_id[:8], len(lost))
            return
        # repacked is keyed by position in lost_bundles, which was built
        # from lost_sorted — map each slot back to its original index
        new_placements: Dict[int, str] = {}
        for j, i in enumerate(lost_sorted):
            new_placements[i] = repacked[j]
        sub = _PgRecord(rec.pg_id, rec.bundles, rec.strategy)
        if self._commit_bundles(sub, new_placements):
            with self._lock:
                rec.placements.update(new_placements)
                rec.state = "CREATED"
                self._change_seq += 1
            self._persist_pg(rec)

    def pg_get(self, pg_id: str) -> dict:
        with self._lock:
            rec = self._pgs.get(pg_id)
            if rec is None:
                raise KeyError(f"no placement group {pg_id}")
            return rec.view()

    @token_deduped
    def pg_remove(self, pg_id: str) -> dict:
        with self._lock:
            rec = self._pgs.pop(pg_id, None)
        if rec is None:
            return {"ok": False}
        for index, node_id in rec.placements.items():
            client = self._client_for_node(node_id)
            if client is not None:
                try:
                    client.call("return_bundle", pg_id=pg_id,
                                bundle_index=index,
                                bundle=rec.bundles[index], committed=True,
                                timeout=30.0)
                except RpcConnectionError as e:
                    # node unreachable: the prepare-lease expiry (or
                    # node death) reclaims its bundle server-side
                    logger.debug("pg_remove %s: return_bundle[%d] to "
                                 "%s failed: %r", pg_id[:8], index,
                                 node_id[:8], e)
        rec.state = "REMOVED"
        from ray_tpu.gcs.table_storage import PG_TABLE

        self.storage.delete(PG_TABLE, pg_id.encode())
        return {"ok": True}

    # ------------------------------------------------------------------ jobs
    def job_view(self) -> dict:
        from ray_tpu.observability.metrics import actors_alive

        with self._lock:
            alive_actors = sum(1 for a in self._actors.values()
                               if a.state == "ALIVE")
            actors_alive.set(alive_actors)
            return {
                "nodes": len(self._nodes),
                "alive": sum(1 for r in self._nodes.values() if r.alive),
                "actors": len(self._actors),
                "actors_alive": alive_actors,
                "objects": len(self._locations),
                "pgs": len(self._pgs),
            }


def main(argv: Optional[List[str]] = None) -> None:
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--heartbeat-period-ms", type=int, default=None)
    parser.add_argument("--num-heartbeats-timeout", type=int, default=None)
    parser.add_argument("--storage", default="",
                        help="sqlite path for durable table storage")
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    # arm the crash-dump hooks (SIGUSR2 / uncaught exception → JSONL)
    from ray_tpu.observability import flight_recorder
    flight_recorder.install()
    svc = GcsService(args.heartbeat_period_ms, args.num_heartbeats_timeout,
                     storage_path=args.storage or None)
    srv = svc.serve(args.host, args.port)
    # announce the bound port on stdout for the parent to scrape
    print(f"GCS_ADDRESS {srv.address}", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        svc.stop()


if __name__ == "__main__":
    main()
