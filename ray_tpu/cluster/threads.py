"""Named-thread registry with joined teardown.

The raylet and GCS daemons spawn a dozen background threads (heartbeat,
failure detector, dispatch loops, dereg/log flushers, retry sweeps).
They are daemonic so a crashed process still exits, but daemonic alone
means a shutdown that leaves one running produces a silent leak — the
thread keeps mutating state under a half-torn-down server and the flake
surfaces three tests later. The registry makes teardown observable:
every spawn is tracked by name, and ``join_all`` joins them under a
budget, WARN-logging any thread still alive so a hung teardown names
its culprit instead of leaking it (reference: the C++ raylet joins its
io_service threads in NodeManager shutdown; hung ones show up in the
stack dump by thread name)."""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)


def root_label(target: Callable) -> str:
    """Canonical thread-root name for TARGET: ``<module stem>.<qualname>``
    (e.g. ``raylet_server.RayletServer._heartbeat_loop``). This is THE
    root naming — raycheck's RC16/RC17 reports derive the identical
    label statically (``facts._root_label``, pinned by a test), so a
    data-race report, ``cli.py status``, and a ``perf_dump`` lane all
    name the same thread the same way."""
    # derive the module stem from the DEFINING FILE, not __module__: a
    # raylet launched as `python -m ray_tpu.cluster.raylet_server` has
    # __module__ == "__main__" for its own classes, which would break
    # label identity between in-process and subprocess nodes
    code = getattr(getattr(target, "__func__", target), "__code__", None)
    if code is not None:
        mod = code.co_filename.rsplit("/", 1)[-1].removesuffix(".py")
    else:
        mod = (getattr(target, "__module__", None) or "?").rsplit(
            ".", 1)[-1]
    qual = (getattr(target, "__qualname__", None)
            or getattr(target, "__name__", None) or repr(target))
    return f"{mod}.{qual}"


class ThreadRegistry:
    """Tracks daemon threads spawned on behalf of one owner (a raylet
    or GCS instance). Thread-safe; dead threads are pruned on spawn so
    recurring short-lived workers (retry sweeps) don't accumulate."""

    def __init__(self, owner: str):
        self.owner = owner
        self._lock = threading.Lock()
        self._threads: List[threading.Thread] = []
        # thread name -> root-function label (see root_label): the one
        # source of truth tying a live thread back to the code root
        # that raycheck RC16/RC17 and perf_dump lanes report against
        self._roots: Dict[str, str] = {}

    def spawn(self, target: Callable, name: str,
              args: Tuple = ()) -> threading.Thread:
        """Create, register, and start a named daemon thread."""
        t = threading.Thread(target=target, args=args, daemon=True,
                             name=name)
        with self._lock:
            alive = [x for x in self._threads if x.is_alive()]
            for x in self._threads:
                if not x.is_alive():
                    self._roots.pop(x.name, None)
            self._threads = alive
            self._threads.append(t)
            self._roots[name] = root_label(target)
        t.start()
        return t

    def alive(self) -> List[str]:
        with self._lock:
            return [t.name for t in self._threads if t.is_alive()]

    def roots(self) -> Dict[str, str]:
        """Live threads' ``{thread name: root-function label}`` — the
        root naming shared with raycheck's RC16/RC17 reports."""
        with self._lock:
            return {t.name: self._roots.get(t.name, "?")
                    for t in self._threads if t.is_alive()}

    def join_all(self, timeout: float = 5.0) -> List[str]:
        """Join every tracked thread within ``timeout`` total; returns
        (and WARN-logs) the names still running — a teardown flake
        surfaces as a *named* hung thread, not a leaked one."""
        deadline = time.monotonic() + timeout
        with self._lock:
            threads = list(self._threads)
        hung: List[str] = []
        for t in threads:
            if t is threading.current_thread():
                continue  # joining yourself deadlocks the teardown
            t.join(max(0.0, deadline - time.monotonic()))
            if t.is_alive():
                hung.append(t.name)
        if hung:
            logger.warning(
                "%s teardown: %d thread(s) still running after %.1fs: "
                "%s", self.owner, len(hung), timeout, ", ".join(hung))
        return hung
