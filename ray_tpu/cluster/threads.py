"""Named-thread registry with joined teardown.

The raylet and GCS daemons spawn a dozen background threads (heartbeat,
failure detector, dispatch loops, dereg/log flushers, retry sweeps).
They are daemonic so a crashed process still exits, but daemonic alone
means a shutdown that leaves one running produces a silent leak — the
thread keeps mutating state under a half-torn-down server and the flake
surfaces three tests later. The registry makes teardown observable:
every spawn is tracked by name, and ``join_all`` joins them under a
budget, WARN-logging any thread still alive so a hung teardown names
its culprit instead of leaking it (reference: the C++ raylet joins its
io_service threads in NodeManager shutdown; hung ones show up in the
stack dump by thread name)."""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, List, Optional, Tuple

logger = logging.getLogger(__name__)


class ThreadRegistry:
    """Tracks daemon threads spawned on behalf of one owner (a raylet
    or GCS instance). Thread-safe; dead threads are pruned on spawn so
    recurring short-lived workers (retry sweeps) don't accumulate."""

    def __init__(self, owner: str):
        self.owner = owner
        self._lock = threading.Lock()
        self._threads: List[threading.Thread] = []

    def spawn(self, target: Callable, name: str,
              args: Tuple = ()) -> threading.Thread:
        """Create, register, and start a named daemon thread."""
        t = threading.Thread(target=target, args=args, daemon=True,
                             name=name)
        with self._lock:
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)
        t.start()
        return t

    def alive(self) -> List[str]:
        with self._lock:
            return [t.name for t in self._threads if t.is_alive()]

    def join_all(self, timeout: float = 5.0) -> List[str]:
        """Join every tracked thread within ``timeout`` total; returns
        (and WARN-logs) the names still running — a teardown flake
        surfaces as a *named* hung thread, not a leaked one."""
        deadline = time.monotonic() + timeout
        with self._lock:
            threads = list(self._threads)
        hung: List[str] = []
        for t in threads:
            if t is threading.current_thread():
                continue  # joining yourself deadlocks the teardown
            t.join(max(0.0, deadline - time.monotonic()))
            if t.is_alive():
                hung.append(t.name)
        if hung:
            logger.warning(
                "%s teardown: %d thread(s) still running after %.1fs: "
                "%s", self.owner, len(hung), timeout, ", ".join(hung))
        return hung
