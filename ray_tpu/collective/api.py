"""Collective communication API.

Mirrors the reference's ray.util.collective surface
(util/collective/collective.py: allreduce:253, broadcast:368,
allgather:418, reducescatter:467, send:526, recv:589, barrier) with two
backends:

  - "ici": inside an SPMD region (shard_map/pjit over a Mesh), ops lower
    to XLA collectives over ICI — psum/all_gather/ppermute. This replaces
    the reference's NCCL backend (nccl_collective_group.py:127).
  - "store": between actors/processes holding host arrays, a rendezvous
    through the object store + a named synchronization actor — the moral
    equivalent of the reference's Gloo/Redis-store backend
    (gloo_collective_group.py), used off the SPMD hot path.

Group bootstrap maps to the reference's named-actor NCCLUniqueID exchange
(nccl_collective_group.py Rendezvous:28): the "store" backend rendezvouses
through a named coordinator actor exactly the same way.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import numpy as np

# --------------------------------------------------------------------------
# ICI backend: thin, axis-name-based wrappers usable inside shard_map/pjit.
# --------------------------------------------------------------------------


class ici:
    """Collectives over the ICI mesh — call inside shard_map regions."""

    @staticmethod
    def allreduce(x, axis: str = "dp", op: str = "sum"):
        import jax

        if op == "sum":
            return jax.lax.psum(x, axis)
        if op == "max":
            return jax.lax.pmax(x, axis)
        if op == "min":
            return jax.lax.pmin(x, axis)
        if op == "mean":
            return jax.lax.pmean(x, axis)
        raise ValueError(f"unsupported reduce op {op!r}")

    @staticmethod
    def allgather(x, axis: str = "dp", *, tiled: bool = False):
        import jax

        return jax.lax.all_gather(x, axis, tiled=tiled)

    @staticmethod
    def reducescatter(x, axis: str = "dp", *, scatter_dimension: int = 0):
        import jax

        return jax.lax.psum_scatter(
            x, axis, scatter_dimension=scatter_dimension, tiled=True)

    @staticmethod
    def broadcast(x, axis: str = "dp", root: int = 0):
        import jax
        import jax.numpy as jnp

        idx = jax.lax.axis_index(axis)
        gathered = jax.lax.all_gather(x, axis)
        return jnp.take(gathered, root, axis=0)

    @staticmethod
    def ring_shift(x, axis: str, shift: int = 1):
        """ppermute to the next neighbor on the ring — the primitive under
        ring attention and pipeline transfer."""
        import jax

        n = jax.lax.axis_size(axis)
        perm = [(i, (i + shift) % n) for i in range(n)]
        return jax.lax.ppermute(x, axis, perm)

    @staticmethod
    def alltoall(x, axis: str, split_axis: int, concat_axis: int):
        import jax

        return jax.lax.all_to_all(x, axis, split_axis, concat_axis,
                                  tiled=True)

    @staticmethod
    def axis_index(axis: str):
        import jax

        return jax.lax.axis_index(axis)


# --------------------------------------------------------------------------
# Store backend: CPU-tensor collectives across actors via the object store.
# --------------------------------------------------------------------------


def _coordinator_cls():
    import ray_tpu

    @ray_tpu.remote(num_cpus=0)
    class CollectiveCoordinator:
        """Named rendezvous + blackboard, one per group
        (reference: Rendezvous via named actor store,
        nccl_collective_group.py:43-100)."""

        def __init__(self, world_size: int):
            self.world_size = world_size
            self.boards: Dict[tuple, dict] = {}
            self.reads: Dict[tuple, set] = {}

        def post(self, op_id: tuple, rank: int, ref_holder: list):
            board = self.boards.setdefault(op_id, {})
            board[rank] = ref_holder[0]
            return len(board)

        def collect(self, op_id: tuple, rank: int, expected: int = -1):
            """Returns all refs once `expected` ranks have posted. The
            board is garbage-collected only after every expected rank has
            *collected* — an eager clear by the first reader would strand
            slower ranks on an empty board forever."""
            expected = self.world_size if expected < 0 else expected
            board = self.boards.get(op_id)
            if board is None or len(board) < expected:
                return None
            refs = [board[r] for r in sorted(board)]
            reads = self.reads.setdefault(op_id, set())
            reads.add(rank)
            if len(reads) >= expected:
                self.boards.pop(op_id, None)
                self.reads.pop(op_id, None)
            return refs

    return CollectiveCoordinator


_groups: Dict[str, "CollectiveGroup"] = {}
_groups_lock = threading.Lock()


class CollectiveGroup:
    def __init__(self, name: str, world_size: int, rank: int, coordinator):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self.coordinator = coordinator
        self._op_counter = 0

    def _next_op(self, kind: str) -> tuple:
        self._op_counter += 1
        return (self.name, kind, self._op_counter)

    def _exchange(self, kind: str, value) -> List[Any]:
        """Post local value, busy-wait for all ranks, return all values.

        Bounded: a peer that died before posting (e.g. its train
        function raised) must surface as an error here, not leave this
        rank polling forever (collective_op_timeout_s; the reference's
        NCCL ops have the same watchdog shape)."""
        import time

        import ray_tpu
        from ray_tpu._private.config import Config

        op_id = self._next_op(kind)
        ref = ray_tpu.put(value)
        ray_tpu.get(self.coordinator.post.remote(op_id, self.rank, [ref]))
        timeout_s = Config.instance().collective_op_timeout_s
        deadline = time.monotonic() + timeout_s
        while True:
            refs = ray_tpu.get(
                self.coordinator.collect.remote(op_id, self.rank))
            if refs is not None:
                return ray_tpu.get(list(refs))
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"collective {kind} op {op_id} on rank {self.rank} "
                    f"timed out after {timeout_s:.0f}s waiting for "
                    f"{self.world_size} rank(s) to post — a peer died "
                    "before reaching this op, or is initializing slower "
                    "than collective_op_timeout_s allows")
            time.sleep(0.001)

    # -- ops ---------------------------------------------------------------
    def allreduce(self, array, op: str = "sum"):
        values = self._exchange("allreduce", np.asarray(array))
        stacked = np.stack(values)
        if op == "sum":
            return stacked.sum(axis=0)
        if op == "mean":
            return stacked.mean(axis=0)
        if op == "max":
            return stacked.max(axis=0)
        if op == "min":
            return stacked.min(axis=0)
        raise ValueError(f"unsupported reduce op {op!r}")

    def allgather(self, array) -> List[np.ndarray]:
        return self._exchange("allgather", np.asarray(array))

    def reducescatter(self, array, op: str = "sum"):
        values = self._exchange("reducescatter", np.asarray(array))
        total = np.stack(values).sum(axis=0) if op == "sum" else None
        if total is None:
            raise ValueError(f"unsupported reduce op {op!r}")
        shards = np.array_split(total, self.world_size, axis=0)
        return shards[self.rank]

    def broadcast(self, array, root: int = 0):
        values = self._exchange("broadcast", np.asarray(array))
        return values[root]

    def barrier(self) -> None:
        self._exchange("barrier", 0)

    def _next_p2p(self, src: int, dst: int) -> tuple:
        # per-channel counters so send/recv pair up even when the two
        # ranks' overall op sequences differ
        if not hasattr(self, "_p2p_counters"):
            self._p2p_counters: Dict[tuple, int] = {}
        key = (src, dst)
        n = self._p2p_counters.get(key, 0)
        self._p2p_counters[key] = n + 1
        return (self.name, "p2p", src, dst, n)

    def send(self, array, dst_rank: int) -> None:
        import ray_tpu

        op_id = self._next_p2p(self.rank, dst_rank)
        ref = ray_tpu.put(np.asarray(array))
        ray_tpu.get(self.coordinator.post.remote(op_id, 0, [ref]))

    def recv(self, src_rank: int):
        import time

        import ray_tpu
        from ray_tpu._private.config import Config

        op_id = self._next_p2p(src_rank, self.rank)
        timeout_s = Config.instance().collective_op_timeout_s
        deadline = time.monotonic() + timeout_s
        while True:
            refs = ray_tpu.get(
                self.coordinator.collect.remote(op_id, 0, 1))
            if refs is not None:
                return ray_tpu.get(refs[0])
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"collective recv from rank {src_rank} on rank "
                    f"{self.rank} timed out after {timeout_s:.0f}s — "
                    "no matching send arrived")
            time.sleep(0.001)


def init_collective_group(world_size: int, rank: int,
                          group_name: str = "default") -> CollectiveGroup:
    """Join (rank 0 creates) a named store-backend group
    (reference: util/collective/collective.py init_collective_group)."""
    import ray_tpu

    coordinator_name = f"__collective_{group_name}"
    cls = _coordinator_cls()
    coordinator = cls.options(
        name=coordinator_name, get_if_exists=True,
        lifetime="detached").remote(world_size)
    # p2p ops need a dedicated world_size=1 view; coordinator handles all
    group = CollectiveGroup(group_name, world_size, rank, coordinator)
    with _groups_lock:
        _groups[(group_name, rank)] = group
    return group


def get_group(group_name: str = "default", rank: int = 0) -> CollectiveGroup:
    with _groups_lock:
        group = _groups.get((group_name, rank))
    if group is None:
        raise ValueError(f"collective group {group_name!r} not initialized")
    return group


def destroy_collective_group(group_name: str = "default") -> None:
    import ray_tpu

    coordinator = None
    with _groups_lock:
        for key in [k for k in _groups if k[0] == group_name]:
            group = _groups.pop(key)
            coordinator = group.coordinator
    if coordinator is None:
        try:
            coordinator = ray_tpu.get_actor(f"__collective_{group_name}")
        except Exception:  # noqa: BLE001
            return
    # kill the detached coordinator so a re-init with the same name gets a
    # fresh world_size instead of the stale detached actor
    try:
        ray_tpu.kill(coordinator)
    except Exception:  # noqa: BLE001
        pass
