"""Durable KV for the Serve control plane.

Reference: python/ray/serve/storage/kv_store.py — the controller
checkpoints its goal state through a pluggable KV (GCS internal KV by
default, S3/local alternatives) and recovers it on restart. Here the
default backend is the runtime's internal KV, which lives in the
Runtime/GCS — NOT in the controller actor — so it survives controller
death; a filesystem backend covers fully-external durability."""

from __future__ import annotations

import os
from typing import List, Optional

_NS = "serve"


class KVStore:
    """Runtime-internal KV, namespaced (reference: RayInternalKVStore)."""

    def __init__(self, namespace: str = _NS):
        self._ns = namespace

    def _rt(self):
        from ray_tpu.core import runtime as rt_mod

        rt = rt_mod.global_runtime
        if rt is None or rt.is_shutdown:
            raise RuntimeError("runtime not initialized")
        return rt

    def put(self, key: bytes, value: bytes) -> None:
        self._rt().kv_put(self._ns, bytes(key), bytes(value))

    def get(self, key: bytes) -> Optional[bytes]:
        return self._rt().kv_get(self._ns, bytes(key))

    def delete(self, key: bytes) -> None:
        self._rt().kv_del(self._ns, bytes(key))

    def keys(self, prefix: bytes = b"") -> List[bytes]:
        return self._rt().kv_keys(self._ns, prefix)


class LocalDiskKVStore:
    """Filesystem-backed KV (reference: serve/storage/kv_store.py
    RayLocalKVStore) — survives whole-cluster restarts."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key: bytes) -> str:
        return os.path.join(self.root, key.hex())

    def put(self, key: bytes, value: bytes) -> None:
        tmp = self._path(key) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(value)
        os.replace(tmp, self._path(key))

    def get(self, key: bytes) -> Optional[bytes]:
        try:
            with open(self._path(key), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def delete(self, key: bytes) -> None:
        try:
            os.unlink(self._path(key))
        except FileNotFoundError:  # raycheck: disable=RC05 — delete is idempotent; a missing file is the already-deleted success case
            pass

    def keys(self, prefix: bytes = b"") -> List[bytes]:
        out = []
        for name in os.listdir(self.root):
            if name.endswith(".tmp"):
                continue
            try:
                key = bytes.fromhex(name)
            except ValueError:
                continue
            if key.startswith(prefix):
                out.append(key)
        return out
