"""Serve controller — the singleton control-plane actor.

Reference: python/ray/serve/controller.py + deployment_state.py: owns the
goal state of every deployment, reconciles replica actor sets (scale
up/down, rolling updates on version change), and runs the autoscaling
loop on replica queue metrics (serve/autoscaling_policy.py).

Resilience plane (this repo's serve hardening, reference:
deployment_state.py health-check/drain machinery):

- A health-probe loop calls each replica's cheap ``check_health()``
  every ``health_check_period_s``; a timeout or falsy reply counts as a
  failure, and ``health_check_failure_threshold`` CONSECUTIVE failures
  mark the replica unhealthy — it is removed from routing (membership
  version bump), drained, killed, and replaced by the reconcile loop.
  This is DISTINCT from actor death: a wedged-but-alive replica (stuck
  lock, poisoned state) fails probes while still holding its actor slot.
- Every replica stop — scale-down, rolling update, unhealthy
  replacement, deletion — goes through the graceful drain: routing
  stops first (membership bump), the replica sheds new work after the
  grace window, and the controller polls in-flight down to zero for up
  to ``graceful_shutdown_timeout_s`` before the kill. A calm rolling
  update therefore drops zero in-flight requests.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import ray_tpu
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig
from ray_tpu.serve.replica import ReplicaActor

logger = logging.getLogger(__name__)

AUTOSCALE_INTERVAL_S = 0.25
HEALTH_TICK_S = 0.05


CHECKPOINT_KEY = b"controller-checkpoint"


@dataclass
class DeploymentState:
    name: str
    func_or_class: Any
    config: DeploymentConfig
    init_args: tuple
    init_kwargs: dict
    version: Optional[str]
    route_prefix: Optional[str]
    replicas: List[Any] = field(default_factory=list)   # actor handles
    replica_names: List[str] = field(default_factory=list)
    replica_versions: List[Optional[str]] = field(default_factory=list)
    target_replicas: int = 1
    membership_version: int = 0
    # consecutive health-probe failures per replica name; a name crossing
    # the deployment's threshold is drained and replaced
    health_failures: Dict[str, int] = field(default_factory=dict)
    last_probe: float = 0.0


class ServeController:
    """Singleton control-plane actor. FAULT-TOLERANT: every goal-state
    mutation checkpoints to the runtime KV (which lives outside this
    actor), and __init__ recovers from the checkpoint — re-attaching
    still-live replica actors by their stable names and restarting the
    rest — so controller death loses no deployments (reference:
    serve/controller.py checkpoints via serve/storage/kv_store.py and
    deployment_state.py recovers replica actors by name)."""

    def __init__(self, http_options: Optional[dict] = None):
        from ray_tpu.serve.kv_store import KVStore

        self._deployments: Dict[str, DeploymentState] = {}
        self._lock = threading.RLock()
        self._http_options = http_options or {}
        self._stopped = False
        self._kv = KVStore()
        self._recover_from_checkpoint()
        self._autoscale_thread = threading.Thread(
            target=self._autoscale_loop, daemon=True)
        self._autoscale_thread.start()
        self._health_thread = threading.Thread(
            target=self._health_loop, daemon=True)
        self._health_thread.start()

    def ready(self) -> bool:
        return True

    # -------------------------------------------------- checkpoint/recover
    def _checkpoint(self) -> None:
        """Persist goal state + replica names (NOT handles — those die
        with their owner; names re-resolve). Called under self._lock
        after every mutation."""
        import cloudpickle

        data = {}
        for name, s in self._deployments.items():
            try:
                func_bytes = cloudpickle.dumps(s.func_or_class)
            except Exception:
                # an unpicklable deployable (e.g. a wrapper capturing a
                # lock) cannot survive a controller failover; keep it
                # serving now and keep every OTHER deployment durable
                logger.warning(
                    "deployment %r is not picklable and will not "
                    "survive controller failover", name)
                continue
            data[name] = {
                "func_or_class": func_bytes,
                "config": s.config,
                "init_args": s.init_args,
                "init_kwargs": s.init_kwargs,
                "version": s.version,
                "route_prefix": s.route_prefix,
                "target_replicas": s.target_replicas,
                "replica_names": list(s.replica_names),
                "replica_versions": list(s.replica_versions),
                "membership_version": s.membership_version,
            }
        self._kv.put(CHECKPOINT_KEY, cloudpickle.dumps(data))

    def _recover_from_checkpoint(self) -> None:
        import cloudpickle

        try:
            raw = self._kv.get(CHECKPOINT_KEY)
        except RuntimeError:
            return  # no runtime (unit-test construction): cold start
        if raw is None:
            return
        data = cloudpickle.loads(raw)
        with self._lock:
            for name, d in data.items():
                state = DeploymentState(
                    name, cloudpickle.loads(d["func_or_class"]),
                    d["config"], d["init_args"], d["init_kwargs"],
                    d["version"], d["route_prefix"])
                state.target_replicas = d["target_replicas"]
                # bump so routers holding the old version re-fetch
                state.membership_version = d["membership_version"] + 1
                for rname, rver in zip(d["replica_names"],
                                       d["replica_versions"]):
                    try:  # re-attach replicas that survived us
                        h = ray_tpu.get_actor(rname)
                        ray_tpu.get(h.ready.remote())
                    except Exception:
                        continue
                    state.replicas.append(h)
                    state.replica_names.append(rname)
                    state.replica_versions.append(rver)
                self._deployments[name] = state
                self._reconcile(state)  # start whatever is missing
            self._checkpoint()

    # ------------------------------------------------------------- deploy
    def deploy(self, name: str, func_or_class, config: DeploymentConfig,
               init_args: tuple, init_kwargs: dict,
               version: Optional[str], route_prefix: Optional[str]) -> bool:
        with self._lock:
            state = self._deployments.get(name)
            rolling = (state is not None and
                       (state.version != version or version is None))
            if state is None:
                state = DeploymentState(
                    name, func_or_class, config, init_args, init_kwargs,
                    version, route_prefix)
                self._deployments[name] = state
            else:
                state.func_or_class = func_or_class
                state.config = config
                state.init_args = init_args
                state.init_kwargs = init_kwargs
                state.version = version
                state.route_prefix = route_prefix
            if config.autoscaling_config is not None:
                state.target_replicas = max(
                    config.autoscaling_config.min_replicas,
                    min(state.target_replicas or 1,
                        config.autoscaling_config.max_replicas))
            else:
                state.target_replicas = config.num_replicas
            stops = self._reconcile(state, rolling_update=rolling)
            self._checkpoint()
            timeout_s = config.graceful_shutdown_timeout_s
        # drains happen OUTSIDE the lock: routing already moved to the
        # new membership, and a drain wait must not block other
        # control-plane calls (deploys, router refreshes)
        self._finalize_stops(stops, timeout_s)
        return True

    def _start_replica(self, state: DeploymentState):
        import uuid

        opts = dict(state.config.ray_actor_options)
        # Replicas admit up to max_concurrent_queries in-flight requests
        # (reference: replicas are async actors; backpressure above that
        # cap is the router's job).
        opts.setdefault("max_concurrency",
                        state.config.max_concurrent_queries)
        # stable name => a restarted controller can re-attach the live
        # replica instead of restarting it (reference: deployment_state
        # recovers replicas by actor name)
        name = f"SERVE_REPLICA::{state.name}::{uuid.uuid4().hex[:8]}"
        opts["name"] = name
        replica = ray_tpu.remote(ReplicaActor).options(**opts).remote(
            state.func_or_class, state.init_args, state.init_kwargs,
            state.config.user_config,
            deployment_name=state.name, replica_tag=name)
        ray_tpu.get(replica.ready.remote())
        return replica, name

    def _reconcile(self, state: DeploymentState,
                   rolling_update: bool = False) -> List[Tuple[Any, str]]:
        """Drive the replica set to the target (reference:
        deployment_state.py _scale_deployment_replicas + rolling update).

        Called under self._lock. Replicas leaving the set are removed
        from routing HERE (membership bump) and returned as
        ``(handle, name)`` stops for the caller to gracefully drain
        outside the lock."""
        stops: List[Tuple[Any, str]] = []
        if rolling_update:
            # Start the full new set before the old stops serving, then
            # swap membership atomically: routing moves to the new
            # replicas in one version bump and the old set drains.
            old = list(zip(state.replicas, state.replica_names))
            new_replicas, new_names = [], []
            for _ in range(state.target_replicas):
                replica, name = self._start_replica(state)
                new_replicas.append(replica)
                new_names.append(name)
            state.replicas = new_replicas
            state.replica_names = new_names
            state.replica_versions = [state.version] * len(new_replicas)
            state.health_failures = {}
            state.membership_version += 1
            stops.extend(old)
            return stops
        while len(state.replicas) < state.target_replicas:
            replica, name = self._start_replica(state)
            state.replicas.append(replica)
            state.replica_names.append(name)
            state.replica_versions.append(state.version)
            state.membership_version += 1
        while len(state.replicas) > state.target_replicas:
            victim = state.replicas.pop()
            victim_name = state.replica_names.pop()
            state.replica_versions.pop()
            state.health_failures.pop(victim_name, None)
            state.membership_version += 1
            stops.append((victim, victim_name))
        return stops

    # --------------------------------------------------------------- drains
    def _finalize_stops(self, stops: List[Tuple[Any, str]],
                        timeout_s: float) -> None:
        """Gracefully stop replicas already removed from routing: ask
        each to drain (shed new work after the grace window), poll
        in-flight down to zero for up to ``timeout_s``, then kill.
        With the resilience plane off, this is the legacy immediate
        kill."""
        if not stops:
            return
        from ray_tpu._private.config import Config

        cfg = Config.instance()
        if not cfg.serve_resilience_enabled:
            for replica, _ in stops:
                ray_tpu.kill(replica)
            return
        from ray_tpu.observability.metrics import serve_drains_completed

        grace = cfg.serve_drain_grace_s
        for replica, name in stops:
            drained = False
            try:
                ray_tpu.get(replica.drain.remote(grace), timeout=5.0)
                deadline = time.monotonic() + max(0.0, timeout_s)
                while time.monotonic() < deadline:
                    ongoing = ray_tpu.get(replica.num_ongoing.remote(),
                                          timeout=5.0)
                    if ongoing == 0:
                        drained = True
                        break
                    time.sleep(0.02)
            except Exception as e:
                # a dead/wedged replica cannot drain; the kill below is
                # the backstop either way
                logger.debug("drain of replica %s failed: %r", name, e)
            if drained:
                serve_drains_completed.inc()
            else:
                logger.warning(
                    "replica %s still had in-flight requests after "
                    "%.1fs graceful window; killing", name, timeout_s)
            ray_tpu.kill(replica)

    def delete_deployment(self, name: str) -> bool:
        with self._lock:
            state = self._deployments.pop(name, None)
            if state is not None:
                self._checkpoint()
        if state is None:
            return False
        self._finalize_stops(
            list(zip(state.replicas, state.replica_names)),
            state.config.graceful_shutdown_timeout_s)
        return True

    # -------------------------------------------------------------- reads
    def list_deployments(self) -> List[str]:
        with self._lock:
            return list(self._deployments.keys())

    def get_deployment_info(self, name: str):
        with self._lock:
            s = self._deployments.get(name)
            if s is None:
                return None
            return (s.func_or_class, s.config, s.init_args, s.init_kwargs,
                    s.version, s.route_prefix)

    def get_replicas(self, name: str) -> Tuple[int, List[Any]]:
        """Router membership fetch: (membership_version, handles).
        Reference: serve/long_poll.py — routers re-fetch when the version
        they hold goes stale."""
        with self._lock:
            s = self._deployments.get(name)
            if s is None:
                return -1, []
            return s.membership_version, list(s.replicas)

    def get_membership(self, name: str) -> Tuple[int, List[Any], int]:
        """Router fetch with routing config in one round trip:
        (membership_version, handles, max_concurrent_queries)."""
        with self._lock:
            s = self._deployments.get(name)
            if s is None:
                return -1, [], 100
            return (s.membership_version, list(s.replicas),
                    s.config.max_concurrent_queries)

    def get_membership_version(self, name: str) -> int:
        with self._lock:
            s = self._deployments.get(name)
            return -1 if s is None else s.membership_version

    def get_routes(self) -> Dict[str, str]:
        with self._lock:
            return {s.route_prefix: name
                    for name, s in self._deployments.items()
                    if s.route_prefix}

    # ------------------------------------------------------ health probing
    def _health_loop(self) -> None:
        """Probe every replica's check_health() on its deployment's
        period; threshold consecutive failures => drain + replace
        (reference: deployment_state.py check_health loop)."""
        from ray_tpu._private.config import Config

        while not self._stopped:
            time.sleep(HEALTH_TICK_S)
            if not Config.instance().serve_resilience_enabled:
                continue
            try:
                self._probe_due_deployments()
            except Exception as e:  # keep the loop alive
                logger.debug("health-probe tick failed: %r", e)

    def _probe_due_deployments(self) -> None:
        now = time.monotonic()
        with self._lock:
            due = []
            for s in self._deployments.values():
                period, timeout, threshold = \
                    s.config.resolved_health_check()
                if now - s.last_probe >= period:
                    s.last_probe = now
                    due.append((s, timeout, threshold,
                                list(zip(s.replicas, s.replica_names))))
        for state, timeout, threshold, members in due:
            self._probe_deployment(state, timeout, threshold, members)

    def _probe_deployment(self, state: DeploymentState, timeout: float,
                          threshold: int, members) -> None:
        unhealthy: List[str] = []
        for replica, name in members:
            healthy = False
            try:
                healthy = bool(ray_tpu.get(replica.check_health.remote(),
                                           timeout=timeout))
            except Exception as e:
                # dead actor, wedged executor, or probe timeout — all
                # count against the threshold
                logger.debug("health probe of %s raised: %r", name, e)
            with self._lock:
                if name not in state.replica_names:
                    continue  # already removed (scale-down raced us)
                if healthy:
                    state.health_failures.pop(name, None)
                    continue
                fails = state.health_failures.get(name, 0) + 1
                state.health_failures[name] = fails
                if fails >= threshold:
                    unhealthy.append(name)
        for name in unhealthy:
            self._replace_unhealthy_replica(state, name)

    def _replace_unhealthy_replica(self, state: DeploymentState,
                                   name: str) -> None:
        from ray_tpu.observability.metrics import serve_replicas_unhealthy

        with self._lock:
            if name not in state.replica_names:
                return
            idx = state.replica_names.index(name)
            replica = state.replicas.pop(idx)
            state.replica_names.pop(idx)
            state.replica_versions.pop(idx)
            state.health_failures.pop(name, None)
            state.membership_version += 1
        serve_replicas_unhealthy.inc()
        logger.warning(
            "replica %s of %s failed %d consecutive health probes; "
            "draining and replacing", name, state.name,
            state.config.resolved_health_check()[2])
        # a SHORT drain window: the replica is unhealthy, so in-flight
        # work there is already suspect — give it one grace period, not
        # the full graceful_shutdown_timeout_s
        self._finalize_stops(
            [(replica, name)],
            min(1.0, state.config.graceful_shutdown_timeout_s))
        with self._lock:
            if state.name not in self._deployments:
                return  # deleted while we drained
            stops = self._reconcile(state)  # start the replacement
            self._checkpoint()
            timeout_s = state.config.graceful_shutdown_timeout_s
        self._finalize_stops(stops, timeout_s)

    # --------------------------------------------------------- autoscaling
    def _autoscale_loop(self) -> None:
        while not self._stopped:
            time.sleep(AUTOSCALE_INTERVAL_S)
            try:
                self._autoscale_once()
            except Exception as e:  # noqa: BLE001 — keep the loop alive
                logger.debug("autoscale tick failed: %r", e)

    def _autoscale_once(self) -> None:
        with self._lock:
            states = [s for s in self._deployments.values()
                      if s.config.autoscaling_config is not None]
        for state in states:
            cfg: AutoscalingConfig = state.config.autoscaling_config
            metrics = ray_tpu.get(
                [r.metrics.remote() for r in list(state.replicas)])
            total_ongoing = sum(m["ongoing"] for m in metrics)
            n = max(len(state.replicas), 1)
            desired = total_ongoing / cfg.target_num_ongoing_requests_per_replica
            desired = n + cfg.smoothing_factor * (desired - n)
            import math

            target = int(min(cfg.max_replicas,
                             max(cfg.min_replicas, math.ceil(desired))))
            stops: List[Tuple[Any, str]] = []
            with self._lock:
                if target != state.target_replicas:
                    state.target_replicas = target
                    stops = self._reconcile(state)
                    self._checkpoint()
            self._finalize_stops(
                stops, state.config.graceful_shutdown_timeout_s)

    def shutdown(self) -> None:
        self._stopped = True
        with self._lock:
            names = list(self._deployments.keys())
        for n in names:
            self.delete_deployment(n)
        try:  # a CLEAN shutdown clears the checkpoint; a crash leaves
            # it for the next controller to recover from
            self._kv.delete(CHECKPOINT_KEY)
        except RuntimeError as e:
            logger.debug("could not clear controller checkpoint at "
                         "shutdown (runtime already gone): %r", e)
