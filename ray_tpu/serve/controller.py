"""Serve controller — the singleton control-plane actor.

Reference: python/ray/serve/controller.py + deployment_state.py: owns the
goal state of every deployment, reconciles replica actor sets (scale
up/down, rolling updates on version change), and runs the autoscaling
loop on replica queue metrics (serve/autoscaling_policy.py).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import ray_tpu
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig
from ray_tpu.serve.replica import ReplicaActor

AUTOSCALE_INTERVAL_S = 0.25


CHECKPOINT_KEY = b"controller-checkpoint"


@dataclass
class DeploymentState:
    name: str
    func_or_class: Any
    config: DeploymentConfig
    init_args: tuple
    init_kwargs: dict
    version: Optional[str]
    route_prefix: Optional[str]
    replicas: List[Any] = field(default_factory=list)   # actor handles
    replica_names: List[str] = field(default_factory=list)
    replica_versions: List[Optional[str]] = field(default_factory=list)
    target_replicas: int = 1
    membership_version: int = 0


class ServeController:
    """Singleton control-plane actor. FAULT-TOLERANT: every goal-state
    mutation checkpoints to the runtime KV (which lives outside this
    actor), and __init__ recovers from the checkpoint — re-attaching
    still-live replica actors by their stable names and restarting the
    rest — so controller death loses no deployments (reference:
    serve/controller.py checkpoints via serve/storage/kv_store.py and
    deployment_state.py recovers replica actors by name)."""

    def __init__(self, http_options: Optional[dict] = None):
        from ray_tpu.serve.kv_store import KVStore

        self._deployments: Dict[str, DeploymentState] = {}
        self._lock = threading.RLock()
        self._http_options = http_options or {}
        self._stopped = False
        self._kv = KVStore()
        self._recover_from_checkpoint()
        self._autoscale_thread = threading.Thread(
            target=self._autoscale_loop, daemon=True)
        self._autoscale_thread.start()

    def ready(self) -> bool:
        return True

    # -------------------------------------------------- checkpoint/recover
    def _checkpoint(self) -> None:
        """Persist goal state + replica names (NOT handles — those die
        with their owner; names re-resolve). Called under self._lock
        after every mutation."""
        import cloudpickle

        data = {}
        for name, s in self._deployments.items():
            try:
                func_bytes = cloudpickle.dumps(s.func_or_class)
            except Exception:
                # an unpicklable deployable (e.g. a wrapper capturing a
                # lock) cannot survive a controller failover; keep it
                # serving now and keep every OTHER deployment durable
                import logging

                logging.getLogger(__name__).warning(
                    "deployment %r is not picklable and will not "
                    "survive controller failover", name)
                continue
            data[name] = {
                "func_or_class": func_bytes,
                "config": s.config,
                "init_args": s.init_args,
                "init_kwargs": s.init_kwargs,
                "version": s.version,
                "route_prefix": s.route_prefix,
                "target_replicas": s.target_replicas,
                "replica_names": list(s.replica_names),
                "replica_versions": list(s.replica_versions),
                "membership_version": s.membership_version,
            }
        self._kv.put(CHECKPOINT_KEY, cloudpickle.dumps(data))

    def _recover_from_checkpoint(self) -> None:
        import cloudpickle

        try:
            raw = self._kv.get(CHECKPOINT_KEY)
        except RuntimeError:
            return  # no runtime (unit-test construction): cold start
        if raw is None:
            return
        data = cloudpickle.loads(raw)
        with self._lock:
            for name, d in data.items():
                state = DeploymentState(
                    name, cloudpickle.loads(d["func_or_class"]),
                    d["config"], d["init_args"], d["init_kwargs"],
                    d["version"], d["route_prefix"])
                state.target_replicas = d["target_replicas"]
                # bump so routers holding the old version re-fetch
                state.membership_version = d["membership_version"] + 1
                for rname, rver in zip(d["replica_names"],
                                       d["replica_versions"]):
                    try:  # re-attach replicas that survived us
                        h = ray_tpu.get_actor(rname)
                        ray_tpu.get(h.ready.remote())
                    except Exception:
                        continue
                    state.replicas.append(h)
                    state.replica_names.append(rname)
                    state.replica_versions.append(rver)
                self._deployments[name] = state
                self._reconcile(state)  # start whatever is missing
            self._checkpoint()

    # ------------------------------------------------------------- deploy
    def deploy(self, name: str, func_or_class, config: DeploymentConfig,
               init_args: tuple, init_kwargs: dict,
               version: Optional[str], route_prefix: Optional[str]) -> bool:
        with self._lock:
            state = self._deployments.get(name)
            rolling = (state is not None and
                       (state.version != version or version is None))
            if state is None:
                state = DeploymentState(
                    name, func_or_class, config, init_args, init_kwargs,
                    version, route_prefix)
                self._deployments[name] = state
            else:
                state.func_or_class = func_or_class
                state.config = config
                state.init_args = init_args
                state.init_kwargs = init_kwargs
                state.version = version
                state.route_prefix = route_prefix
            if config.autoscaling_config is not None:
                state.target_replicas = max(
                    config.autoscaling_config.min_replicas,
                    min(state.target_replicas or 1,
                        config.autoscaling_config.max_replicas))
            else:
                state.target_replicas = config.num_replicas
            self._reconcile(state, rolling_update=rolling)
            self._checkpoint()
        return True

    def _start_replica(self, state: DeploymentState):
        import uuid

        opts = dict(state.config.ray_actor_options)
        # Replicas admit up to max_concurrent_queries in-flight requests
        # (reference: replicas are async actors; backpressure above that
        # cap is the router's job).
        opts.setdefault("max_concurrency",
                        state.config.max_concurrent_queries)
        # stable name => a restarted controller can re-attach the live
        # replica instead of restarting it (reference: deployment_state
        # recovers replicas by actor name)
        name = f"SERVE_REPLICA::{state.name}::{uuid.uuid4().hex[:8]}"
        opts["name"] = name
        replica = ray_tpu.remote(ReplicaActor).options(**opts).remote(
            state.func_or_class, state.init_args, state.init_kwargs,
            state.config.user_config)
        ray_tpu.get(replica.ready.remote())
        return replica, name

    def _reconcile(self, state: DeploymentState,
                   rolling_update: bool = False) -> None:
        """Drive the replica set to the target (reference:
        deployment_state.py _scale_deployment_replicas + rolling update)."""
        if rolling_update:
            # Replace replicas one at a time: start new before stopping old
            # so capacity never drops below target-1.
            old = list(state.replicas)
            new_replicas, new_names = [], []
            for _ in range(state.target_replicas):
                replica, name = self._start_replica(state)
                new_replicas.append(replica)
                new_names.append(name)
            state.replicas = new_replicas
            state.replica_names = new_names
            state.replica_versions = [state.version] * len(new_replicas)
            state.membership_version += 1
            for r in old:
                ray_tpu.kill(r)
            return
        while len(state.replicas) < state.target_replicas:
            replica, name = self._start_replica(state)
            state.replicas.append(replica)
            state.replica_names.append(name)
            state.replica_versions.append(state.version)
            state.membership_version += 1
        while len(state.replicas) > state.target_replicas:
            victim = state.replicas.pop()
            state.replica_names.pop()
            state.replica_versions.pop()
            state.membership_version += 1
            ray_tpu.kill(victim)

    def delete_deployment(self, name: str) -> bool:
        with self._lock:
            state = self._deployments.pop(name, None)
            if state is not None:
                self._checkpoint()
        if state is None:
            return False
        for r in state.replicas:
            ray_tpu.kill(r)
        return True

    # -------------------------------------------------------------- reads
    def list_deployments(self) -> List[str]:
        with self._lock:
            return list(self._deployments.keys())

    def get_deployment_info(self, name: str):
        with self._lock:
            s = self._deployments.get(name)
            if s is None:
                return None
            return (s.func_or_class, s.config, s.init_args, s.init_kwargs,
                    s.version, s.route_prefix)

    def get_replicas(self, name: str) -> Tuple[int, List[Any]]:
        """Router membership fetch: (membership_version, handles).
        Reference: serve/long_poll.py — routers re-fetch when the version
        they hold goes stale."""
        with self._lock:
            s = self._deployments.get(name)
            if s is None:
                return -1, []
            return s.membership_version, list(s.replicas)

    def get_membership_version(self, name: str) -> int:
        with self._lock:
            s = self._deployments.get(name)
            return -1 if s is None else s.membership_version

    def get_routes(self) -> Dict[str, str]:
        with self._lock:
            return {s.route_prefix: name
                    for name, s in self._deployments.items()
                    if s.route_prefix}

    # --------------------------------------------------------- autoscaling
    def _autoscale_loop(self) -> None:
        while not self._stopped:
            time.sleep(AUTOSCALE_INTERVAL_S)
            try:
                self._autoscale_once()
            except Exception:  # noqa: BLE001 — keep the loop alive
                pass

    def _autoscale_once(self) -> None:
        with self._lock:
            states = [s for s in self._deployments.values()
                      if s.config.autoscaling_config is not None]
        for state in states:
            cfg: AutoscalingConfig = state.config.autoscaling_config
            metrics = ray_tpu.get(
                [r.metrics.remote() for r in list(state.replicas)])
            total_ongoing = sum(m["ongoing"] for m in metrics)
            n = max(len(state.replicas), 1)
            desired = total_ongoing / cfg.target_num_ongoing_requests_per_replica
            desired = n + cfg.smoothing_factor * (desired - n)
            import math

            target = int(min(cfg.max_replicas,
                             max(cfg.min_replicas, math.ceil(desired))))
            with self._lock:
                if target != state.target_replicas:
                    state.target_replicas = target
                    self._reconcile(state)
                    self._checkpoint()

    def shutdown(self) -> None:
        self._stopped = True
        with self._lock:
            names = list(self._deployments.keys())
        for n in names:
            self.delete_deployment(n)
        try:  # a CLEAN shutdown clears the checkpoint; a crash leaves
            # it for the next controller to recover from
            self._kv.delete(CHECKPOINT_KEY)
        except RuntimeError:
            pass
