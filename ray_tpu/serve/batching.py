"""@serve.batch — transparent request batching.

Reference: python/ray/serve/batching.py: calls to the wrapped coroutine
are buffered until max_batch_size requests arrive or batch_wait_timeout_s
elapses, then the underlying function runs once on the list of requests.
This is the TPU-relevant primitive: inference batches need to be large
and static-shaped to hit the MXU, so the batcher is where request-level
traffic turns into device-sized batches.
"""

from __future__ import annotations

import functools
import threading
from concurrent.futures import Future
from typing import Any, Callable, List, Optional


class _Batcher:
    def __init__(self, fn: Callable[[List[Any]], List[Any]],
                 max_batch_size: int, batch_wait_timeout_s: float):
        self._fn = fn
        self._max = max_batch_size
        self._timeout = batch_wait_timeout_s
        self._queue: List[tuple] = []
        self._lock = threading.Lock()
        self._timer: Optional[threading.Timer] = None

    def submit(self, instance, item: Any) -> Future:
        fut: Future = Future()
        flush_now = False
        with self._lock:
            self._queue.append((instance, item, fut))
            if len(self._queue) >= self._max:
                flush_now = True
            elif self._timer is None:
                self._timer = threading.Timer(self._timeout, self._flush)
                self._timer.daemon = True
                self._timer.start()
        if flush_now:
            self._flush()
        return fut

    def _flush(self) -> None:
        with self._lock:
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
            batch, self._queue = self._queue, []
        if not batch:
            return
        instance = batch[0][0]
        items = [item for _, item, _ in batch]
        futs = [fut for _, _, fut in batch]
        try:
            if instance is not None:
                results = self._fn(instance, items)
            else:
                results = self._fn(items)
            if len(results) != len(items):
                raise ValueError(
                    f"batch function returned {len(results)} results for "
                    f"{len(items)} requests")
            for fut, r in zip(futs, results):
                fut.set_result(r)
        except BaseException as e:  # noqa: BLE001
            for fut in futs:
                fut.set_exception(e)


def batch(_fn=None, *, max_batch_size: int = 10,
          batch_wait_timeout_s: float = 0.01):
    """Decorator: fn(self, requests: List) -> List (or fn(requests))."""

    def wrap(fn):
        batcher = _Batcher(fn, max_batch_size, batch_wait_timeout_s)

        @functools.wraps(fn)
        def wrapper(*args):
            if len(args) == 2:       # bound method: (self, item)
                instance, item = args
            else:
                instance, item = None, args[0]
            return batcher.submit(instance, item).result(timeout=60)

        wrapper._batcher = batcher
        return wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap
