"""RayServeHandle + router.

Reference: python/ray/serve/handle.py + router.py: the handle embeds a
router that holds the current replica membership (refreshed when the
controller's membership version moves) and picks replicas round-robin,
skipping replicas above max_concurrent_queries (backpressure).
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Any, List, Optional

import ray_tpu


class ControllerRef:
    """Wraps the controller handle; on a failed call, re-resolves the
    named singleton and retries once — so routers and handles survive a
    controller death + recovery (reference: handles reconnect through
    the long-poll client after controller failover)."""

    def __init__(self, handle):
        if isinstance(handle, ControllerRef):  # idempotent wrap
            handle = handle._handle
        self._handle = handle

    def call(self, method: str, *args) -> Any:
        try:
            return ray_tpu.get(
                getattr(self._handle, method).remote(*args))
        except Exception:
            from ray_tpu.serve.api import _CONTROLLER_NAME

            self._handle = ray_tpu.get_actor(_CONTROLLER_NAME)
            return ray_tpu.get(
                getattr(self._handle, method).remote(*args))


class Router:
    def __init__(self, controller, deployment_name: str):
        self._controller = (controller if isinstance(controller,
                                                     ControllerRef)
                            else ControllerRef(controller))
        self._name = deployment_name
        self._replicas: List[Any] = []
        self._version = -2
        self._rr = itertools.count()
        self._lock = threading.Lock()

    def _refresh(self) -> None:
        version = self._controller.call("get_membership_version",
                                        self._name)
        if version != self._version:
            v, replicas = self._controller.call("get_replicas",
                                                self._name)
            with self._lock:
                self._version = v
                self._replicas = replicas

    def assign(self, max_concurrent: int) -> Any:
        deadline = time.monotonic() + 30.0
        while True:
            self._refresh()
            with self._lock:
                replicas = list(self._replicas)
            if not replicas:
                raise RuntimeError(
                    f"deployment {self._name!r} has no replicas "
                    "(not deployed or deleted)")
            # Round-robin, but skip replicas over the concurrency cap
            # (reference: router.py assign_replica backpressure).
            for _ in range(len(replicas)):
                idx = next(self._rr) % len(replicas)
                replica = replicas[idx]
                try:
                    ongoing = ray_tpu.get(replica.metrics.remote())["ongoing"]
                except Exception:
                    self._version = -2  # dead replica → force refresh
                    continue
                if ongoing < max_concurrent:
                    return replica
            if time.monotonic() > deadline:
                return replicas[next(self._rr) % len(replicas)]
            time.sleep(0.005)


class RayServeHandle:
    def __init__(self, controller, deployment_name: str,
                 method_name: Optional[str] = None,
                 router: Optional[Router] = None):
        self._controller = (controller if isinstance(controller,
                                                     ControllerRef)
                            else ControllerRef(controller))
        self._name = deployment_name
        self._method = method_name
        # Method sub-handles share the parent's router so round-robin
        # state spans all methods of the deployment.
        self._router = router or Router(self._controller,
                                        deployment_name)

    def options(self, method_name: str) -> "RayServeHandle":
        return RayServeHandle(self._controller, self._name, method_name,
                              self._router)

    def __getattr__(self, item: str) -> "RayServeHandle":
        if item.startswith("_"):
            raise AttributeError(item)
        return RayServeHandle(self._controller, self._name, item,
                              self._router)

    def remote(self, *args, **kwargs) -> "ray_tpu.ObjectRef":
        info = self._controller.call("get_deployment_info", self._name)
        max_concurrent = info[1].max_concurrent_queries if info else 100
        replica = self._router.assign(max_concurrent)
        return replica.handle_request.remote(
            self._method or "__call__", args, kwargs)

    def __repr__(self) -> str:
        return f"RayServeHandle(deployment={self._name!r})"
