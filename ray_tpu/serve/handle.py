"""RayServeHandle + router.

Reference: python/ray/serve/handle.py + router.py: the handle embeds a
router that holds the current replica membership (refreshed when the
controller's membership version moves) and picks replicas for each
request.

Resilience plane (this repo's serve hardening): the router runs
power-of-two-choices over LOCAL per-replica in-flight counts (no
metrics round trip per request — counts increment at assignment and
decrement when the result object materializes), consults the
per-destination circuit-breaker registry in :mod:`cluster.overload`
(open breaker => replica excluded), weights down replicas whose
``RetryLaterError`` shed hints are still fresh (temporary exclusion,
not blind retry), and — when every replica is shedding, breaker-open,
or saturated — surfaces a typed :class:`BackpressureError` to the
caller instead of queueing blind work. Completion outcomes feed the
breakers: a dead replica's errors open its breaker and P2C stops
offering it traffic before the controller's health probe even fires.

With ``Config.serve_resilience_enabled`` off, the pre-plane router
(round-robin over a per-request metrics fetch) is restored.
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import ray_tpu

logger = logging.getLogger(__name__)


class ControllerRef:
    """Wraps the controller handle; on a failed call, re-resolves the
    named singleton and retries once — so routers and handles survive a
    controller death + recovery (reference: handles reconnect through
    the long-poll client after controller failover)."""

    def __init__(self, handle):
        if isinstance(handle, ControllerRef):  # idempotent wrap
            handle = handle._handle
        self._handle = handle

    def call(self, method: str, *args) -> Any:
        try:
            return ray_tpu.get(
                getattr(self._handle, method).remote(*args))
        except Exception:
            from ray_tpu.serve.api import _CONTROLLER_NAME

            self._handle = ray_tpu.get_actor(_CONTROLLER_NAME)
            return ray_tpu.get(
                getattr(self._handle, method).remote(*args))


def _replica_key(deployment: str, handle) -> str:
    """Stable per-replica destination key for the overload registries
    (breakers / shed penalties) — shared process-wide, so every handle
    to the same deployment sees one breaker per replica."""
    return f"serve::{deployment}::{handle._actor_id.hex()[:16]}"


class Router:
    def __init__(self, controller, deployment_name: str):
        self._controller = (controller if isinstance(controller,
                                                     ControllerRef)
                            else ControllerRef(controller))
        self._name = deployment_name
        self._replicas: List[Tuple[str, Any]] = []  # (key, handle)
        self._version = -2
        self._max_concurrent = 100
        self._rr = itertools.count()
        self._lock = threading.Lock()
        self._inflight: Dict[str, int] = {}
        self._assigned: Dict[str, int] = {}  # lifetime picks (tie-break)
        from ray_tpu.cluster import fault_plane

        # seeded per-deployment stream: under a fault plan the P2C
        # candidate draws replay with the storm schedule (RC03 posture)
        self._rng = fault_plane.derive_rng(
            f"serve-router|{deployment_name}")

    # ---------------------------------------------------------- membership
    def _refresh(self, force: bool = False) -> None:
        version = self._controller.call("get_membership_version",
                                        self._name)
        if version != self._version or force:
            v, replicas, max_c = self._controller.call(
                "get_membership", self._name)
            keyed = [(_replica_key(self._name, r), r) for r in replicas]
            with self._lock:
                self._version = v
                self._replicas = keyed
                self._max_concurrent = max_c
                live = {k for k, _ in keyed}
                for k in list(self._inflight):
                    if k not in live:
                        del self._inflight[k]
                for k in list(self._assigned):
                    if k not in live:
                        del self._assigned[k]

    # ------------------------------------------------- completion tracking
    def _register_done(self, key: str, ref) -> None:
        """Decrement the replica's in-flight count and feed its breaker
        when the result object materializes (value OR stored error)."""
        from ray_tpu.core import runtime as rt_mod

        rt = rt_mod.global_runtime
        if rt is None or rt.is_shutdown:
            return
        oid = ref.id()
        store = rt.object_store

        def _done() -> None:
            with self._lock:
                n = self._inflight.get(key, 0)
                if n > 0:
                    self._inflight[key] = n - 1
            try:
                self._feed_outcome(key, store.peek(oid))
            except Exception as e:
                logger.debug("router completion hook for %s failed: %r",
                             key, e)

        try:
            store.on_available(oid, _done)
        except Exception as e:
            logger.debug("router could not watch %s: %r", oid, e)
            with self._lock:
                n = self._inflight.get(key, 0)
                if n > 0:
                    self._inflight[key] = n - 1

    def _feed_outcome(self, key: str, stored) -> None:
        from ray_tpu.cluster import overload
        from ray_tpu.exceptions import (
            RayActorError,
            RayTaskError,
            RetryLaterError,
            WorkerCrashedError,
        )

        if stored is None or not stored.is_error:
            overload.breaker_for(key).record_success()
            return
        err = stored.value
        cause = getattr(err, "cause", None) if isinstance(
            err, RayTaskError) else err
        if isinstance(cause, RetryLaterError):
            # shed hint: weight the replica DOWN for the server-chosen
            # window instead of blindly re-offering it traffic
            overload.note_shed(key, cause.retry_after_s)
            return
        if isinstance(err, (RayActorError, WorkerCrashedError)):
            # replica-level failure: count toward the breaker so P2C
            # stops offering a dead/poisoned replica before the
            # controller's probe replaces it
            overload.breaker_for(key).record_failure()
            return
        # a user exception is a HEALTHY replica doing its job
        overload.breaker_for(key).record_success()

    # ------------------------------------------------------------- routing
    def assign(self, max_concurrent: Optional[int] = None) -> Any:
        from ray_tpu._private.config import Config

        cfg = Config.instance()
        if not cfg.serve_resilience_enabled:
            return self._assign_legacy(max_concurrent)
        replica, key = self._assign_resilient(
            cfg.serve_router_backpressure_timeout_s, max_concurrent)
        return replica, key

    def _assign_resilient(self, timeout_s: float,
                          max_concurrent: Optional[int]
                          ) -> Tuple[Any, str]:
        from ray_tpu.cluster import overload
        from ray_tpu.exceptions import BackpressureError
        from ray_tpu.observability.metrics import (
            serve_requests_backpressured,
            serve_router_excluded,
        )

        deadline = time.monotonic() + timeout_s
        spent_desperation = False
        while True:
            self._refresh()
            with self._lock:
                replicas = list(self._replicas)
                inflight = dict(self._inflight)
                cap = (max_concurrent if max_concurrent is not None
                       else self._max_concurrent)
            if not replicas:
                raise RuntimeError(
                    f"deployment {self._name!r} has no replicas "
                    "(not deployed or deleted)")
            candidates: List[Tuple[str, Any]] = []
            min_penalty = None
            for key, handle in replicas:
                if not overload.breaker_for(key).allow():
                    serve_router_excluded.inc(
                        tags={"reason": "breaker_open"})
                    continue
                penalty = overload.shed_penalty_remaining(key)
                if penalty > 0.0:
                    serve_router_excluded.inc(
                        tags={"reason": "shed_penalty"})
                    min_penalty = (penalty if min_penalty is None
                                   else min(min_penalty, penalty))
                    continue
                if inflight.get(key, 0) >= cap:
                    serve_router_excluded.inc(
                        tags={"reason": "saturated"})
                    continue
                candidates.append((key, handle))
            if candidates:
                return self._pick_p2c(candidates, inflight)
            # every replica is shedding, breaker-open, or saturated.
            # One budget-gated desperation pass: offering a penalized
            # replica traffic anyway is a retry in the SRE sense, so it
            # spends a token — with the budget dry we fail fast instead
            # of amplifying (the metastable-storm discipline).
            penalized = [(k, h) for k, h in replicas
                         if overload.shed_penalty_remaining(k) > 0.0
                         and overload.breaker_for(k).allow()]
            if penalized and not spent_desperation \
                    and overload.budget_for(
                        f"serve::{self._name}").try_spend():
                spent_desperation = True
                return self._pick_p2c(penalized, inflight)
            if time.monotonic() >= deadline:
                serve_requests_backpressured.inc()
                raise BackpressureError(
                    self._name, retry_after_s=max(min_penalty or 0.0,
                                                  0.05))
            time.sleep(0.005)

    def _pick_p2c(self, candidates: List[Tuple[str, Any]],
                  inflight: Dict[str, int]) -> Tuple[Any, str]:
        """Power-of-two-choices: sample two distinct candidates, take
        the one with fewer local in-flight requests; ties break on
        fewest lifetime assignments (then membership order), so an
        idle fleet spreads exactly evenly like the old round-robin."""
        if len(candidates) == 1:
            key, handle = candidates[0]
        else:
            if len(candidates) == 2:
                pair = list(candidates)
            else:
                pair = self._rng.sample(candidates, 2)
            with self._lock:
                key, handle = min(
                    pair, key=lambda kh: (inflight.get(kh[0], 0),
                                          self._assigned.get(kh[0], 0)))
        with self._lock:
            self._inflight[key] = self._inflight.get(key, 0) + 1
            self._assigned[key] = self._assigned.get(key, 0) + 1
        return handle, key

    def _assign_legacy(self, max_concurrent: Optional[int]
                       ) -> Tuple[Any, None]:
        """Pre-plane router: round-robin over a per-request metrics
        fetch (kept verbatim behind serve_resilience_enabled=False)."""
        deadline = time.monotonic() + 30.0
        while True:
            self._refresh()
            with self._lock:
                replicas = [h for _, h in self._replicas]
                if max_concurrent is None:
                    max_concurrent = self._max_concurrent
            if not replicas:
                raise RuntimeError(
                    f"deployment {self._name!r} has no replicas "
                    "(not deployed or deleted)")
            # Round-robin, but skip replicas over the concurrency cap
            # (reference: router.py assign_replica backpressure).
            for _ in range(len(replicas)):
                idx = next(self._rr) % len(replicas)
                replica = replicas[idx]
                try:
                    ongoing = ray_tpu.get(replica.metrics.remote())["ongoing"]
                except Exception:
                    self._version = -2  # dead replica → force refresh
                    continue
                if ongoing < max_concurrent:
                    return replica, None
            if time.monotonic() > deadline:
                return replicas[next(self._rr) % len(replicas)], None
            time.sleep(0.005)


class RayServeHandle:
    def __init__(self, controller, deployment_name: str,
                 method_name: Optional[str] = None,
                 router: Optional[Router] = None):
        self._controller = (controller if isinstance(controller,
                                                     ControllerRef)
                            else ControllerRef(controller))
        self._name = deployment_name
        self._method = method_name
        # Method sub-handles share the parent's router so routing
        # state (in-flight counts, membership) spans all methods of
        # the deployment.
        self._router = router or Router(self._controller,
                                        deployment_name)

    def options(self, method_name: str) -> "RayServeHandle":
        return RayServeHandle(self._controller, self._name, method_name,
                              self._router)

    def __getattr__(self, item: str) -> "RayServeHandle":
        if item.startswith("_"):
            raise AttributeError(item)
        return RayServeHandle(self._controller, self._name, item,
                              self._router)

    def remote(self, *args, **kwargs) -> "ray_tpu.ObjectRef":
        replica, key = self._router.assign()
        ref = replica.handle_request.remote(
            self._method or "__call__", args, kwargs)
        if key is not None:
            self._router._register_done(key, ref)
        return ref

    def __repr__(self) -> str:
        return f"RayServeHandle(deployment={self._name!r})"
