"""RayServeHandle + router.

Reference: python/ray/serve/handle.py + router.py: the handle embeds a
router that holds the current replica membership (refreshed when the
controller's membership version moves) and picks replicas round-robin,
skipping replicas above max_concurrent_queries (backpressure).
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Any, List, Optional

import ray_tpu


class Router:
    def __init__(self, controller, deployment_name: str):
        self._controller = controller
        self._name = deployment_name
        self._replicas: List[Any] = []
        self._version = -2
        self._rr = itertools.count()
        self._lock = threading.Lock()

    def _refresh(self) -> None:
        version = ray_tpu.get(
            self._controller.get_membership_version.remote(self._name))
        if version != self._version:
            v, replicas = ray_tpu.get(
                self._controller.get_replicas.remote(self._name))
            with self._lock:
                self._version = v
                self._replicas = replicas

    def assign(self, max_concurrent: int) -> Any:
        deadline = time.monotonic() + 30.0
        while True:
            self._refresh()
            with self._lock:
                replicas = list(self._replicas)
            if not replicas:
                raise RuntimeError(
                    f"deployment {self._name!r} has no replicas "
                    "(not deployed or deleted)")
            # Round-robin, but skip replicas over the concurrency cap
            # (reference: router.py assign_replica backpressure).
            for _ in range(len(replicas)):
                idx = next(self._rr) % len(replicas)
                replica = replicas[idx]
                try:
                    ongoing = ray_tpu.get(replica.metrics.remote())["ongoing"]
                except Exception:
                    self._version = -2  # dead replica → force refresh
                    continue
                if ongoing < max_concurrent:
                    return replica
            if time.monotonic() > deadline:
                return replicas[next(self._rr) % len(replicas)]
            time.sleep(0.005)


class RayServeHandle:
    def __init__(self, controller, deployment_name: str,
                 method_name: Optional[str] = None,
                 router: Optional[Router] = None):
        self._controller = controller
        self._name = deployment_name
        self._method = method_name
        # Method sub-handles share the parent's router so round-robin
        # state spans all methods of the deployment.
        self._router = router or Router(controller, deployment_name)

    def options(self, method_name: str) -> "RayServeHandle":
        return RayServeHandle(self._controller, self._name, method_name,
                              self._router)

    def __getattr__(self, item: str) -> "RayServeHandle":
        if item.startswith("_"):
            raise AttributeError(item)
        return RayServeHandle(self._controller, self._name, item,
                              self._router)

    def remote(self, *args, **kwargs) -> "ray_tpu.ObjectRef":
        info = ray_tpu.get(
            self._controller.get_deployment_info.remote(self._name))
        max_concurrent = info[1].max_concurrent_queries if info else 100
        replica = self._router.assign(max_concurrent)
        return replica.handle_request.remote(
            self._method or "__call__", args, kwargs)

    def __repr__(self) -> str:
        return f"RayServeHandle(deployment={self._name!r})"
