"""Deployment + autoscaling config (reference: python/ray/serve/config.py)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class AutoscalingConfig:
    """Replica autoscaling on observed queue sizes (reference:
    serve/autoscaling_policy.py BasicAutoscalingPolicy)."""
    min_replicas: int = 1
    max_replicas: int = 1
    target_num_ongoing_requests_per_replica: float = 1.0
    upscale_delay_s: float = 0.0
    downscale_delay_s: float = 60.0
    smoothing_factor: float = 1.0


@dataclass
class DeploymentConfig:
    num_replicas: int = 1
    ray_actor_options: Dict[str, Any] = field(default_factory=dict)
    user_config: Optional[Any] = None
    max_concurrent_queries: int = 100
    autoscaling_config: Optional[AutoscalingConfig] = None
    graceful_shutdown_timeout_s: float = 20.0
    # Health probing (resilience plane): the controller calls each
    # replica's cheap check_health() every period; timeout or a falsy
    # reply counts as a failure, and `threshold` CONSECUTIVE failures
    # mark the replica unhealthy — drained from routing and replaced
    # via the reconcile loop (reference: Ray Serve deployment_state.py
    # health_check_period_s / health_check_timeout_s). None = the
    # process-wide Config.serve_health_check_* defaults.
    health_check_period_s: Optional[float] = None
    health_check_timeout_s: Optional[float] = None
    health_check_failure_threshold: Optional[int] = None

    def resolved_health_check(self) -> tuple:
        """(period_s, timeout_s, threshold) with Config defaults filled."""
        from ray_tpu._private.config import Config

        cfg = Config.instance()
        period = (self.health_check_period_s
                  if self.health_check_period_s is not None
                  else cfg.serve_health_check_period_s)
        timeout = (self.health_check_timeout_s
                   if self.health_check_timeout_s is not None
                   else cfg.serve_health_check_timeout_s)
        threshold = (self.health_check_failure_threshold
                     if self.health_check_failure_threshold is not None
                     else cfg.serve_health_check_failure_threshold)
        return float(period), float(timeout), int(threshold)
