"""Deployment + autoscaling config (reference: python/ray/serve/config.py)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class AutoscalingConfig:
    """Replica autoscaling on observed queue sizes (reference:
    serve/autoscaling_policy.py BasicAutoscalingPolicy)."""
    min_replicas: int = 1
    max_replicas: int = 1
    target_num_ongoing_requests_per_replica: float = 1.0
    upscale_delay_s: float = 0.0
    downscale_delay_s: float = 60.0
    smoothing_factor: float = 1.0


@dataclass
class DeploymentConfig:
    num_replicas: int = 1
    ray_actor_options: Dict[str, Any] = field(default_factory=dict)
    user_config: Optional[Any] = None
    max_concurrent_queries: int = 100
    autoscaling_config: Optional[AutoscalingConfig] = None
    graceful_shutdown_timeout_s: float = 20.0
