"""ray_tpu.serve — model serving on actors.

Reference surface: python/ray/serve/__init__.py (@serve.deployment,
serve.start/shutdown, get_deployment, list_deployments, @serve.batch).
"""

from ray_tpu.serve.api import (  # noqa: F401
    Deployment,
    deployment,
    get_deployment,
    list_deployments,
    run,
    shutdown,
    start,
)
from ray_tpu.serve import pipeline  # noqa: F401
from ray_tpu.serve.batching import batch  # noqa: F401
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig  # noqa: F401
from ray_tpu.serve.handle import RayServeHandle  # noqa: F401
from ray_tpu.serve.http_proxy import HTTPProxy, start_http_proxy  # noqa: F401
from ray_tpu.exceptions import BackpressureError  # noqa: F401

__all__ = [
    "deployment", "Deployment", "start", "run", "shutdown", "get_deployment",
    "list_deployments", "batch", "AutoscalingConfig", "DeploymentConfig",
    "RayServeHandle", "HTTPProxy", "start_http_proxy", "pipeline",
    "BackpressureError",
]
