"""ray_tpu.serve public API.

Reference: python/ray/serve/api.py (@serve.deployment:1037, Deployment
class :730, serve.start, get_deployment, list_deployments). Deployments
are versioned replica sets managed by a singleton controller actor;
traffic flows driver/ingress → router → replica actor calls.
"""

from __future__ import annotations

import inspect
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Union

import ray_tpu
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig

_CONTROLLER_NAME = "SERVE_CONTROLLER"


def start(detached: bool = False, http_options: Optional[dict] = None):
    """Start (or connect to) the serve control plane: a named singleton
    controller actor (reference: serve/api.py serve.start)."""
    from ray_tpu.serve.controller import ServeController

    if not ray_tpu.is_initialized():
        ray_tpu.init()
    try:
        return ray_tpu.get_actor(_CONTROLLER_NAME)
    except ValueError:  # raycheck: disable=RC05 — ValueError means "no controller yet"; creating one below IS the handling
        pass
    controller = ray_tpu.remote(ServeController).options(
        name=_CONTROLLER_NAME,
        lifetime="detached" if detached else None,
    ).remote(http_options or {})
    ray_tpu.get(controller.ready.remote())
    return controller


def _get_controller():
    try:
        return ray_tpu.get_actor(_CONTROLLER_NAME)
    except ValueError:
        return start()


def shutdown() -> None:
    try:
        controller = ray_tpu.get_actor(_CONTROLLER_NAME)
    except ValueError:
        return
    ray_tpu.get(controller.shutdown.remote())
    ray_tpu.kill(controller)


class Deployment:
    """A named, versioned, replicated callable (reference:
    serve/api.py:730)."""

    def __init__(self, func_or_class: Union[Callable, type], name: str,
                 config: DeploymentConfig,
                 init_args: tuple = (), init_kwargs: Optional[dict] = None,
                 version: Optional[str] = None,
                 route_prefix: Optional[str] = None):
        self._func_or_class = func_or_class
        self._name = name
        self._config = config
        self._init_args = init_args
        self._init_kwargs = init_kwargs or {}
        self._version = version
        self._route_prefix = route_prefix

    @property
    def name(self) -> str:
        return self._name

    @property
    def version(self) -> Optional[str]:
        return self._version

    @property
    def num_replicas(self) -> int:
        return self._config.num_replicas

    @property
    def route_prefix(self) -> Optional[str]:
        return self._route_prefix if self._route_prefix is not None \
            else f"/{self._name}"

    @property
    def func_or_class(self):
        return self._func_or_class

    def options(self, **kwargs) -> "Deployment":
        cfg_fields = {f for f in DeploymentConfig.__dataclass_fields__}
        cfg_updates = {k: v for k, v in kwargs.items() if k in cfg_fields}
        import dataclasses

        new_cfg = dataclasses.replace(self._config, **cfg_updates)
        return Deployment(
            kwargs.get("func_or_class", self._func_or_class),
            kwargs.get("name", self._name),
            new_cfg,
            kwargs.get("init_args", self._init_args),
            kwargs.get("init_kwargs", self._init_kwargs),
            kwargs.get("version", self._version),
            kwargs.get("route_prefix", self._route_prefix),
        )

    def deploy(self, *init_args, _blocking: bool = True, **init_kwargs):
        controller = _get_controller()
        if init_args or init_kwargs:
            self._init_args = init_args
            self._init_kwargs = init_kwargs
        ref = controller.deploy.remote(
            self._name, self._func_or_class, self._config,
            self._init_args, self._init_kwargs, self._version,
            self.route_prefix)
        if _blocking:
            ray_tpu.get(ref)
        return self

    def delete(self) -> None:
        controller = _get_controller()
        ray_tpu.get(controller.delete_deployment.remote(self._name))

    def get_handle(self, sync: bool = True) -> "RayServeHandle":
        from ray_tpu.serve.handle import RayServeHandle

        return RayServeHandle(_get_controller(), self._name)

    def __call__(self, *args, **kwargs):
        raise RuntimeError(
            "Deployments cannot be called directly; use "
            f"{self._name}.get_handle() or HTTP.")

    def __repr__(self) -> str:
        return (f"Deployment(name={self._name}, "
                f"version={self._version}, "
                f"num_replicas={self._config.num_replicas})")


def deployment(_func_or_class=None, *, name: Optional[str] = None,
               version: Optional[str] = None,
               num_replicas: Optional[int] = None,
               init_args: tuple = (),
               init_kwargs: Optional[dict] = None,
               route_prefix: Optional[str] = None,
               ray_actor_options: Optional[dict] = None,
               user_config: Optional[Any] = None,
               max_concurrent_queries: Optional[int] = None,
               autoscaling_config: Optional[Union[dict,
                                                  AutoscalingConfig]] = None,
               graceful_shutdown_timeout_s: float = 20.0,
               health_check_period_s: Optional[float] = None,
               health_check_timeout_s: Optional[float] = None,
               health_check_failure_threshold: Optional[int] = None):
    """@serve.deployment decorator (reference: serve/api.py:1037).

    The ``health_check_*`` knobs tune the controller's probe loop per
    deployment (None = the process-wide Config.serve_health_check_*
    defaults); a class deployment may also define its own cheap
    ``check_health()`` whose falsy/raising answer fails the probe."""
    if isinstance(autoscaling_config, dict):
        autoscaling_config = AutoscalingConfig(**autoscaling_config)
    config = DeploymentConfig(
        num_replicas=num_replicas or 1,
        ray_actor_options=ray_actor_options or {},
        user_config=user_config,
        max_concurrent_queries=max_concurrent_queries or 100,
        autoscaling_config=autoscaling_config,
        graceful_shutdown_timeout_s=graceful_shutdown_timeout_s,
        health_check_period_s=health_check_period_s,
        health_check_timeout_s=health_check_timeout_s,
        health_check_failure_threshold=health_check_failure_threshold,
    )

    def wrap(func_or_class):
        return Deployment(
            func_or_class,
            name or func_or_class.__name__,
            config,
            init_args,
            init_kwargs,
            version,
            route_prefix,
        )

    if _func_or_class is not None:
        return wrap(_func_or_class)
    return wrap


def run(target, *, name: Optional[str] = None,
        route_prefix: Optional[str] = None):
    """The reference's 2.x entrypoint (serve/api.py serve.run).

    Deployment (or bare function/class, which gets wrapped) ->
    RayServeHandle. PipelineNode -> DeployedPipeline (call via
    .call(); pipelines route through their own step graph, so
    route_prefix does not apply and is rejected)."""
    from ray_tpu.serve.pipeline import PipelineNode

    if isinstance(target, PipelineNode):
        if route_prefix is not None:
            raise ValueError(
                "route_prefix does not apply to pipeline targets")
        return target.deploy(name or "pipeline")
    if not isinstance(target, Deployment):
        target = deployment(target)
    if name or route_prefix is not None:
        overrides = {}
        if name:
            overrides["name"] = name
        if route_prefix is not None:
            overrides["route_prefix"] = route_prefix
        target = target.options(**overrides)
    target.deploy()
    return target.get_handle()


def get_deployment(name: str) -> Deployment:
    controller = _get_controller()
    info = ray_tpu.get(controller.get_deployment_info.remote(name))
    if info is None:
        raise KeyError(f"no deployment named {name!r}")
    func_or_class, config, init_args, init_kwargs, version, route = info
    return Deployment(func_or_class, name, config, init_args, init_kwargs,
                      version, route)


def list_deployments() -> Dict[str, Deployment]:
    controller = _get_controller()
    names = ray_tpu.get(controller.list_deployments.remote())
    return {n: get_deployment(n) for n in names}
