"""Replica actor — hosts one copy of the user's deployment.

Reference: python/ray/serve/replica.py (RayServeReplica): executes
requests against the user callable, tracks ongoing-request count (the
autoscaling metric), applies user_config via reconfigure().

Resilience plane additions (this repo's serve hardening):

- ``check_health()`` — the cheap controller probe; delegates to the
  user callable's own ``check_health`` when it defines one (reference:
  deployment_state.py replica health checks), else reports alive.
- ``drain(grace_s)`` — graceful shutdown entry: new requests are shed
  with :class:`RetryLaterError` once the grace window passes (the
  window absorbs assignments routed on the pre-drain membership), and
  the controller polls ``num_ongoing()`` down to zero before killing.
- A fault-plane response seam: when a :mod:`cluster.fault_plane` plan
  is active, the reply payload round-trips through bytes with a crc32
  computed ONCE at creation, the plane's seeded ``stall``/``corrupt``
  actions fire against ``dst="serve::<deployment>"``, and — with the
  resilience plane on — a flipped byte is caught by the digest and the
  reply is re-serialized from the still-intact value (correct answer,
  detection counted) instead of deserializing to silent garbage.
"""

from __future__ import annotations

import inspect
import logging
import threading
import time
from typing import Any, Optional

logger = logging.getLogger(__name__)


class ReplicaActor:
    def __init__(self, func_or_class, init_args: tuple, init_kwargs: dict,
                 user_config: Optional[Any] = None,
                 deployment_name: str = "", replica_tag: str = ""):
        self._is_function = inspect.isfunction(func_or_class) or (
            callable(func_or_class) and not inspect.isclass(func_or_class))
        if self._is_function:
            self._callable = func_or_class
        else:
            self._callable = func_or_class(*init_args, **init_kwargs)
        self._deployment = deployment_name
        self._replica_tag = replica_tag
        self._ongoing = 0
        self._total = 0
        self._num_shed = 0
        self._lock = threading.Lock()
        self._draining = False
        self._drain_started = 0.0
        self._drain_grace_s = 0.0
        if user_config is not None:
            self.reconfigure(user_config)

    def ready(self) -> bool:
        return True

    def check_health(self) -> bool:
        """Controller probe (cheap). A user callable that defines its
        own ``check_health`` decides (falsy/raise = unhealthy); without
        one, answering at all is the health signal."""
        if not self._is_function:
            probe = getattr(self._callable, "check_health", None)
            if callable(probe):
                return bool(probe())
        return True

    def reconfigure(self, user_config: Any) -> None:
        if not self._is_function and hasattr(self._callable, "reconfigure"):
            self._callable.reconfigure(user_config)

    # ------------------------------------------------------------- draining
    def drain(self, grace_s: float = 0.0) -> int:
        """Stop accepting new work (after ``grace_s``) and report the
        current in-flight count; the controller polls num_ongoing()
        down to zero before the kill (reference: deployment_state.py
        graceful_shutdown_wait_loop_s drain loop)."""
        with self._lock:
            if not self._draining:
                self._draining = True
                self._drain_started = time.monotonic()
                self._drain_grace_s = max(0.0, float(grace_s))
            return self._ongoing

    def num_ongoing(self) -> int:
        with self._lock:
            return self._ongoing

    # ------------------------------------------------------------- requests
    def handle_request(self, method_name: str, args: tuple, kwargs: dict
                       ) -> Any:
        with self._lock:
            if self._draining and (
                    time.monotonic() - self._drain_started
                    > self._drain_grace_s):
                self._num_shed += 1
                shed = True
            else:
                shed = False
                self._ongoing += 1
                self._total += 1
        if shed:
            from ray_tpu.exceptions import RetryLaterError

            raise RetryLaterError(
                f"replica {self._replica_tag or '?'} of "
                f"{self._deployment or '?'} is draining", retry_after_s=0.1)
        try:
            self._maybe_stall(method_name)
            if self._is_function:
                result = self._callable(*args, **kwargs)
            elif method_name in (None, "", "__call__"):
                result = self._callable(*args, **kwargs)
            else:
                result = getattr(self._callable, method_name)(
                    *args, **kwargs)
            return self._respond(result, method_name)
        finally:
            with self._lock:
                self._ongoing -= 1

    # ------------------------------------------------- fault-plane seam
    def _fault_dst(self) -> str:
        return f"serve::{self._deployment or '?'}"

    def _maybe_stall(self, method_name: str) -> None:
        """Seeded ``stall`` rules against ``dst="serve::<deployment>"``
        slow this replica down inside its request slot — the storm
        ingredient the router's in-flight balancing routes around."""
        from ray_tpu.cluster import fault_plane

        plane = fault_plane.get_plane()
        if plane is None:
            return
        fault = plane.decide("handler", self._fault_dst(),
                             method_name or "__call__")
        if fault is not None and fault["action"] == "stall":
            time.sleep(fault["seconds"])

    def _respond(self, result: Any, method_name: str) -> Any:
        """Response seam. With no fault plane active (the common case)
        the value passes through untouched. Under a plan, the reply
        takes the byte path: serialize once, digest once, let the
        plane's seeded ``corrupt`` flip a byte in 'transit', then —
        resilience plane on — verify the digest at hand-off and
        re-serialize from the intact value on mismatch (detection, not
        wrongness); plane off, deserialize whatever the bytes say (the
        silent-wrong-answer baseline the storm demo measures)."""
        from ray_tpu.cluster import fault_plane

        plane = fault_plane.get_plane()
        if plane is None:
            return result
        fault = plane.decide("reply", self._fault_dst(),
                             method_name or "__call__")
        if fault is None or fault["action"] != "corrupt":
            return result
        import zlib

        import cloudpickle

        from ray_tpu._private.config import Config

        try:
            payload = cloudpickle.dumps(result)
        except Exception as e:
            logger.debug("serve reply seam: result of %s.%s not "
                         "picklable (%r); skipping byte path",
                         self._deployment, method_name, e)
            return result
        crc = zlib.crc32(payload)
        buf = bytes(fault_plane.apply_corruption(payload, fault,
                                                 tail_bias=True))
        if Config.instance().serve_resilience_enabled:
            if zlib.crc32(buf) != crc:
                from ray_tpu.cluster import integrity

                integrity.record_corruption("serve_reply",
                                            discarded=False)
                # recovery: the computed value is still intact in this
                # process — re-serialize and hand off the correct bytes
                return result
            return cloudpickle.loads(buf)
        try:
            return cloudpickle.loads(buf)  # plane off: silent garbage
        except Exception as e:
            # the flip landed in pickle structure instead of payload
            # bytes: loud failure, the lucky case
            raise RuntimeError(
                f"corrupted serve reply for {self._deployment}."
                f"{method_name}: {e!r}")

    def metrics(self) -> dict:
        with self._lock:
            return {"ongoing": self._ongoing, "total": self._total,
                    "shed": self._num_shed,
                    "draining": self._draining}
