"""Replica actor — hosts one copy of the user's deployment.

Reference: python/ray/serve/replica.py (RayServeReplica): executes
requests against the user callable, tracks ongoing-request count (the
autoscaling metric), applies user_config via reconfigure().
"""

from __future__ import annotations

import inspect
import threading
from typing import Any, Optional


class ReplicaActor:
    def __init__(self, func_or_class, init_args: tuple, init_kwargs: dict,
                 user_config: Optional[Any] = None):
        self._is_function = inspect.isfunction(func_or_class) or (
            callable(func_or_class) and not inspect.isclass(func_or_class))
        if self._is_function:
            self._callable = func_or_class
        else:
            self._callable = func_or_class(*init_args, **init_kwargs)
        self._ongoing = 0
        self._total = 0
        self._lock = threading.Lock()
        if user_config is not None:
            self.reconfigure(user_config)

    def ready(self) -> bool:
        return True

    def reconfigure(self, user_config: Any) -> None:
        if not self._is_function and hasattr(self._callable, "reconfigure"):
            self._callable.reconfigure(user_config)

    def handle_request(self, method_name: str, args: tuple, kwargs: dict
                       ) -> Any:
        with self._lock:
            self._ongoing += 1
            self._total += 1
        try:
            if self._is_function:
                return self._callable(*args, **kwargs)
            if method_name in (None, "", "__call__"):
                return self._callable(*args, **kwargs)
            return getattr(self._callable, method_name)(*args, **kwargs)
        finally:
            with self._lock:
                self._ongoing -= 1

    def metrics(self) -> dict:
        with self._lock:
            return {"ongoing": self._ongoing, "total": self._total}
