"""Deployment graphs — composable inference pipelines over deployments.

Reference: python/ray/serve/pipeline/ (experimental DAG API): steps are
sealed callables/classes deployed as replica groups; a pipeline is a DAG
of steps rooted at INPUT, executed by fanning calls out across the step
handles. Same shape here:

    @pipeline.step(num_replicas=2)
    def preprocess(x): ...

    @pipeline.step
    class Model:
        def __call__(self, x): ...

    graph = Model()(preprocess(pipeline.INPUT))
    deployed = graph.deploy("my_pipeline")
    deployed.call(payload)

Execution is handle-based: each step invocation becomes an actor task on
that step's deployment, upstream results flow in as resolved arguments,
and independent branches run concurrently (their ObjectRefs are awaited
together at the join)."""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple, Union

import ray_tpu

_INPUT_SENTINEL = "__pipeline_input__"


class _Input:
    """Marker for the pipeline's runtime input."""

    def __repr__(self):
        return "pipeline.INPUT"


INPUT = _Input()


class Step:
    """A sealed computation unit; calling it on upstream nodes builds the
    graph (reference: serve/pipeline/step.py)."""

    def __init__(self, func_or_class, name: str,
                 num_replicas: int = 1,
                 ray_actor_options: Optional[dict] = None):
        self.func_or_class = func_or_class
        self.name = name
        self.num_replicas = num_replicas
        self.ray_actor_options = ray_actor_options or {}
        self._instance_args: Tuple = ()
        self._instance_kwargs: Dict = {}
        self._is_class = isinstance(func_or_class, type)
        self._bound = not self._is_class  # function steps wire directly

    def __call__(self, *args, **kwargs):
        if not self._bound:
            # class step: the FIRST call always binds constructor args
            # (even zero), the second wires the graph —
            # Model(init_args)(upstream). An explicit flag, not arg
            # sniffing: Gen()(42) must wire, not re-bind.
            bound = Step(self.func_or_class, self.name, self.num_replicas,
                         self.ray_actor_options)
            bound._instance_args = args
            bound._instance_kwargs = kwargs
            bound._bound = True
            return bound
        return PipelineNode(self, args, kwargs)

    def instantiate(self):
        if self._is_class:
            return self.func_or_class(*self._instance_args,
                                      **self._instance_kwargs)
        return self.func_or_class


class PipelineNode:
    """One step invocation in the DAG."""

    def __init__(self, step: Step, args: Tuple, kwargs: Dict):
        self.step = step
        self.args = args
        self.kwargs = kwargs

    def deploy(self, name: str = "pipeline") -> "DeployedPipeline":
        return DeployedPipeline(self, name)

    def __repr__(self):
        return f"PipelineNode({self.step.name})"


class _StepReplica:
    """Actor class hosting one step instance."""

    def __init__(self, step: Step):
        self._callable = step.instantiate()

    def handle_call(self, *args, **kwargs):
        return self._callable(*args, **kwargs)


class DeployedPipeline:
    """A live pipeline: every step backed by a pool of replica actors,
    calls routed round-robin (reference: pipeline deployments share the
    serve replica machinery)."""

    def __init__(self, root: PipelineNode, name: str):
        self.root = root
        self.name = name
        self._pools: Dict[str, List] = {}
        self._rr: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._deploy_steps(root)

    def _deploy_steps(self, node: PipelineNode) -> None:
        step = node.step
        if step.name not in self._pools:
            actor_cls = ray_tpu.remote(_StepReplica)
            opts = dict(step.ray_actor_options)
            pool = [
                actor_cls.options(**opts).remote(step)
                for _ in range(step.num_replicas)
            ]
            self._pools[step.name] = pool
            self._rr[step.name] = 0
        for dep in list(node.args) + list(node.kwargs.values()):
            if isinstance(dep, PipelineNode):
                self._deploy_steps(dep)

    def _replica(self, step_name: str):
        with self._lock:
            pool = self._pools[step_name]
            idx = self._rr[step_name] % len(pool)
            self._rr[step_name] = idx + 1
            return pool[idx]

    def call(self, input_value: Any) -> Any:
        """Execute the DAG on one input. Shared nodes evaluate once;
        sibling branches run concurrently (unresolved ObjectRefs are only
        awaited where a downstream step consumes them)."""
        memo: Dict[int, Any] = {}
        ref = self._submit(self.root, input_value, memo)
        return ray_tpu.get(ref)

    def call_many(self, inputs: List[Any]) -> List[Any]:
        memos = [{} for _ in inputs]
        refs = [self._submit(self.root, v, m)
                for v, m in zip(inputs, memos)]
        return ray_tpu.get(refs)

    def _submit(self, node: Union[PipelineNode, _Input, Any],
                input_value: Any, memo: Dict[int, Any]):
        if isinstance(node, _Input):
            return input_value
        if not isinstance(node, PipelineNode):
            return node  # constant argument
        if id(node) in memo:
            return memo[id(node)]
        args = [self._submit(a, input_value, memo) for a in node.args]
        kwargs = {k: self._submit(v, input_value, memo)
                  for k, v in node.kwargs.items()}
        replica = self._replica(node.step.name)
        ref = replica.handle_call.remote(*args, **kwargs)
        memo[id(node)] = ref
        return ref

    def shutdown(self) -> None:
        import logging

        for pool in self._pools.values():
            for actor in pool:
                try:
                    ray_tpu.kill(actor)
                except Exception as e:
                    logging.getLogger(__name__).debug(
                        "killing pipeline step actor %r at shutdown "
                        "failed (already dead?): %r", actor, e)
        self._pools.clear()


def step(_func_or_class=None, *, num_replicas: int = 1,
         ray_actor_options: Optional[dict] = None,
         name: Optional[str] = None):
    """Decorator sealing a function/class into a pipeline Step."""

    def wrap(func_or_class):
        return Step(func_or_class,
                    name or getattr(func_or_class, "__name__", "step"),
                    num_replicas=num_replicas,
                    ray_actor_options=ray_actor_options)

    if _func_or_class is not None:
        return wrap(_func_or_class)
    return wrap
