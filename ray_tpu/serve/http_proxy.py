"""HTTP ingress for serve (reference: python/ray/serve/http_proxy.py).

The reference runs a uvicorn/starlette proxy actor per node; here a
stdlib ThreadingHTTPServer inside the proxy actor routes
``route_prefix`` → deployment handle. JSON in/JSON out.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

import ray_tpu


class HTTPProxy:
    """Actor hosting the HTTP server; resolves routes via the controller."""

    def __init__(self, controller, host: str = "127.0.0.1", port: int = 0):
        from ray_tpu.serve.handle import ControllerRef, RayServeHandle

        self._controller = ControllerRef(controller)
        self._handles: Dict[str, RayServeHandle] = {}
        proxy = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # silence
                pass

            def _dispatch(self, body: Optional[bytes]):
                routes = proxy._controller.call("get_routes")
                path = self.path.split("?")[0]
                name = routes.get(path)
                if name is None:
                    for prefix, n in routes.items():
                        if prefix != "/" and path.startswith(prefix):
                            name = n
                            break
                if name is None:
                    self.send_response(404)
                    self.end_headers()
                    self.wfile.write(b'{"error": "no route"}')
                    return
                if name not in proxy._handles:
                    proxy._handles[name] = RayServeHandle(
                        proxy._controller, name)
                try:
                    payload = json.loads(body) if body else None
                except json.JSONDecodeError:
                    payload = body.decode()
                from ray_tpu.exceptions import RetryLaterError

                try:
                    args = (payload,) if payload is not None else ()
                    result = ray_tpu.get(
                        [proxy._handles[name].remote(*args)])[0]
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.end_headers()
                    self.wfile.write(json.dumps(result).encode())
                except RetryLaterError as e:
                    # backpressure (every replica shedding) or a
                    # draining replica's shed: 503 + Retry-After, the
                    # HTTP spelling of the typed hint (reference:
                    # Serve proxy returning 503 on backpressure).
                    # A replica-raised shed arrives as the dual
                    # RayTaskError(RetryLaterError); the hint then
                    # lives on the cause.
                    hint = getattr(e, "retry_after_s", None)
                    if hint is None:
                        hint = getattr(getattr(e, "cause", None),
                                       "retry_after_s", 0.05)
                    self.send_response(503)
                    self.send_header("Retry-After",
                                     f"{max(hint, 0.05):.3f}")
                    self.end_headers()
                    self.wfile.write(json.dumps(
                        {"error": str(e),
                         "retry_after_s": hint}).encode())
                except Exception as e:  # noqa: BLE001
                    self.send_response(500)
                    self.end_headers()
                    self.wfile.write(json.dumps(
                        {"error": str(e)}).encode())

            def do_GET(self):
                self._dispatch(None)

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                self._dispatch(self.rfile.read(length) if length else None)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._thread.start()

    def address(self) -> str:
        return f"http://127.0.0.1:{self._port}"

    def port(self) -> int:
        return self._port

    def stop(self) -> None:
        self._server.shutdown()


def start_http_proxy(controller, host: str = "127.0.0.1", port: int = 0):
    proxy = ray_tpu.remote(HTTPProxy).remote(controller, host, port)
    return proxy
