"""ray_tpu.gcs — global control state introspection.

Reference surface: python/ray/state.py (GlobalState) +
internal/internal_api.py (memory dump). The authoritative data lives in
the runtime (the in-process GCS); this module is the read path.
"""

from ray_tpu.gcs.state import (  # noqa: F401
    GlobalState,
    actors,
    memory_summary,
    nodes,
    state,
    timeline,
)

__all__ = ["GlobalState", "state", "actors", "nodes", "memory_summary",
           "timeline"]
