"""GCS durability: snapshot and restore of cluster metadata.

Reference: the GCS persists its tables (actor/job/node/PG/KV) to Redis
(gcs_table_storage.cc, gcs/store_client/redis_store_client.cc) and bulk
re-loads them on restart (gcs_init_data.cc), restarting detached actors
and re-placing placement groups. This build's control plane lives
in-process, so durability is an explicit snapshot file:

  save_snapshot(path)     serialize internal KV, job info, node resource
                          configs, detached-actor creation specs, and
                          placement-group specs (cloudpickle).
  restore_snapshot(path)  after a fresh ``init``: re-register the KV,
                          re-create detached named actors (fresh state —
                          the reference also loses actor memory on
                          restart-from-GCS) and re-place PGs.

Like the reference, only *detached* actors survive the control plane:
non-detached actors die with their owner (job)."""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

import cloudpickle

from ray_tpu.core import runtime as rt_mod

SNAPSHOT_VERSION = 1


def _runtime():
    rt = rt_mod.global_runtime
    if rt is None or rt.is_shutdown:
        raise RuntimeError("ray_tpu is not initialized")
    return rt


def capture() -> Dict[str, Any]:
    """Materialize the durable subset of cluster state."""
    rt = _runtime()
    from ray_tpu.core.actor_runtime import ActorState

    actors: List[Dict[str, Any]] = []
    for rec in rt.actor_directory.list():
        if not rec.detached or rec.state is ActorState.DEAD:
            continue
        creation = rec.creation_spec
        actors.append({
            "cls": creation.cls,
            "cls_descriptor": creation.cls_descriptor,
            "init_args": creation.init_args,
            "init_kwargs": creation.init_kwargs,
            "options": creation.options,
            "name": rec.name,
            "namespace": rec.namespace,
        })
    from ray_tpu.scheduler.placement_group import PlacementGroupState

    pgs: List[Dict[str, Any]] = []
    for pg in rt.pg_manager._groups.values():
        if pg.state is PlacementGroupState.REMOVED:
            continue
        pgs.append({
            "id": pg.id,  # stable identity -> idempotent restore
            "bundles": [dict(b) for b in pg.bundles],
            "strategy": pg.strategy,
            "name": pg.name,
        })
    with rt._kv_lock:
        kv = dict(rt.kv)
    nodes = []
    for raylet in rt.cluster_state.alive_raylets():
        nodes.append({
            "resources": raylet.local_resources.to_map(rt.cluster_state.ids),
            "is_head": raylet is rt.head_raylet,
        })
    return {
        "version": SNAPSHOT_VERSION,
        "namespace": rt.namespace,
        "kv": kv,
        "detached_actors": actors,
        "placement_groups": pgs,
        "nodes": nodes,
    }


def save_snapshot(path: str) -> str:
    data = capture()
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        cloudpickle.dump(data, f)
    os.replace(tmp, path)  # atomic publish, never a torn snapshot
    return path


def load_snapshot(path: str) -> Dict[str, Any]:
    with open(path, "rb") as f:
        data = cloudpickle.load(f)
    if data.get("version") != SNAPSHOT_VERSION:
        raise ValueError(
            f"snapshot version {data.get('version')} != {SNAPSHOT_VERSION}")
    return data


def restore_snapshot(path: str, *, restore_nodes: bool = False) -> Dict[str, int]:
    """Apply a snapshot to the (already initialized) runtime. Returns
    counts per restored table (reference: gcs_init_data.cc load +
    GcsActorManager restart of detached actors)."""
    rt = _runtime()
    data = load_snapshot(path)
    counts = {"kv": 0, "actors": 0, "placement_groups": 0, "nodes": 0}
    if restore_nodes:
        # re-create worker-node capacity (head node already exists)
        for node in data["nodes"]:
            if node["is_head"]:
                continue
            rt.add_node(dict(node["resources"]))
            counts["nodes"] += 1
    with rt._kv_lock:
        for key, value in data["kv"].items():
            if key not in rt.kv:
                rt.kv[key] = value
                counts["kv"] += 1
    from ray_tpu.scheduler.placement_group import PlacementGroup

    for pg in data["placement_groups"]:
        # re-create under the ORIGINAL id (the reference keys its PG
        # table by id), so unnamed groups are idempotent too
        if pg["id"] in rt.pg_manager._groups:
            continue
        rt.pg_manager.create(PlacementGroup(
            id=pg["id"],
            bundles=[dict(b) for b in pg["bundles"]],
            strategy=pg["strategy"],
            name=pg["name"]))
        counts["placement_groups"] += 1
    for spec in data["detached_actors"]:
        # anonymous-namespace actors re-register under the *current*
        # runtime namespace, so the duplicate check must look there
        ns = getattr(spec["options"], "namespace", None) or rt.namespace
        existing = rt.actor_directory.get_by_name(
            spec["name"], ns) if spec["name"] else None
        if existing is not None:
            continue
        rt.create_actor(spec["cls"], spec["cls_descriptor"],
                        spec["init_args"], spec["init_kwargs"],
                        spec["options"])
        counts["actors"] += 1
    return counts


class PeriodicSnapshotter:
    """Background autosave (reference: the GCS continuously writes table
    mutations to Redis; here the whole table set flushes on an interval)."""

    def __init__(self, path: str, interval_s: float = 30.0):
        import threading

        self.path = path
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                save_snapshot(self.path)
            except Exception:
                pass

    def stop(self, final_save: bool = True) -> None:
        self._stop.set()
        self._thread.join(timeout=5)
        if final_save:
            try:
                save_snapshot(self.path)
            except Exception:
                pass
