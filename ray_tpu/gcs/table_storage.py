"""Pluggable GCS table storage.

Reference: src/ray/gcs/store_client/ (InMemoryStoreClient,
RedisStoreClient) under gcs_table_storage.h — named tables of
key -> bytes rows behind one interface, so the GCS survives a restart
when backed by durable storage (the reference's external Redis; here
stdlib sqlite3 in WAL mode).
"""

from __future__ import annotations

import os
import sqlite3
import threading
from typing import Dict, List, Optional

# table names mirror gcs_table_storage.h's table set
NODE_TABLE = "node"
ACTOR_TABLE = "actor"
PG_TABLE = "placement_group"
JOB_TABLE = "job"
KV_TABLE = "internal_kv"


class GcsTableStorage:
    """key -> bytes rows in named tables."""

    def put(self, table: str, key: bytes, value: bytes) -> None:
        raise NotImplementedError

    def get(self, table: str, key: bytes) -> Optional[bytes]:
        raise NotImplementedError

    def delete(self, table: str, key: bytes) -> None:
        raise NotImplementedError

    def keys(self, table: str) -> List[bytes]:
        raise NotImplementedError

    def all(self, table: str) -> Dict[bytes, bytes]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class InMemoryTableStorage(GcsTableStorage):
    """reference: store_client/in_memory_store_client.h"""

    def __init__(self):
        self._lock = threading.Lock()
        self._tables: Dict[str, Dict[bytes, bytes]] = {}

    def put(self, table: str, key: bytes, value: bytes) -> None:
        with self._lock:
            self._tables.setdefault(table, {})[key] = value

    def get(self, table: str, key: bytes) -> Optional[bytes]:
        with self._lock:
            return self._tables.get(table, {}).get(key)

    def delete(self, table: str, key: bytes) -> None:
        with self._lock:
            self._tables.get(table, {}).pop(key, None)

    def keys(self, table: str) -> List[bytes]:
        with self._lock:
            return list(self._tables.get(table, {}))

    def all(self, table: str) -> Dict[bytes, bytes]:
        with self._lock:
            return dict(self._tables.get(table, {}))


class SqliteTableStorage(GcsTableStorage):
    """Durable backend: one sqlite file, one SQL table per GCS table,
    WAL journaling so concurrent readers never block the writer (the
    role Redis plays for the reference GCS)."""

    def __init__(self, path: str):
        self.path = path
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        self._lock = threading.Lock()
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute("PRAGMA synchronous=NORMAL")
        self._created: set = set()

    def _table(self, table: str) -> str:
        if not table.replace("_", "").isalnum():
            raise ValueError(f"bad table name {table!r}")
        name = f"gcs_{table}"
        if name not in self._created:
            with self._lock:
                self._conn.execute(
                    f"CREATE TABLE IF NOT EXISTS {name} "
                    "(key BLOB PRIMARY KEY, value BLOB)")
                self._conn.commit()
            self._created.add(name)
        return name

    def put(self, table: str, key: bytes, value: bytes) -> None:
        name = self._table(table)
        with self._lock:
            self._conn.execute(
                f"INSERT INTO {name} (key, value) VALUES (?, ?) "
                "ON CONFLICT(key) DO UPDATE SET value = excluded.value",
                (key, value))
            self._conn.commit()

    def get(self, table: str, key: bytes) -> Optional[bytes]:
        name = self._table(table)
        with self._lock:
            row = self._conn.execute(
                f"SELECT value FROM {name} WHERE key = ?", (key,)
            ).fetchone()
        return row[0] if row else None

    def delete(self, table: str, key: bytes) -> None:
        name = self._table(table)
        with self._lock:
            self._conn.execute(f"DELETE FROM {name} WHERE key = ?",
                               (key,))
            self._conn.commit()

    def keys(self, table: str) -> List[bytes]:
        name = self._table(table)
        with self._lock:
            rows = self._conn.execute(f"SELECT key FROM {name}").fetchall()
        return [r[0] for r in rows]

    def all(self, table: str) -> Dict[bytes, bytes]:
        name = self._table(table)
        with self._lock:
            rows = self._conn.execute(
                f"SELECT key, value FROM {name}").fetchall()
        return {k: v for k, v in rows}

    def close(self) -> None:
        with self._lock:
            self._conn.close()


def open_table_storage(path: Optional[str]) -> GcsTableStorage:
    """path=None -> in-memory (state dies with the GCS process);
    otherwise sqlite-backed durability."""
    if path:
        return SqliteTableStorage(path)
    return InMemoryTableStorage()
