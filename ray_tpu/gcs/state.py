"""GlobalState — cluster introspection tables.

Reference: python/ray/state.py:20 (GlobalState over GlobalStateAccessor:
actor_table, node_table, placement_group_table, jobs) and
python/ray/internal/internal_api.py (``ray memory`` ownership dump).
Reads come straight from the runtime's authoritative structures — the
same data the reference's GCS tables hold.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ray_tpu.core import runtime as rt_mod


def _runtime():
    rt = rt_mod.global_runtime
    if rt is None or rt.is_shutdown:
        raise RuntimeError("ray_tpu is not initialized")
    return rt


class GlobalState:
    # ------------------------------------------------------------- nodes
    def node_table(self) -> List[Dict[str, Any]]:
        return _runtime().nodes()

    # ------------------------------------------------------------ actors
    def actor_table(self) -> Dict[str, Dict[str, Any]]:
        rt = _runtime()
        out = {}
        for rec in rt.actor_directory.list():
            out[rec.actor_id.hex()] = {
                "ActorID": rec.actor_id.hex(),
                "State": rec.state.name,
                "Name": rec.name or "",
                "Namespace": rec.namespace,
                "NodeID": rec.node_id.hex() if rec.node_id else None,
                "NumRestarts": rec.num_restarts,
                "RestartsRemaining": rec.restarts_remaining,
                "DeathCause": rec.death_cause,
                "ClassName": rec.creation_spec.cls_descriptor,
            }
        return out

    # --------------------------------------------------- placement groups
    def placement_group_table(self) -> Dict[str, Dict[str, Any]]:
        rt = _runtime()
        out = {}
        with rt.pg_manager._lock:
            groups = dict(rt.pg_manager._groups)
        for pg_id, pg in groups.items():
            out[pg_id.hex()] = {
                "PlacementGroupID": pg_id.hex(),
                "Name": pg.name or "",
                "State": pg.state.name,
                "Strategy": pg.strategy,
                "Bundles": [dict(b) for b in pg.bundles],
                "BundleNodes": [n.hex() if n else None
                                for n in pg.bundle_nodes],
            }
        return out

    # ------------------------------------------------------------ objects
    def object_table(self) -> Dict[str, Dict[str, Any]]:
        rt = _runtime()
        from ray_tpu._private.ids import ObjectID

        out = {}
        for oid_hex, entry in rt.reference_counter.dump().items():
            stored = rt.object_store.peek(ObjectID.from_hex(oid_hex))
            out[oid_hex] = {
                "ObjectID": oid_hex,
                "LocalRefCount": entry.get("local", 0),
                "SubmittedTaskRefCount": entry.get("submitted", 0),
                "Borrowers": entry.get("borrowers", 0),
                "Pinned": entry.get("pinned", False),
                "Present": stored is not None,
                "SizeBytes": stored.size if stored is not None else 0,
            }
        return out

    def memory_summary(self) -> str:
        """``ray memory`` — ownership/refcount dump."""
        rows = self.object_table().values()
        total = sum(r["SizeBytes"] for r in rows)
        lines = [
            f"{len(rows)} objects tracked, "
            f"{total / (1024 ** 2):.3f} MiB resident",
            f"{'ObjectID':<44} {'refs':>5} {'task_refs':>9} "
            f"{'present':>8} {'bytes':>12}",
        ]
        for r in rows:
            lines.append(
                f"{r['ObjectID']:<44} {r['LocalRefCount']:>5} "
                f"{r['SubmittedTaskRefCount']:>9} "
                f"{str(r['Present']):>8} {r['SizeBytes']:>12}")
        return "\n".join(lines)

    # --------------------------------------------------------------- jobs
    def job_table(self) -> List[Dict[str, Any]]:
        rt = _runtime()
        return [{
            "JobID": rt.job_id.hex(),
            "Namespace": rt.namespace,
            "Alive": not rt.is_shutdown,
        }]

    def cluster_resources(self) -> Dict[str, float]:
        return _runtime().cluster_resources()

    def available_resources(self) -> Dict[str, float]:
        return _runtime().available_resources()


state = GlobalState()


def actors(actor_id: Optional[str] = None):
    table = state.actor_table()
    return table if actor_id is None else table.get(actor_id)


def nodes():
    return state.node_table()


def memory_summary() -> str:
    return state.memory_summary()


def timeline(filename: Optional[str] = None):
    from ray_tpu.observability.profiling import timeline as _timeline

    return _timeline(filename)


def actor_node_of(handle) -> "Optional[str]":
    """Node id hosting an actor handle (the locality signal behind
    dataset.split(locality_hints=...) — reference dataset.py:735 maps
    hint actors to nodes through the actor table)."""
    actor_id = getattr(handle, "_actor_id", None) or getattr(
        handle, "actor_id", None)
    if actor_id is None:
        return None
    rt = _runtime()
    rec = rt.actor_directory.get(actor_id)
    if rec is None or rec.node_id is None:
        return None
    return rec.node_id.hex()
