"""User-facing exception hierarchy.

Mirrors the reference's python/ray/exceptions.py: errors raised inside a
remote task are captured with their traceback, shipped back as the task's
return object, and re-raised at every ``get`` with a cause chain that names
the remote function and the process it died in.
"""

from __future__ import annotations

import traceback
from typing import Optional


class RayTpuError(Exception):
    """Base class for all framework errors."""


class CrossLanguageError(RayTpuError):
    pass


class TaskError(RayTpuError):
    pass


class RayTaskError(TaskError):
    """Wraps an exception raised by user code inside a remote task.

    Stored as the task's return object; re-raised on ``get``. Carries the
    remote traceback text so the user sees where the failure happened.
    (reference: python/ray/exceptions.py RayTaskError)
    """

    def __init__(
        self,
        function_name: str,
        traceback_str: str,
        cause: Optional[BaseException] = None,
        pid: int = 0,
        node_hex: str = "",
    ):
        self.function_name = function_name
        self.traceback_str = traceback_str
        self.cause = cause
        self.pid = pid
        self.node_hex = node_hex
        super().__init__(self._message())

    def _message(self) -> str:
        return (
            f"{type(self.cause).__name__ if self.cause else 'Error'} in "
            f"{self.function_name} (pid={self.pid}, node={self.node_hex[:8]}):\n"
            f"{self.traceback_str}"
        )

    @classmethod
    def from_exception(cls, function_name: str, exc: BaseException, pid: int = 0,
                       node_hex: str = "") -> "RayTaskError":
        tb = "".join(
            traceback.format_exception(type(exc), exc, exc.__traceback__)
        )
        return cls(function_name, tb, cause=exc, pid=pid, node_hex=node_hex)

    def as_instanceof_cause(self) -> BaseException:
        """Return an exception that is both a RayTaskError and an instance
        of the user's exception class, so ``except UserError`` works across
        the process boundary (reference exceptions.py make_dual_exception)."""
        cause = self.cause
        if cause is None or isinstance(cause, RayTaskError):
            return self
        cause_cls = type(cause)
        try:
            dual_cls = type(
                "RayTaskError(" + cause_cls.__name__ + ")",
                (RayTaskError, cause_cls),
                # accept (and ignore) pickle's re-construction args so the
                # dual class survives a cloudpickle round-trip (client)
                {"__init__": lambda s, *a, **k: None},
            )
            dual = dual_cls()
            dual.function_name = self.function_name
            dual.traceback_str = self.traceback_str
            dual.cause = cause
            dual.pid = self.pid
            dual.node_hex = self.node_hex
            dual.args = (self._message(),)
            return dual
        except TypeError:
            return self


class WorkerCrashedError(TaskError):
    """The worker executing the task died mid-execution."""


class TaskCancelledError(TaskError):
    def __init__(self, task_id=None):
        self.task_id = task_id
        super().__init__(
            "This task or its dependency was cancelled"
            + (f" (task {task_id})" if task_id else "")
        )


class RayActorError(RayTpuError):
    """The actor died before or while executing a submitted method."""

    def __init__(self, message: str = "The actor died unexpectedly before "
                 "finishing this task."):
        super().__init__(message)


class ActorDiedError(RayActorError):
    pass


class ActorInitError(RayActorError):
    """The actor's ``__init__`` (or class deserialization) raised — a
    DETERMINISTIC creation failure. Raylets raise it so the GCS marks
    the actor DEAD with the error instead of burning placement retries
    on other nodes (infra failures — crashes, timeouts, resource races
    — stay retryable and are never wrapped in this type)."""


class ActorUnavailableError(RayActorError):
    pass


class RetryLaterError(RayTpuError):
    """The peer is alive but overloaded — it shed this request before
    running the handler (admission-queue full, queue-deadline expiry,
    or a bounded task queue pushing back).

    Carries ``retry_after_s``, the server-suggested backoff hint; the
    resilient client honors it (and its circuit breaker uses it for the
    open window) so N callers back off at the pace the overloaded server
    asked for instead of hammering it in lockstep (reference: gRPC
    RESOURCE_EXHAUSTED + retry pushback / Ray raylet task backpressure).
    """

    def __init__(self, message: str = "server overloaded; retry later",
                 retry_after_s: float = 0.05):
        self.retry_after_s = float(retry_after_s)
        super().__init__(message)

    def __reduce__(self):
        # keep the hint across the pickled err-frame round trip (bare
        # Exception reduce would rebuild from args and drop it)
        return (type(self), (self.args[0] if self.args else "",
                             self.retry_after_s))


class BackpressureError(RetryLaterError):
    """Every replica of a serve deployment is currently shedding,
    breaker-open, or saturated — the router could not place the request
    anywhere without amplifying the overload (the serve-layer cousin of
    ``RetryLaterError``: typed, carries the soonest-retry hint, raised
    SYNCHRONOUSLY by ``handle.remote()`` so callers back off instead of
    queueing blind work against a collapsing replica set).

    Reference: Ray Serve's router backpressure / max_queued_requests
    rejection (serve/_private/router.py)."""

    def __init__(self, deployment: str = "",
                 message: str = "", retry_after_s: float = 0.1):
        self.deployment = deployment
        super().__init__(
            message or (f"deployment {deployment!r}: all replicas are "
                        f"shedding or unavailable; retry later"),
            retry_after_s=retry_after_s)

    def __reduce__(self):
        return (type(self), (self.deployment,
                             self.args[0] if self.args else "",
                             self.retry_after_s))


class ObjectCorruptedError(RayTpuError):
    """A stored or transferred object's payload failed its checksum —
    a flipped bit on the wire, a torn spill file, or a scribbled shm
    segment (the integrity plane, cluster/integrity.py). The detecting
    holder discards the corrupt replica; callers recover by re-pulling
    from another holder or reconstructing via lineage, so the driver
    sees the correct value or this typed error — never garbage."""

    def __init__(self, object_id_hex: str = "", seam: str = "",
                 message: str = ""):
        self.object_id_hex = object_id_hex
        self.seam = seam
        super().__init__(
            message
            or f"Object {object_id_hex[:16] or '?'} failed checksum "
               f"verification at {seam or 'an unknown seam'}; the "
               f"corrupt replica was discarded.")

    def __reduce__(self):
        # keep the id/seam across the pickled err-frame round trip
        return (type(self), (self.object_id_hex, self.seam,
                             self.args[0] if self.args else ""))


class ObjectStoreFullError(RayTpuError):
    pass


class OutOfDiskError(RayTpuError):
    pass


class ObjectLostError(RayTpuError):
    def __init__(self, object_id_hex: str, message: str = ""):
        self.object_id_hex = object_id_hex
        super().__init__(
            message
            or f"Object {object_id_hex[:16]} is lost (all copies unavailable "
            "and reconstruction disabled or exhausted)."
        )


class ObjectFetchTimedOutError(ObjectLostError):
    pass


class ReferenceCountingAssertionError(ObjectLostError):
    pass


class OwnerDiedError(ObjectLostError):
    def __init__(self, object_id_hex: str):
        super().__init__(
            object_id_hex,
            f"Object {object_id_hex[:16]} cannot be retrieved: its owner "
            "process died, so its metadata and lineage are gone.",
        )


class ObjectReconstructionFailedError(ObjectLostError):
    pass


class ObjectReconstructionFailedMaxAttemptsExceededError(ObjectLostError):
    pass


class ObjectReconstructionFailedLineageEvictedError(ObjectLostError):
    pass


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class PlacementGroupError(RayTpuError):
    pass


class PlacementGroupRemovedError(PlacementGroupError):
    pass


class RuntimeEnvSetupError(RayTpuError):
    pass


class PendingCallsLimitExceeded(RayTpuError):
    pass


class AsyncioActorExit(RayTpuError):
    """Raised internally by exit_actor() inside async actors."""
