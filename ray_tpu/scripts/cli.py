"""CLI — `python -m ray_tpu <command>`.

Reference: python/ray/scripts/scripts.py (`ray start/stop/status/memory/
timeline/microbenchmark`). In-process runtime means start/stop manage a
head "session" in this process; status/memory/timeline introspect it.
"""

from __future__ import annotations

import argparse
import json
import sys


def cmd_status(args) -> int:
    if getattr(args, "address", None):
        # a live process cluster: read the GCS view over the wire
        from ray_tpu.cluster.rpc import RpcClient

        client = RpcClient(args.address)
        try:
            view = client.call("cluster_view", timeout=10.0)
            summary = client.call("job_view", timeout=10.0)
        finally:
            client.close()
        nodes = view["nodes"]
        print(f"{len(nodes)} node(s)  [gcs {args.address}]")
        print(f"  actors={summary['actors']} objects={summary['objects']}"
              f" pgs={summary['pgs']}")
        total: dict = {}
        avail: dict = {}
        for nid, info in nodes.items():
            # lifecycle: ALIVE -> (DRAINING) -> DEAD; pre-drain-plane
            # GCS versions lack the "state" key, so fall back to alive
            state = info.get("state",
                             "ALIVE" if info["alive"] else "DEAD")
            print(f"  {nid[:16]} {state} {info['resources']}")
            ov = info.get("overload") or {}
            rpc_ov = ov.get("rpc") or {}
            breakers = ov.get("breakers") or {}
            open_breakers = sum(
                1 for b in breakers.values()
                if b.get("state") != "closed")
            print(f"    overload: shed="
                  f"{rpc_ov.get('shed_queue_full', 0)}+"
                  f"{rpc_ov.get('shed_deadline', 0)} "
                  f"tasks_shed={ov.get('tasks_shed', 0)} "
                  f"push_shed={ov.get('push_shed', 0)} "
                  f"breakers={len(breakers)}"
                  f" (open={open_breakers})")
            srv = info.get("serve") or {}
            print(f"    serve: unhealthy="
                  f"{int(srv.get('replicas_unhealthy', 0))} "
                  f"drains={int(srv.get('drains_completed', 0))} "
                  f"router_excluded="
                  f"{int(srv.get('router_excluded', 0))} "
                  f"backpressured="
                  f"{int(srv.get('requests_backpressured', 0))}")
            integ = info.get("integrity") or {}
            print(f"    integrity: detected="
                  f"{int(integ.get('corruption_detected', 0))} "
                  f"discarded="
                  f"{int(integ.get('corrupt_replicas_discarded', 0))} "
                  f"orphans_adopted="
                  f"{int(integ.get('orphans_adopted', 0))} "
                  f"verified_mib="
                  f"{integ.get('bytes_verified', 0) / 2**20:.1f}")
            pool = info.get("worker_pool") or {}
            print(f"    worker pool: idle="
                  f"{int(pool.get('warm_idle', 0))}/"
                  f"{int(pool.get('warm_size', 0))} "
                  f"hits={int(pool.get('warm_hits', 0))} "
                  f"misses={int(pool.get('warm_misses', 0))} "
                  f"returned={int(pool.get('warm_returned', 0))} "
                  f"reaped={int(pool.get('warm_reaped', 0))} "
                  f"create_p50_ms={pool.get('create_ms_p50') or 0}")
            thr = info.get("threads") or {}
            if thr:
                # thread roots use the raycheck RC16/RC17 report naming
                shown = sorted(set(thr.values()))
                extra = (f" +{len(shown) - 4} more"
                         if len(shown) > 4 else "")
                print(f"    threads: {len(thr)} live, roots: "
                      f"{', '.join(shown[:4])}{extra}")
            if info["alive"]:
                for k, v in info["resources"].items():
                    total[k] = total.get(k, 0.0) + v
                for k, v in info["available"].items():
                    avail[k] = avail.get(k, 0.0) + v
        print("cluster:", total)
        print("available:", avail)
        gcs_ov = view.get("overload") or {}
        print(f"gcs overload: shed_queue_full="
              f"{gcs_ov.get('shed_queue_full', 0)} shed_deadline="
              f"{gcs_ov.get('shed_deadline', 0)} replies_dropped="
              f"{gcs_ov.get('replies_dropped', 0)}")
        batch = view.get("actor_batch") or {}
        print(f"gcs actor batches: creates_batched="
              f"{int(batch.get('creates_batched', 0))} "
              f"kills_batched={int(batch.get('kills_batched', 0))}")
        drain = view.get("drain") or {}
        print(f"gcs drain: nodes_draining="
              f"{int(drain.get('nodes_draining', 0))} "
              f"drains_completed="
              f"{int(drain.get('drains_completed', 0))} "
              f"preemption_notices="
              f"{int(drain.get('preemption_notices', 0))} "
              f"objects_rereplicated="
              f"{int(drain.get('objects_rereplicated', 0))}")
        return 0
    import ray_tpu

    if not ray_tpu.is_initialized():
        ray_tpu.init()
    from ray_tpu import gcs

    nodes = gcs.nodes()
    print(f"{len(nodes)} node(s)")
    for n in nodes:
        state = "ALIVE" if n["Alive"] else "DEAD"
        print(f"  {n['NodeID'][:16]} {state} {n['Resources']}")
    print("cluster:", ray_tpu.cluster_resources())
    print("available:", ray_tpu.available_resources())
    return 0


def cmd_memory(args) -> int:
    import ray_tpu
    from ray_tpu import gcs

    if not ray_tpu.is_initialized():
        ray_tpu.init()
    print(gcs.memory_summary())
    return 0


def cmd_timeline(args) -> int:
    if getattr(args, "address", None):
        # merge every node's flight-recorder ring (clock-offset
        # corrected) into one chrome://tracing document
        from ray_tpu.cluster.rpc import RpcClient
        from ray_tpu.observability.flight_recorder import (
            merge_chrome_trace)

        client = RpcClient(args.address)
        try:
            result = client.call("collect_timeline",
                                 per_node_timeout_s=args.per_node_timeout,
                                 timeout=args.per_node_timeout * 4 + 10.0)
        finally:
            client.close()
        dumps = result["dumps"]
        trace = merge_chrome_trace(dumps)
        with open(args.output, "w") as f:
            json.dump(trace, f)
        reachable = sum(1 for d in dumps if "error" not in d)
        spans = sum(len(d.get("spans") or []) for d in dumps)
        print(f"wrote merged Chrome trace to {args.output} "
              f"({reachable}/{len(dumps)} node(s), {spans} span(s), "
              f"{len(trace['traceEvents'])} trace event(s))")
        return 0 if reachable == len(dumps) else 1
    from ray_tpu.observability import timeline

    path = timeline(args.output)
    print(f"wrote Chrome trace to {path}")
    return 0


def cmd_microbenchmark(args) -> int:
    from ray_tpu._private.ray_perf import main as perf_main

    rows = perf_main(duration=args.duration)
    if args.json:
        print(json.dumps(rows))
    else:
        for row in rows:
            print(f"{row['name']:>40}: {row['rate']:>12.1f} /s")
    return 0


def cmd_metrics(args) -> int:
    from ray_tpu.observability import prometheus_text

    print(prometheus_text())
    return 0


def cmd_up(args) -> int:
    """`ray up cluster.yaml` (reference: scripts.py up →
    commands.create_or_update_cluster)."""
    from ray_tpu.autoscaler.commands import create_or_update_cluster

    handle = create_or_update_cluster(args.cluster_config)
    print(f"cluster {handle.name} is up")
    print(f"  head: {handle.head_id} @ {handle.head_node_ip()}")
    print(f"  workers: {len(handle.worker_ids())}")
    if getattr(handle.provider, "gcs_address", None):
        print(f"  gcs address: {handle.provider.gcs_address}")
    if args.monitor:
        handle.start_monitor()
        print("  autoscaler monitor running")
        try:
            import time

            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            pass
    return 0


def cmd_down(args) -> int:
    """`ray down cluster.yaml`."""
    from ray_tpu.autoscaler.commands import teardown_cluster

    teardown_cluster(args.cluster_config,
                     keep_min_workers=args.keep_min_workers)
    print("cluster torn down")
    return 0


def cmd_job(args) -> int:
    """`ray job submit/status/logs/list/stop` (reference:
    dashboard/modules/job/cli.py)."""
    from ray_tpu.cluster.job_manager import JobSubmissionClient

    client = JobSubmissionClient(args.address)
    try:
        if args.job_command == "submit":
            job_id = client.submit_job(
                entrypoint=" ".join(args.entrypoint))
            print(f"submitted {job_id}")
            if args.wait:
                status = client.wait_until_finish(job_id,
                                                  timeout=args.timeout)
                print(f"{job_id}: {status}")
                print(client.get_job_logs(job_id), end="")
                return 0 if status == "SUCCEEDED" else 1
        elif args.job_command == "status":
            status = client.get_job_status(args.job_id)
            print(status or "NOT_FOUND")
            if status is None:
                return 1
        elif args.job_command == "logs":
            print(client.get_job_logs(args.job_id), end="")
        elif args.job_command == "list":
            for row in client.list_jobs():
                print(f"{row['job_id']:>28} {row['status']:>10} "
                      f"{row['entrypoint']}")
        elif args.job_command == "stop":
            if client.stop_job(args.job_id):
                print("stopped")
            else:
                print("not running")
                return 1
    finally:
        client.close()
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="ray_tpu", description="ray_tpu command line")
    sub = parser.add_subparsers(dest="command", required=True)
    p = sub.add_parser("status", help="cluster resource status")
    p.add_argument("--address", default=None,
                   help="GCS address of a process cluster (host:port); "
                        "omit to inspect the in-process runtime")
    sub.add_parser("memory", help="object ownership dump")
    p = sub.add_parser("timeline", help="dump Chrome trace")
    p.add_argument("-o", "--output", default="ray_tpu_timeline.json")
    p.add_argument("--address", default=None,
                   help="GCS address (host:port): merge every node's "
                        "flight-recorder buffer into one cluster-wide "
                        "trace; omit to dump the local profiler")
    p.add_argument("--per-node-timeout", type=float, default=5.0,
                   help="seconds the GCS waits on each node's buffer")
    p = sub.add_parser("microbenchmark", help="run the perf matrix")
    p.add_argument("--duration", type=float, default=1.0)
    p.add_argument("--json", action="store_true")
    sub.add_parser("metrics", help="print Prometheus metrics")
    p = sub.add_parser("up", help="bring a cluster up from a YAML config")
    p.add_argument("cluster_config")
    p.add_argument("--monitor", action="store_true",
                   help="keep running the autoscaler reconcile loop")
    p = sub.add_parser("down", help="tear a cluster down")
    p.add_argument("cluster_config")
    p.add_argument("--keep-min-workers", action="store_true")
    p = sub.add_parser("job", help="submit and manage cluster jobs")
    p.add_argument("--address", required=True,
                   help="GCS address (host:port)")
    jsub = p.add_subparsers(dest="job_command", required=True)
    js = jsub.add_parser("submit")
    js.add_argument("--wait", action="store_true")
    js.add_argument("--timeout", type=float, default=300.0)
    js.add_argument("entrypoint", nargs="+")
    for name in ("status", "logs", "stop"):
        jp = jsub.add_parser(name)
        jp.add_argument("job_id")
    jsub.add_parser("list")
    args = parser.parse_args(argv)
    return {
        "status": cmd_status,
        "memory": cmd_memory,
        "timeline": cmd_timeline,
        "microbenchmark": cmd_microbenchmark,
        "metrics": cmd_metrics,
        "up": cmd_up,
        "down": cmd_down,
        "job": cmd_job,
    }[args.command](args)


if __name__ == "__main__":
    sys.exit(main())
