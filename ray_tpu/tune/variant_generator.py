"""Config expansion: grid_search cross-products + Domain sampling.

Mirrors the reference's ray.tune.suggest.variant_generator
(python/ray/tune/suggest/variant_generator.py): generate_variants walks
nested dicts, cross-multiplies every grid_search marker, then samples
Domain/lambda leaves per variant.
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Dict, Iterator, List, Tuple

from ray_tpu.tune.sample import Domain


def _is_grid(v: Any) -> bool:
    return isinstance(v, dict) and set(v.keys()) == {"grid_search"}


def _walk(spec: Any, path: Tuple = ()) -> Iterator[Tuple[Tuple, Any]]:
    if isinstance(spec, dict) and not _is_grid(spec):
        for k, v in spec.items():
            yield from _walk(v, path + (k,))
    else:
        yield path, spec


def _set_path(d: Dict, path: Tuple, value: Any) -> None:
    for k in path[:-1]:
        d = d.setdefault(k, {})
    d[path[-1]] = value


def _deepcopy_spec(spec: Dict) -> Dict:
    out = {}
    for k, v in spec.items():
        out[k] = _deepcopy_spec(v) if isinstance(v, dict) and not _is_grid(v) \
            else v
    return out


def count_variants(spec: Dict) -> int:
    n = 1
    for _, v in _walk(spec):
        if _is_grid(v):
            n *= len(v["grid_search"])
    return n


def generate_variants(spec: Dict, rng: random.Random = None
                      ) -> Iterator[Tuple[str, Dict]]:
    """Yields (variant_tag, resolved_config) pairs."""
    rng = rng or random.Random()
    grid_leaves: List[Tuple[Tuple, List[Any]]] = []
    for path, v in _walk(spec):
        if _is_grid(v):
            grid_leaves.append((path, v["grid_search"]))
    grids = [vals for _, vals in grid_leaves]
    for combo in itertools.product(*grids) if grids else [()]:
        config = _deepcopy_spec(spec)
        tags = []
        for (path, _), value in zip(grid_leaves, combo):
            _set_path(config, path, value)
            tags.append(f"{'/'.join(map(str, path))}={value}")
        # resolve sampled leaves after grid substitution
        for path, v in list(_walk(config)):
            if isinstance(v, Domain):
                _set_path(config, path, v.sample(rng))
            elif callable(v) and getattr(v, "__name__", "") == "<lambda>":
                resolved = _try_call(v, config)
                _set_path(config, path, resolved)
        yield ",".join(tags), config


def _try_call(fn, config):
    try:
        return fn({"config": config})
    except TypeError:
        return fn()
