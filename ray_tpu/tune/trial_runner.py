"""Trial runner — trials as actors, event loop on the driver.

Mirrors the reference's ray.tune TrialRunner + RayTrialExecutor
(python/ray/tune/trial_runner.py, ray_trial_executor.py): each trial's
Trainable is hosted in an actor; the runner keeps one in-flight
``train()`` call per running trial, processes completions in arrival
order via ray_tpu.wait, and lets the scheduler stop/pause/perturb.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.tune.schedulers import FIFOScheduler, TrialScheduler
from ray_tpu.tune.trial import Trial

logger = logging.getLogger(__name__)


class TrialRunner:
    def __init__(self, scheduler: Optional[TrialScheduler] = None,
                 max_concurrent_trials: Optional[int] = None,
                 callbacks: Optional[List] = None,
                 search_alg=None,
                 trial_factory=None,
                 max_trials: Optional[int] = None):
        self.scheduler = scheduler or FIFOScheduler()
        self.trials: List[Trial] = []
        self.max_concurrent = max_concurrent_trials
        self.callbacks = list(callbacks) if callbacks else []
        self._in_flight: Dict[Any, Trial] = {}  # result ref -> trial
        self._actor_cls_cache: Dict[type, Any] = {}
        # search-algorithm plumbing (reference: trial_runner holds a
        # SearchGenerator wrapping the Searcher)
        self.search_alg = search_alg
        self._trial_factory = trial_factory
        self._max_trials = max_trials
        self._search_exhausted = search_alg is None
        self._trial_counter = 0
        # resume support: seed past a previous run's searcher trials so
        # new suggestions never reuse a restored trial's id
        self.trial_id_offset = 0

    # -------------------------------------------------------------- setup
    def add_trial(self, trial: Trial) -> None:
        self.trials.append(trial)
        self.scheduler.on_trial_add(self, trial)

    def is_finished(self) -> bool:
        return self._search_exhausted and all(
            t.status in (Trial.TERMINATED, Trial.ERROR)
            for t in self.trials)

    def has_resources_for(self, trial: Trial) -> bool:
        # Account against the runner's own committed demand, not the live
        # view: actor creation is asynchronous, so available_resources()
        # lags starts and would over-admit (the reference's trial
        # executor keeps its own committed-resources ledger the same way,
        # ray_trial_executor.py _committed_resources).
        total = ray_tpu.cluster_resources()
        used = {"CPU": 0.0, "GPU": 0.0}
        for t in self.trials:
            if t.status != Trial.RUNNING:
                continue
            o = t.actor_options()
            used["CPU"] += o.get("num_cpus", 1)
            used["GPU"] += o.get("num_gpus", 0) or 0
            for k, v in (o.get("resources") or {}).items():
                used[k] = used.get(k, 0.0) + v
        opts = trial.actor_options()
        if total.get("CPU", 0) - used["CPU"] < opts.get("num_cpus", 1):
            return False
        if opts.get("num_gpus", 0) and \
                total.get("GPU", 0) - used["GPU"] < opts["num_gpus"]:
            return False
        for k, v in (opts.get("resources") or {}).items():
            if total.get(k, 0) - used.get(k, 0.0) < v:
                return False
        return True

    def _remote_cls(self, trainable_cls: type):
        if trainable_cls not in self._actor_cls_cache:
            self._actor_cls_cache[trainable_cls] = \
                ray_tpu.remote(trainable_cls)
        return self._actor_cls_cache[trainable_cls]

    # ------------------------------------------------------------- running
    def _num_running(self) -> int:
        return sum(1 for t in self.trials if t.status == Trial.RUNNING)

    def _maybe_start_trials(self) -> None:
        while True:
            if self.max_concurrent and \
                    self._num_running() >= self.max_concurrent:
                return
            trial = self.scheduler.choose_trial_to_run(self)
            if trial is None:
                # Only pull a new suggestion when no created trial is
                # waiting to start — pending trials blocked on resources
                # must NOT drain the searcher (adaptive searchers need
                # completed results before suggesting more).
                if any(t.status == Trial.PENDING for t in self.trials):
                    return
                if not self._refill_from_searcher():
                    return
                continue
            self._start_trial(trial)

    def _refill_from_searcher(self) -> bool:
        """Pull the next suggestion into a new trial. Returns True if a
        trial was added (reference: SearchGenerator.create_trial_if_possible)."""
        if self._search_exhausted or self.search_alg is None:
            return False
        if self._max_trials is not None and \
                self._trial_counter - self.trial_id_offset \
                >= self._max_trials:
            self._search_exhausted = True
            return False
        from ray_tpu.tune.suggest import FINISHED

        trial_id = f"trial_{self._trial_counter}"
        suggestion = self.search_alg.suggest(trial_id)
        if suggestion is FINISHED:
            self._search_exhausted = True
            return False
        if suggestion is None:
            # not ready (e.g. concurrency-limited). If nothing is running
            # that could ever unblock it, treat as exhausted to avoid a
            # live-lock.
            if not self._in_flight and not any(
                    t.status in (Trial.RUNNING, Trial.PENDING, Trial.PAUSED)
                    for t in self.trials):
                self._search_exhausted = True
            return False
        trial = self._trial_factory(suggestion, trial_id)
        self._trial_counter += 1
        self.add_trial(trial)
        return True

    def _start_trial(self, trial: Trial) -> None:
        cls = self._remote_cls(trial.trainable_cls)
        trial.runner = cls.options(**trial.actor_options()).remote(
            trial.config, trial.trial_id)
        if trial.checkpoint is not None:
            ray_tpu.get(trial.runner.restore.remote(trial.checkpoint))
        trial.status = Trial.RUNNING
        self._queue_train(trial)

    def _queue_train(self, trial: Trial) -> None:
        ref = trial.runner.train.remote()
        self._in_flight[ref] = trial

    def step(self) -> None:
        """One event-loop turn."""
        self._maybe_start_trials()
        if not self._in_flight:
            return
        ready, _ = ray_tpu.wait(list(self._in_flight), num_returns=1)
        ref = ready[0]
        trial = self._in_flight.pop(ref)
        if trial.status != Trial.RUNNING:
            return
        try:
            result = ray_tpu.get(ref)
        except Exception as e:  # noqa: BLE001
            self._handle_trial_error(trial, e)
            return
        trial.update_result(result)
        for cb in self.callbacks:
            cb.on_trial_result(self, trial, result)
        if self.search_alg is not None:
            self.search_alg.on_trial_result(trial.trial_id, result)
        if trial.should_stop(result):
            self._complete_trial(trial, result)
            return
        decision = self.scheduler.on_trial_result(self, trial, result)
        if decision == TrialScheduler.STOP:
            self._complete_trial(trial, result)
        elif decision == TrialScheduler.PAUSE:
            self._pause_trial(trial)
        elif trial.status == Trial.RUNNING and trial.runner is not None:
            # the scheduler hook may have torn the actor down itself
            # (e.g. PBT exploit on a trainable whose reset_config fails,
            # which re-queues the trial as PENDING)
            self._queue_train(trial)

    def run_loop(self) -> None:
        while not self.is_finished():
            self.step()

    # ----------------------------------------------------------- lifecycle
    def _complete_trial(self, trial: Trial, result: Dict) -> None:
        trial.status = Trial.TERMINATED
        self.scheduler.on_trial_complete(self, trial, result)
        if self.search_alg is not None:
            self.search_alg.on_trial_complete(trial.trial_id, result)
        self._stop_actor(trial)

    def _pause_trial(self, trial: Trial) -> None:
        trial.checkpoint = ray_tpu.get(trial.runner.save.remote())
        trial.status = Trial.PAUSED
        self._stop_actor(trial)

    def _handle_trial_error(self, trial: Trial, error: Exception) -> None:
        trial.num_failures += 1
        self._stop_actor(trial)
        if trial.num_failures <= trial.max_failures:
            logger.warning("trial %s failed (%d/%d); restarting from "
                           "checkpoint", trial, trial.num_failures,
                           trial.max_failures)
            trial.status = Trial.PENDING
            return
        trial.status = Trial.ERROR
        trial.error = repr(error)
        if self.search_alg is not None:
            self.search_alg.on_trial_complete(trial.trial_id, error=True)
        # synchronous schedulers (HyperBand) gate rounds on every live
        # bracket member; an errored trial must not stall its round
        self.scheduler.on_trial_remove(self, trial)

    def _stop_actor(self, trial: Trial) -> None:
        if trial.runner is not None:
            # drop any in-flight result from this incarnation
            for ref, t in list(self._in_flight.items()):
                if t is trial:
                    del self._in_flight[ref]
            try:
                ray_tpu.get(trial.runner.stop.remote())
            except Exception:  # noqa: BLE001
                pass
            ray_tpu.kill(trial.runner)
            trial.runner = None

    # ------------------------------------------------------ scheduler hooks
    def save_trial(self, trial: Trial) -> Optional[Dict]:
        if trial.runner is None:
            return trial.checkpoint
        try:
            return ray_tpu.get(trial.runner.save.remote())
        except Exception:  # noqa: BLE001
            return None

    def restart_trial_with(self, trial: Trial, new_config: Dict,
                           checkpoint: Dict) -> None:
        """PBT exploit: reload `trial` from `checkpoint` with new config."""
        trial.config = new_config
        trial.checkpoint = checkpoint
        if trial.runner is None:
            return
        reset_ok = False
        try:
            reset_ok = ray_tpu.get(
                trial.runner.reset.remote(new_config, trial.trial_id))
        except Exception:  # noqa: BLE001
            reset_ok = False
        if reset_ok:
            ray_tpu.get(trial.runner.restore.remote(checkpoint))
        else:
            self._stop_actor(trial)
            trial.status = Trial.PENDING
