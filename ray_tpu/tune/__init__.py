"""ray_tpu.tune — hyperparameter search (reference: python/ray/tune/)."""

from ray_tpu.tune.analysis import ExperimentAnalysis  # noqa: F401
from ray_tpu.tune.sample import (  # noqa: F401
    choice,
    grid_search,
    loguniform,
    qrandint,
    quniform,
    randint,
    randn,
    sample_from,
    uniform,
)
from ray_tpu.tune.schedulers import (  # noqa: F401
    PB2,
    AsyncHyperBandScheduler,
    FIFOScheduler,
    HyperBandForBOHB,
    HyperBandScheduler,
    MedianStoppingRule,
    PopulationBasedTraining,
    TrialScheduler,
)
from ray_tpu.tune.syncer import DirSyncer, Syncer  # noqa: F401
from ray_tpu.tune.trainable import (  # noqa: F401
    Trainable,
    checkpoint_dir,
    get_trial_id,
    report,
)
from ray_tpu.tune import suggest  # noqa: F401
from ray_tpu.tune.trial import Trial  # noqa: F401
from ray_tpu.tune.trial_runner import TrialRunner  # noqa: F401
from ray_tpu.tune.tune import run, with_parameters  # noqa: F401
