"""Searcher base class and composable wrappers.

Mirrors the reference's suggest/suggestion.py (Searcher,
ConcurrencyLimiter) and suggest/basic_variant.py / repeater.py. The
contract:

  suggest(trial_id) -> resolved config dict
                     | None      (nothing *right now*; ask again later)
                     | FINISHED  (search space exhausted; stop creating)

  on_trial_result(trial_id, result)           intermediate results
  on_trial_complete(trial_id, result, error)  terminal notification
"""

from __future__ import annotations

import copy
import random
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.tune.sample import Domain
from ray_tpu.tune.variant_generator import generate_variants

FINISHED = "FINISHED"


def walk_domains(spec: Dict, path: Tuple = ()) -> List[Tuple[Tuple, Domain]]:
    """Flatten a (possibly nested) config spec into (path, Domain) leaves."""
    out: List[Tuple[Tuple, Domain]] = []
    for k, v in spec.items():
        if isinstance(v, dict) and "grid_search" not in v:
            out.extend(walk_domains(v, path + (k,)))
        elif isinstance(v, Domain):
            out.append((path + (k,), v))
    return out


def modelable_domains(spec: Dict) -> List[Tuple[Tuple, Domain]]:
    """Domains a model-based searcher can reason about. Function domains
    (sample_from/randn) have no bounds — they stay sample-only and are
    resolved by resolve_spec, never modeled."""
    from ray_tpu.tune.sample import Categorical, Float, Integer

    return [(p, d) for p, d in walk_domains(spec)
            if isinstance(d, (Float, Integer, Categorical))]


def snap_int(dom, v: float) -> int:
    """Clamp a continuous suggestion into an Integer domain, staying ON
    the q-grid when the domain is quantized (clamping to upper-1 after
    rounding can otherwise land off-grid, e.g. qrandint(0,8,4) -> 7)."""
    import math

    q = getattr(dom, "_quantum", None)
    if q:
        v = round(v / q) * q
        hi = ((dom.upper - 1) // q) * q
        lo = math.ceil(dom.lower / q) * q
        return int(min(hi, max(lo, v)))
    return int(min(dom.upper - 1, max(dom.lower, round(v))))


def snap_float(dom, v: float) -> float:
    """Clamp a continuous suggestion into a Float domain, on-grid for
    quantized domains."""
    import math

    q = getattr(dom, "_quantum", None)
    if q:
        v = round(v / q) * q
        hi = math.floor(dom.upper / q) * q
        lo = math.ceil(dom.lower / q) * q
        return min(hi, max(lo, v))
    return min(dom.upper, max(dom.lower, v))


def extract_values(config: Dict, domains) -> Dict[Tuple, Any]:
    """Read back what a resolved config actually chose for each domain
    path — what model-based searchers record as observations."""
    chosen: Dict[Tuple, Any] = {}
    for path, _dom in domains:
        node = config
        for k in path:
            node = node[k]
        chosen[path] = node
    return chosen


def set_path(config: Dict, path: Tuple, value: Any) -> None:
    d = config
    for k in path[:-1]:
        d = d.setdefault(k, {})
    d[path[-1]] = value


def resolve_spec(spec: Dict, overrides: Dict[Tuple, Any],
                 rng: Optional[random.Random] = None) -> Dict:
    """Copy `spec` replacing Domain leaves: from `overrides` when given,
    sampled otherwise."""
    rng = rng or random
    config = copy.deepcopy({k: v for k, v in spec.items()})
    for path, domain in walk_domains(spec):
        value = overrides.get(path, None)
        if value is None:
            value = domain.sample(rng)
        set_path(config, path, value)
    return config


def _contains_grid_search(spec: Dict) -> bool:
    for v in spec.values():
        if isinstance(v, dict):
            if "grid_search" in v or _contains_grid_search(v):
                return True
    return False


class Searcher:
    """Plugin seam for search algorithms (reference: suggest/suggestion.py
    Searcher)."""

    # grid_search markers are only consumed by the variant generator;
    # model-based searchers must reject them rather than hand trials the
    # raw marker dict (reference raises the same way)
    supports_grid_search = False

    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None):
        self.metric = metric
        self.mode = mode or "max"
        self._space: Optional[Dict] = None

    # ------------------------------------------------------------ contract
    def set_search_properties(self, metric: Optional[str],
                              mode: Optional[str], config: Dict) -> bool:
        if metric:
            self.metric = metric
        if mode:
            self.mode = mode
        if self._space is None:
            self._space = config
        if not self.supports_grid_search and self._space and \
                _contains_grid_search(self._space):
            raise ValueError(
                f"{type(self).__name__} does not support grid_search "
                "parameters; use BasicVariantGenerator (or plain "
                "tune.run without search_alg) for grid sweeps")
        return True

    def suggest(self, trial_id: str):
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: Dict) -> None:
        pass

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict] = None,
                          error: bool = False) -> None:
        pass

    # -------------------------------------------------------------- helpers
    def metric_of(self, result: Optional[Dict]) -> Optional[float]:
        if not result or self.metric is None:
            return None
        v = result.get(self.metric)
        return None if v is None else float(v)

    def signed(self, value: float) -> float:
        """Normalize to maximization."""
        return value if self.mode == "max" else -value


class BasicVariantGenerator(Searcher):
    """The default: grid expansion x random sampling, exactly what
    generate_variants yields (reference: suggest/basic_variant.py)."""

    supports_grid_search = True

    def __init__(self, num_samples: int = 1,
                 seed: Optional[int] = None):
        super().__init__()
        self.num_samples = num_samples
        self._rng = random.Random(seed)
        self._queue: Optional[List[Dict]] = None

    def suggest(self, trial_id: str):
        if self._queue is None:
            if self._space is None:
                return FINISHED
            self._queue = []
            for _ in range(self.num_samples):
                for _tag, cfg in generate_variants(self._space, self._rng):
                    self._queue.append(cfg)
        if not self._queue:
            return FINISHED
        return self._queue.pop(0)


class ConcurrencyLimiter(Searcher):
    """Cap in-flight suggestions (reference: suggest/suggestion.py
    ConcurrencyLimiter)."""

    supports_grid_search = True  # delegate; the inner searcher checks

    def __init__(self, searcher: Searcher, max_concurrent: int):
        super().__init__(searcher.metric, searcher.mode)
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set = set()

    def set_search_properties(self, metric, mode, config) -> bool:
        super().set_search_properties(metric, mode, config)
        return self.searcher.set_search_properties(metric, mode, config)

    def suggest(self, trial_id: str):
        if len(self._live) >= self.max_concurrent:
            return None
        suggestion = self.searcher.suggest(trial_id)
        if isinstance(suggestion, dict):
            self._live.add(trial_id)
        return suggestion

    def on_trial_result(self, trial_id, result) -> None:
        self.searcher.on_trial_result(trial_id, result)

    def on_trial_complete(self, trial_id, result=None, error=False) -> None:
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result, error)


class Repeater(Searcher):
    """Run each suggestion `repeat` times and report the mean to the
    wrapped searcher — for noisy objectives (reference:
    suggest/repeater.py)."""

    supports_grid_search = True  # delegate; the inner searcher checks

    def __init__(self, searcher: Searcher, repeat: int = 3):
        super().__init__(searcher.metric, searcher.mode)
        self.searcher = searcher
        self.repeat = repeat
        self._group_of: Dict[str, str] = {}        # trial_id -> group id
        self._config_of: Dict[str, Dict] = {}      # group id -> config
        self._remaining: Dict[str, int] = {}       # group id -> to hand out
        self._outstanding: Dict[str, int] = {}     # group id -> in flight
        self._scores: Dict[str, List[float]] = {}  # group id -> results
        self._group_counter = 0

    def set_search_properties(self, metric, mode, config) -> bool:
        super().set_search_properties(metric, mode, config)
        return self.searcher.set_search_properties(metric, mode, config)

    def suggest(self, trial_id: str):
        for gid, left in self._remaining.items():
            if left > 0:
                self._remaining[gid] = left - 1
                self._outstanding[gid] += 1
                self._group_of[trial_id] = gid
                return copy.deepcopy(self._config_of[gid])
        suggestion = self.searcher.suggest(f"group_{self._group_counter}")
        if not isinstance(suggestion, dict):
            return suggestion
        gid = f"group_{self._group_counter}"
        self._group_counter += 1
        self._config_of[gid] = suggestion
        self._remaining[gid] = self.repeat - 1
        self._outstanding[gid] = 1
        self._scores[gid] = []
        self._group_of[trial_id] = gid
        return copy.deepcopy(suggestion)

    def on_trial_complete(self, trial_id, result=None, error=False) -> None:
        gid = self._group_of.pop(trial_id, None)
        if gid is None:
            return
        self._outstanding[gid] -= 1
        value = self.metric_of(result)
        if not error and value is not None:
            self._scores[gid].append(value)
        # the group closes when every handed-out repeat has reported,
        # successes and errors alike — an errored repeat must not stall
        # the group (mean over whatever succeeded; all-errors -> error)
        if self._remaining[gid] == 0 and self._outstanding[gid] == 0:
            scores = self._scores.pop(gid, [])
            self._remaining.pop(gid, None)
            self._outstanding.pop(gid, None)
            self._config_of.pop(gid, None)
            mean_result = None
            if scores and self.metric:
                mean_result = {self.metric: sum(scores) / len(scores)}
            self.searcher.on_trial_complete(
                gid, mean_result, error=not scores)
