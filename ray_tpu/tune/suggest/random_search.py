"""Independent random sampling over the Domain space — the baseline
every model-based searcher is judged against."""

from __future__ import annotations

import random
from typing import Optional

from ray_tpu.tune.suggest.search import FINISHED, Searcher, resolve_spec


class RandomSearcher(Searcher):
    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None,
                 max_suggestions: Optional[int] = None,
                 seed: Optional[int] = None):
        super().__init__(metric, mode)
        self.max_suggestions = max_suggestions
        self._rng = random.Random(seed)
        self._count = 0

    def suggest(self, trial_id: str):
        if self._space is None:
            return FINISHED
        if self.max_suggestions is not None and \
                self._count >= self.max_suggestions:
            return FINISHED
        self._count += 1
        return resolve_spec(self._space, {}, self._rng)
