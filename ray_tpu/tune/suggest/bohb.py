"""BOHB's model component — multi-fidelity TPE.

Reference: python/ray/tune/suggest/bohb.py (TuneBOHB wrapping HpBandSter's
ConfigSpace KDE model). The defining idea (Falkner et al. 2018): density
models are built PER BUDGET — observations at 3 iterations and at 81
iterations describe different objectives — and suggestions come from the
model of the LARGEST budget that has enough observations, so early rungs
seed the model and later rungs refine it. This build implements that on
the repo's native TPE (suggest/tpe.py) instead of an external KDE
library; pair it with schedulers.HyperBandForBOHB."""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from ray_tpu.tune.suggest.tpe import TPESearcher


class BOHBSearcher(TPESearcher):
    def __init__(self, time_attr: str = "training_iteration", **kwargs):
        super().__init__(**kwargs)
        self.time_attr = time_attr
        # budget -> trial_id -> (params, signed score); keyed by trial so
        # a trial re-reporting at the same rung replaces, not appends
        self._buckets: Dict[float, Dict[str, Tuple]] = {}

    # ------------------------------------------------------------ feedback
    def on_trial_result(self, trial_id: str, result: Dict) -> None:
        self._record(trial_id, result)

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict] = None,
                          error: bool = False) -> None:
        if result and not error:
            self._record(trial_id, result)
        self._pending.pop(trial_id, None)

    def _record(self, trial_id: str, result: Dict) -> None:
        params = self._pending.get(trial_id)
        value = self.metric_of(result)
        if params is None or value is None:
            return
        budget = float(result.get(self.time_attr, 0))
        self._buckets.setdefault(budget, {})[trial_id] = (
            params, self.signed(value))

    # ----------------------------------------------------------- suggest
    def suggest(self, trial_id: str):
        # largest budget with enough observations wins; with none deep
        # enough, pool every budget (better than pure random — the
        # low-fidelity signal still ranks configurations)
        self._history = self._model_history()
        return super().suggest(trial_id)

    def _model_history(self):
        for budget in sorted(self._buckets, reverse=True):
            entries = list(self._buckets[budget].values())
            if len(entries) >= self.n_initial:
                return entries
        return [e for bucket in self._buckets.values()
                for e in bucket.values()]
