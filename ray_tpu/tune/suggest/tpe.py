"""Tree-structured Parzen estimator searcher — the HyperOpt algorithm
(Bergstra et al. 2011), implemented natively over the Domain space.

The reference wraps the hyperopt package (suggest/hyperopt.py); this
build implements the estimator itself: split completed trials into a
good quantile and the rest, model each dimension with Parzen (kernel
density) estimators l(x) over good and g(x) over bad, and suggest the
candidate maximizing l(x)/g(x). Dimensions are treated independently
(as hyperopt does for flat spaces).
"""

from __future__ import annotations

import math
import random
from typing import Dict, List, Optional, Tuple

from ray_tpu.tune.sample import Categorical, Float, Integer
from ray_tpu.tune.suggest.search import (
    FINISHED,
    Searcher,
    extract_values,
    modelable_domains,
    resolve_spec,
)


class TPESearcher(Searcher):
    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None,
                 n_initial_points: int = 10,
                 gamma: float = 0.25,
                 n_candidates: int = 24,
                 max_suggestions: Optional[int] = None,
                 seed: Optional[int] = None):
        super().__init__(metric, mode)
        self.n_initial = n_initial_points
        self.gamma = gamma
        self.n_candidates = n_candidates
        self.max_suggestions = max_suggestions
        self._rng = random.Random(seed)
        self._count = 0
        # (values per domain-path, signed score)
        self._history: List[Tuple[Dict[Tuple, float], float]] = []
        self._pending: Dict[str, Dict[Tuple, float]] = {}

    # -------------------------------------------------------------- searcher
    def suggest(self, trial_id: str):
        if self._space is None:
            return FINISHED
        if self.max_suggestions is not None and \
                self._count >= self.max_suggestions:
            return FINISHED
        self._count += 1
        domains = modelable_domains(self._space)
        if len(self._history) < self.n_initial or not domains:
            overrides: Dict[Tuple, float] = {}
        else:
            good, bad = self._split()  # one sort per suggestion, not per dim
            overrides = {path: self._suggest_dim(path, dom, good, bad)
                         for path, dom in domains}
        config = resolve_spec(self._space, overrides, self._rng)
        # record what was actually chosen (sampled dims included)
        self._pending[trial_id] = extract_values(config, domains)
        return config

    def on_trial_complete(self, trial_id, result=None, error=False) -> None:
        params = self._pending.pop(trial_id, None)
        if params is None or error:
            return
        value = self.metric_of(result)
        if value is None:
            return
        self._history.append((params, self.signed(value)))

    # ------------------------------------------------------------ estimator
    def _split(self) -> Tuple[list, list]:
        ranked = sorted(self._history, key=lambda kv: kv[1], reverse=True)
        n_good = max(2, int(math.ceil(self.gamma * len(ranked))))
        return ranked[:n_good], ranked[n_good:]

    def _suggest_dim(self, path: Tuple, dom, good, bad) -> float:
        good_vals = [p[path] for p, _ in good if path in p]
        bad_vals = [p[path] for p, _ in bad if path in p]
        if isinstance(dom, Categorical):
            return self._suggest_categorical(dom, good_vals, bad_vals)
        return self._suggest_numeric(dom, good_vals, bad_vals)

    def _suggest_categorical(self, dom: Categorical, good_vals, bad_vals):
        k = len(dom.categories)

        def probs(vals):
            counts = [1.0] * k  # Laplace smoothing
            for v in vals:
                try:
                    counts[dom.categories.index(v)] += 1.0
                except ValueError:
                    pass
            total = sum(counts)
            return [c / total for c in counts]

        pg, pb = probs(good_vals), probs(bad_vals)
        # sample candidates from the good distribution, keep max ratio
        best, best_ratio = None, -1.0
        for _ in range(self.n_candidates):
            idx = self._rng.choices(range(k), weights=pg)[0]
            ratio = pg[idx] / pb[idx]
            if ratio > best_ratio:
                best, best_ratio = dom.categories[idx], ratio
        return best

    def _suggest_numeric(self, dom, good_vals, bad_vals) -> float:
        log = isinstance(dom, Float) and dom.log
        lo, hi = float(dom.lower), float(dom.upper)
        if log:
            lo, hi = math.log(lo), math.log(hi)
            tx = math.log
        else:
            def tx(v):
                return float(v)
        gv = [tx(v) for v in good_vals] or [(lo + hi) / 2]
        bv = [tx(v) for v in bad_vals] or [(lo + hi) / 2]

        def bandwidth(vals):
            n = len(vals)
            mean = sum(vals) / n
            var = sum((v - mean) ** 2 for v in vals) / max(1, n - 1)
            scott = math.sqrt(var) * n ** (-0.2) if var > 0 else 0.0
            return max(scott, (hi - lo) * 0.01, 1e-12)

        bw_g, bw_b = bandwidth(gv), bandwidth(bv)

        def density(x, vals, bw):
            s = 0.0
            for m in vals:
                z = (x - m) / bw
                s += math.exp(-0.5 * z * z)
            return s / (len(vals) * bw * math.sqrt(2 * math.pi)) + 1e-12

        best_x, best_ratio = None, -1.0
        for _ in range(self.n_candidates):
            # draw from the good mixture, truncated to the domain
            m = self._rng.choice(gv)
            x = min(hi, max(lo, self._rng.gauss(m, bw_g)))
            ratio = density(x, gv, bw_g) / density(x, bv, bw_b)
            if ratio > best_ratio:
                best_x, best_ratio = x, ratio
        value = math.exp(best_x) if log else best_x
        from ray_tpu.tune.suggest.search import snap_float, snap_int

        if isinstance(dom, Integer):
            return snap_int(dom, value)
        return snap_float(dom, value)


# The reference exposes this algorithm as HyperOptSearch
# (tune/suggest/hyperopt.py); same estimator, native implementation.
HyperOptSearch = TPESearcher
