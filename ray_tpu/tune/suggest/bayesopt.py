"""Gaussian-process searcher with expected improvement.

The reference wraps the bayes_opt package (suggest/bayesopt.py); this is
a native numpy implementation: parameters are mapped onto the unit cube
(log-space for log domains, index-scaled for categoricals), a GP with an
RBF kernel is fit to completed trials, and the next point maximizes EI
over a random candidate sweep.
"""

from __future__ import annotations

import math
import random
from typing import Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.tune.sample import Categorical, Float, Integer
from ray_tpu.tune.suggest.search import (
    FINISHED,
    Searcher,
    extract_values,
    modelable_domains,
    resolve_spec,
    snap_float as _snap_float,
    snap_int as _snap_int,
)


class BayesOptSearcher(Searcher):
    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None,
                 n_initial_points: int = 8,
                 n_candidates: int = 256,
                 length_scale: float = 0.2,
                 xi: float = 0.01,
                 max_suggestions: Optional[int] = None,
                 seed: Optional[int] = None):
        super().__init__(metric, mode)
        self.n_initial = n_initial_points
        self.n_candidates = n_candidates
        self.length_scale = length_scale
        self.xi = xi
        self.max_suggestions = max_suggestions
        self._rng = random.Random(seed)
        self._np_rng = np.random.default_rng(seed)
        self._count = 0
        self._X: List[np.ndarray] = []  # unit-cube points
        self._y: List[float] = []       # signed scores
        self._pending: Dict[str, np.ndarray] = {}

    # ---------------------------------------------------------- unit cube
    def _to_unit(self, path_values: Dict[Tuple, float],
                 domains) -> np.ndarray:
        out = []
        for path, dom in domains:
            v = path_values[path]
            if isinstance(dom, Categorical):
                k = len(dom.categories)
                idx = dom.categories.index(v) if v in dom.categories else 0
                out.append(idx / max(1, k - 1))
            elif isinstance(dom, Float) and dom.log:
                out.append((math.log(v) - math.log(dom.lower))
                           / (math.log(dom.upper) - math.log(dom.lower)))
            elif isinstance(dom, Integer):
                # values span lower..upper-1 (exclusive upper, like
                # randrange); normalize over the inclusive max so the
                # mapping matches _from_unit exactly
                out.append((float(v) - dom.lower)
                           / max(1.0, dom.upper - 1 - dom.lower))
            else:
                out.append((float(v) - dom.lower)
                           / max(1e-12, dom.upper - dom.lower))
        return np.asarray(out)

    def _from_unit(self, u: np.ndarray, domains) -> Dict[Tuple, float]:
        overrides: Dict[Tuple, float] = {}
        for x, (path, dom) in zip(u, domains):
            x = float(min(1.0, max(0.0, x)))
            if isinstance(dom, Categorical):
                k = len(dom.categories)
                overrides[path] = dom.categories[
                    int(round(x * (k - 1)))]
            elif isinstance(dom, Float) and dom.log:
                v = math.exp(
                    math.log(dom.lower)
                    + x * (math.log(dom.upper) - math.log(dom.lower)))
                overrides[path] = self._quantize(dom, v)
            elif isinstance(dom, Integer):
                v = dom.lower + x * (dom.upper - 1 - dom.lower)
                overrides[path] = _snap_int(dom, v)
            else:
                v = dom.lower + x * (dom.upper - dom.lower)
                overrides[path] = self._quantize(dom, v)
        return overrides

    @staticmethod
    def _quantize(dom: Float, v: float) -> float:
        """Quantized domains only admit multiples of _quantum; the GP's
        continuous argmax must be snapped back onto the grid — clamping
        happens ON the grid, never off it."""
        return _snap_float(dom, v)

    # -------------------------------------------------------------- searcher
    def suggest(self, trial_id: str):
        if self._space is None:
            return FINISHED
        if self.max_suggestions is not None and \
                self._count >= self.max_suggestions:
            return FINISHED
        self._count += 1
        domains = modelable_domains(self._space)
        if len(self._y) < self.n_initial or not domains:
            config = resolve_spec(self._space, {}, self._rng)
        else:
            u = self._acquire(len(domains))
            config = resolve_spec(self._space,
                                  self._from_unit(u, domains), self._rng)
        chosen = extract_values(config, domains)
        self._pending[trial_id] = self._to_unit(chosen, domains)
        return config

    def on_trial_complete(self, trial_id, result=None, error=False) -> None:
        u = self._pending.pop(trial_id, None)
        if u is None or error:
            return
        value = self.metric_of(result)
        if value is None:
            return
        self._X.append(u)
        self._y.append(self.signed(value))

    # --------------------------------------------------------------- the GP
    def _acquire(self, dim: int) -> np.ndarray:
        X = np.stack(self._X)
        y = np.asarray(self._y)
        y_mean, y_std = y.mean(), max(y.std(), 1e-9)
        yn = (y - y_mean) / y_std
        K = self._kernel(X, X) + 1e-6 * np.eye(len(X))
        L = np.linalg.cholesky(K)
        alpha = np.linalg.solve(L.T, np.linalg.solve(L, yn))
        cand = self._np_rng.uniform(size=(self.n_candidates, dim))
        Ks = self._kernel(cand, X)                     # [C, N]
        mu = Ks @ alpha
        v = np.linalg.solve(L, Ks.T)                   # [N, C]
        var = np.maximum(1e-12, 1.0 - np.sum(v * v, axis=0))
        sigma = np.sqrt(var)
        best = yn.max()
        z = (mu - best - self.xi) / sigma
        ei = sigma * (z * _norm_cdf(z) + _norm_pdf(z))
        return cand[int(np.argmax(ei))]

    def _kernel(self, A: np.ndarray, B: np.ndarray) -> np.ndarray:
        d2 = ((A[:, None, :] - B[None, :, :]) ** 2).sum(-1)
        return np.exp(-0.5 * d2 / (self.length_scale ** 2))


def _norm_pdf(z):
    return np.exp(-0.5 * z * z) / math.sqrt(2 * math.pi)


_erf = np.vectorize(math.erf)


def _norm_cdf(z):
    return 0.5 * (1.0 + _erf(z / math.sqrt(2)))
