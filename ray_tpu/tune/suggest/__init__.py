"""Search-algorithm tier (reference: python/ray/tune/suggest/).

The reference ships a ``Searcher`` plugin API plus 16 third-party
integrations (Optuna, HyperOpt, Ax, BayesOpt, ...). This build keeps the
same plugin seam — ``Searcher.suggest / on_trial_result /
on_trial_complete``, ``ConcurrencyLimiter``, ``Repeater``,
``BasicVariantGenerator`` — and ships *native* model-based searchers
instead of wrappers (no third-party solver dependencies):

  - RandomSearcher                 (suggest/random_search — baseline)
  - TPESearcher / HyperOptSearch   (suggest/tpe — tree-structured Parzen
                                    estimator, the HyperOpt algorithm)
  - BayesOptSearcher               (suggest/bayesopt — GP + expected
                                    improvement on a normalized cube)

All consume the same Domain search spaces (tune/sample.py) used by the
built-in variant generator.
"""

from ray_tpu.tune.suggest.search import (  # noqa: F401
    FINISHED,
    BasicVariantGenerator,
    ConcurrencyLimiter,
    Repeater,
    Searcher,
)
from ray_tpu.tune.suggest.random_search import RandomSearcher  # noqa: F401
from ray_tpu.tune.suggest.tpe import HyperOptSearch, TPESearcher  # noqa: F401
from ray_tpu.tune.suggest.bayesopt import BayesOptSearcher  # noqa: F401
from ray_tpu.tune.suggest.external import AskTellSearcher  # noqa: F401
