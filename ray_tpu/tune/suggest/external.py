"""External search-library adapter seam.

The reference ships 16 adapters (tune/suggest/optuna.py, hyperopt.py,
skopt.py, ax.py, ...) that all reduce to the same shape: the external
library owns the sampling model behind an ask/tell (or
get_next/report) surface, and the adapter maps Tune's ``Searcher``
contract onto it — suggest() asks the library for a parameter
assignment, on_trial_complete() tells it the observed objective.

``AskTellSearcher`` is that shape as one generic class: wrap anything
exposing ``ask() -> dict`` and ``tell(params: dict, value: float)``
(optuna's study.ask/tell literally matches; hyperopt/skopt need a
3-line lambda pair). None of those libraries are in this image, so the
test suite drives the seam with an in-repo ask/tell optimizer — the
adapter is what a real library client drops into.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from ray_tpu.tune.suggest.search import FINISHED, Searcher


class AskTellSearcher(Searcher):
    """Adapter from an external ask/tell optimizer to Tune's Searcher.

    opt: object with ``ask() -> Optional[dict]`` (None = exhausted) and
        ``tell(params: dict, value: float) -> None``. ``value`` is
        normalized to MAXIMIZATION before the tell; pass
        ``tell_signed=False`` to receive the raw metric instead.
    """

    def __init__(self, opt: Any, metric: Optional[str] = None,
                 mode: Optional[str] = None, tell_signed: bool = True,
                 config_of: Optional[Callable[[Dict], Dict]] = None):
        super().__init__(metric=metric, mode=mode)
        self._opt = opt
        self._tell_signed = tell_signed
        self._config_of = config_of
        self._live: Dict[str, Dict] = {}

    def suggest(self, trial_id: str):
        params = self._opt.ask()
        if params is None:
            return FINISHED
        self._live[trial_id] = dict(params)
        config = dict(params)
        if self._config_of is not None:
            config = self._config_of(config)
        # external params overlay the declared space's constants, so a
        # partial external space still yields a complete trial config
        if self._space:
            merged = {k: v for k, v in self._space.items()
                      if not hasattr(v, "sample")}
            merged.update(config)
            config = merged
        return config

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict] = None,
                          error: bool = False) -> None:
        params = self._live.pop(trial_id, None)
        if params is None or error:
            return
        value = self.metric_of(result)
        if value is None:
            return
        self._opt.tell(params,
                       self.signed(value) if self._tell_signed else value)
