"""Trial state.

Mirrors the reference's ray.tune Trial (python/ray/tune/trial.py): id,
config, status FSM (PENDING/RUNNING/PAUSED/TERMINATED/ERROR), result log,
checkpoint slot, resource request.
"""

from __future__ import annotations

import os
import uuid
from typing import Any, Dict, List, Optional


class Trial:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    PAUSED = "PAUSED"
    TERMINATED = "TERMINATED"
    ERROR = "ERROR"

    def __init__(self, trainable_cls: type, config: Dict,
                 experiment_tag: str = "",
                 resources: Optional[Dict[str, float]] = None,
                 stopping_criterion: Optional[Dict] = None,
                 max_failures: int = 0):
        self.trial_id = uuid.uuid4().hex[:8]
        self.trainable_cls = trainable_cls
        self.config = config
        self.experiment_tag = experiment_tag
        self.resources = resources or {"cpu": 1}
        self.stopping_criterion = stopping_criterion or {}
        self.max_failures = max_failures
        self.num_failures = 0
        self.status = Trial.PENDING
        self.runner: Any = None            # actor handle
        self.last_result: Dict = {}
        self.results: List[Dict] = []
        self.checkpoint: Optional[Dict] = None
        self.error: Optional[str] = None
        self.metric_history: Dict[str, List[float]] = {}

    def __repr__(self):
        name = getattr(self.trainable_cls, "__name__", "trainable")
        return f"{name}_{self.experiment_tag or self.trial_id}"

    def update_result(self, result: Dict) -> None:
        self.last_result = result
        self.results.append(result)
        for k, v in result.items():
            if isinstance(v, (int, float)):
                self.metric_history.setdefault(k, []).append(float(v))

    def should_stop(self, result: Dict) -> bool:
        if result.get("done"):
            return True
        crit = self.stopping_criterion
        if callable(crit):
            return bool(crit(self.trial_id, result))
        for k, v in (crit or {}).items():
            if k in result and result[k] >= v:
                return True
        return False

    def actor_options(self) -> Dict:
        res = dict(self.resources)
        opts: Dict[str, Any] = {}
        opts["num_cpus"] = res.pop("cpu", res.pop("CPU", 1))
        gpu = res.pop("gpu", res.pop("GPU", 0))
        if gpu:
            opts["num_gpus"] = gpu
        extra = {k: v for k, v in res.items() if v}
        if extra:
            opts["resources"] = extra
        return opts

    @property
    def local_dir(self) -> str:
        return os.path.join("~", "ray_tpu_results")
