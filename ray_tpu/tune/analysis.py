"""ExperimentAnalysis — results of a tune.run.

Mirrors the reference's ray.tune.ExperimentAnalysis
(python/ray/tune/analysis/experiment_analysis.py): best trial/config/
result lookup plus tabular access to all results.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu.tune.trial import Trial


class ExperimentAnalysis:
    def __init__(self, trials: List[Trial],
                 default_metric: Optional[str] = None,
                 default_mode: Optional[str] = None):
        self.trials = trials
        self.default_metric = default_metric
        self.default_mode = default_mode

    def _metric_mode(self, metric, mode):
        metric = metric or self.default_metric
        mode = mode or self.default_mode or "max"
        if metric is None:
            raise ValueError("No metric given and no default_metric set")
        if mode not in ("max", "min"):
            raise ValueError("mode must be 'max' or 'min'")
        return metric, mode

    def get_best_trial(self, metric: Optional[str] = None,
                       mode: Optional[str] = None,
                       scope: str = "last") -> Optional[Trial]:
        metric, mode = self._metric_mode(metric, mode)
        sign = 1 if mode == "max" else -1
        best, best_v = None, None
        for t in self.trials:
            if scope == "all":
                hist = t.metric_history.get(metric)
                if not hist:
                    continue
                v = max(sign * x for x in hist)
            else:
                if metric not in t.last_result:
                    continue
                v = sign * t.last_result[metric]
            if best_v is None or v > best_v:
                best, best_v = t, v
        return best

    def get_best_config(self, metric: Optional[str] = None,
                        mode: Optional[str] = None,
                        scope: str = "last") -> Optional[Dict]:
        t = self.get_best_trial(metric, mode, scope)
        return t.config if t else None

    @property
    def best_trial(self) -> Trial:
        return self.get_best_trial()

    @property
    def best_config(self) -> Dict:
        return self.get_best_config()

    @property
    def best_result(self) -> Dict:
        t = self.get_best_trial()
        return t.last_result if t else {}

    def results(self) -> Dict[str, Dict]:
        return {t.trial_id: t.last_result for t in self.trials}

    def dataframe(self):
        """All trials' last results as a pandas DataFrame (pandas ships
        in the image via jax deps; falls back to list of dicts)."""
        rows = []
        for t in self.trials:
            row = dict(t.last_result)
            row["trial_id"] = t.trial_id
            for k, v in t.config.items():
                if isinstance(v, (int, float, str, bool)):
                    row[f"config/{k}"] = v
            rows.append(row)
        try:
            import pandas as pd

            return pd.DataFrame(rows)
        except ImportError:
            return rows

    def trial_dataframes(self):
        out = {}
        for t in self.trials:
            try:
                import pandas as pd

                out[t.trial_id] = pd.DataFrame(t.results)
            except ImportError:
                out[t.trial_id] = t.results
        return out
