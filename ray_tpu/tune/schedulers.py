"""Trial schedulers.

Mirrors the reference's ray.tune.schedulers: FIFOScheduler,
AsyncHyperBandScheduler/ASHA (schedulers/async_hyperband.py),
MedianStoppingRule (median_stopping_rule.py), HyperBandScheduler
(hyperband.py, simplified to successive halving brackets), and
PopulationBasedTraining (pbt.py: exploit via checkpoint copy + explore
via mutation).
"""

from __future__ import annotations

import math
import random
from typing import Callable, Dict, List, Optional

from ray_tpu.tune.trial import Trial


class TrialScheduler:
    CONTINUE = "CONTINUE"
    PAUSE = "PAUSE"
    STOP = "STOP"

    def on_trial_add(self, runner, trial: Trial) -> None:
        pass

    def on_trial_result(self, runner, trial: Trial, result: Dict) -> str:
        return TrialScheduler.CONTINUE

    def on_trial_complete(self, runner, trial: Trial, result: Dict) -> None:
        pass

    def on_trial_remove(self, runner, trial: Trial) -> None:
        pass

    def choose_trial_to_run(self, runner) -> Optional[Trial]:
        for t in runner.trials:
            if t.status == Trial.PENDING and runner.has_resources_for(t):
                return t
        return None


class FIFOScheduler(TrialScheduler):
    pass


def _get_metric(result: Dict, metric: str, mode: str) -> Optional[float]:
    v = result.get(metric)
    if v is None:
        return None
    return float(v) if mode == "max" else -float(v)


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA: asynchronous successive halving. At each rung (iteration
    milestone r*eta^k), stop a trial whose metric falls below the rung's
    top-1/eta quantile (reference schedulers/async_hyperband.py)."""

    def __init__(self, time_attr: str = "training_iteration",
                 metric: str = "episode_reward_mean", mode: str = "max",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: float = 4, brackets: int = 1):
        self.time_attr = time_attr
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.grace_period = grace_period
        self.rf = reduction_factor
        # rung -> recorded (negated-if-min) metric values
        self._rungs: Dict[float, List[float]] = {}
        milestone = grace_period
        while milestone < max_t:
            self._rungs.setdefault(milestone, [])
            milestone = int(milestone * self.rf)

    def on_trial_result(self, runner, trial: Trial, result: Dict) -> str:
        t = result.get(self.time_attr, 0)
        if t >= self.max_t:
            return TrialScheduler.STOP
        value = _get_metric(result, self.metric, self.mode)
        if value is None:
            return TrialScheduler.CONTINUE
        action = TrialScheduler.CONTINUE
        for milestone in sorted(self._rungs, reverse=True):
            if t < milestone:
                continue
            recorded = self._rungs[milestone]
            if recorded:
                k = max(1, int(len(recorded) / self.rf))
                cutoff = sorted(recorded, reverse=True)[k - 1]
                if value < cutoff:
                    action = TrialScheduler.STOP
            recorded.append(value)
            break
        return action


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best result is worse than the median of other
    trials' running means at the same point in time
    (reference schedulers/median_stopping_rule.py)."""

    def __init__(self, time_attr: str = "training_iteration",
                 metric: str = "episode_reward_mean", mode: str = "max",
                 grace_period: int = 1, min_samples_required: int = 3):
        self.time_attr = time_attr
        self.metric = metric
        self.mode = mode
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self._results: Dict[str, List[float]] = {}

    def on_trial_result(self, runner, trial: Trial, result: Dict) -> str:
        value = _get_metric(result, self.metric, self.mode)
        t = result.get(self.time_attr, 0)
        if value is None:
            return TrialScheduler.CONTINUE
        self._results.setdefault(trial.trial_id, []).append(value)
        if t < self.grace_period:
            return TrialScheduler.CONTINUE
        means = [sum(v) / len(v) for tid, v in self._results.items()
                 if tid != trial.trial_id and v]
        if len(means) < self.min_samples:
            return TrialScheduler.CONTINUE
        median = sorted(means)[len(means) // 2]
        best = max(self._results[trial.trial_id])
        return TrialScheduler.STOP if best < median \
            else TrialScheduler.CONTINUE


class HyperBandScheduler(TrialScheduler):
    """Synchronous HyperBand (reference schedulers/hyperband.py): trials
    fill brackets; each bracket runs successive-halving rounds — all its
    trials run to the round's milestone, then only the top 1/eta continue
    (PAUSE at the milestone, bottom trials STOP when the round closes).

    Unlike ASHA (AsyncHyperBandScheduler) the halving decision waits for
    every live trial in the bracket to reach the milestone, trading
    stragglers for exact quantiles."""

    def __init__(self, time_attr: str = "training_iteration",
                 metric: str = "episode_reward_mean", mode: str = "max",
                 max_t: int = 81, reduction_factor: float = 3):
        self.time_attr = time_attr
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.eta = reduction_factor
        # s_max+1 bracket shapes, bracket s: n = ceil((s_max+1)/(s+1) *
        # eta^s) trials starting at r = max_t / eta^s iterations.
        # Integer loop, not int(log(...)): float log truncates exact
        # powers (log(243, 3) == 4.999...).
        s = 0
        while self.eta ** (s + 1) <= max_t:
            s += 1
        self._s_max = s
        self._brackets: List[dict] = []
        self._trial_bracket: Dict[str, dict] = {}

    def _new_bracket(self) -> dict:
        s = self._s_max - (len(self._brackets) % (self._s_max + 1))
        n = int(math.ceil((self._s_max + 1) / (s + 1) * self.eta ** s))
        r = max(1, int(self.max_t / self.eta ** s))
        bracket = {"s": s, "capacity": n, "milestone": r,
                   "trials": {}, "results": {}}
        self._brackets.append(bracket)
        return bracket

    def on_trial_add(self, runner, trial: Trial) -> None:
        for bracket in self._brackets:
            if len(bracket["trials"]) < bracket["capacity"]:
                break
        else:
            bracket = self._new_bracket()
        bracket["trials"][trial.trial_id] = trial
        self._trial_bracket[trial.trial_id] = bracket

    def on_trial_result(self, runner, trial: Trial, result: Dict) -> str:
        t = result.get(self.time_attr, 0)
        if t >= self.max_t:
            return TrialScheduler.STOP
        bracket = self._trial_bracket.get(trial.trial_id)
        if bracket is None:
            return TrialScheduler.CONTINUE
        if t < bracket["milestone"]:
            return TrialScheduler.CONTINUE
        # AT (or past) the milestone: record the score that counts for
        # this round — pre-milestone reports must not enter the ranking,
        # or concurrent trials would be halved at mixed iteration counts.
        value = _get_metric(result, self.metric, self.mode)
        if value is None:
            return TrialScheduler.CONTINUE  # nothing comparable reported
        bracket["results"][trial.trial_id] = value
        return self._maybe_close_round(runner, bracket, trial)

    def on_trial_complete(self, runner, trial: Trial, result: Dict) -> None:
        # a trial leaving through the stop criterion (runner completes it
        # BEFORE consulting the scheduler) must not stall its round
        self._forget(runner, trial)

    def on_trial_remove(self, runner, trial: Trial) -> None:
        self._forget(runner, trial)

    def _forget(self, runner, trial: Trial) -> None:
        bracket = self._trial_bracket.pop(trial.trial_id, None)
        if bracket is None:
            return
        bracket["results"].pop(trial.trial_id, None)
        # its departure may have been the round's last missing report
        if any(tr.status not in (Trial.TERMINATED, Trial.ERROR)
               for tr in bracket["trials"].values()):
            self._maybe_close_round(runner, bracket, None)

    def _maybe_close_round(self, runner, bracket: dict,
                           trial: Optional[Trial]) -> str:
        live = [tid for tid, tr in bracket["trials"].items()
                if tr.status not in (Trial.TERMINATED, Trial.ERROR)
                and tid in self._trial_bracket]
        reported = [tid for tid in live if tid in bracket["results"]]
        waiting = [tid for tid in live if tid not in reported]
        if waiting or not reported:
            return TrialScheduler.PAUSE  # stragglers still mid-round
        # whole round in: keep the top 1/eta, stop the rest
        ranked = sorted(reported,
                        key=lambda tid: bracket["results"][tid],
                        reverse=True)
        keep = max(1, int(len(ranked) / self.eta))
        survivors = set(ranked[:keep])
        bracket["milestone"] = min(self.max_t,
                                   int(bracket["milestone"] * self.eta))
        bracket["results"] = {}
        for tid in list(ranked):
            if tid in survivors:
                continue
            tr = bracket["trials"][tid]
            if trial is not None and tr is trial:
                continue  # returned as STOP below
            runner._complete_trial(tr, {})
        for tid in survivors:
            tr = bracket["trials"][tid]
            if tr.status == Trial.PAUSED:
                tr.status = Trial.PENDING  # resume the next round
        if trial is None:
            return TrialScheduler.CONTINUE
        return (TrialScheduler.CONTINUE if trial.trial_id in survivors
                else TrialScheduler.STOP)


class PopulationBasedTraining(TrialScheduler):
    """PBT: at each perturbation interval, a bottom-quantile trial clones
    the checkpoint of a top-quantile trial (exploit) and perturbs its
    hyperparameters (explore) — reference schedulers/pbt.py."""

    def __init__(self, time_attr: str = "training_iteration",
                 metric: str = "episode_reward_mean", mode: str = "max",
                 perturbation_interval: int = 5,
                 hyperparam_mutations: Optional[Dict] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: Optional[int] = None):
        self.time_attr = time_attr
        self.metric = metric
        self.mode = mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_prob = resample_probability
        self._last_perturb: Dict[str, float] = {}
        self._scores: Dict[str, float] = {}
        self._rng = random.Random(seed)
        self.num_perturbations = 0

    def on_trial_result(self, runner, trial: Trial, result: Dict) -> str:
        t = result.get(self.time_attr, 0)
        score = _get_metric(result, self.metric, self.mode)
        if score is not None:
            self._scores[trial.trial_id] = score
        last = self._last_perturb.get(trial.trial_id, 0)
        if t - last < self.interval:
            return TrialScheduler.CONTINUE
        self._last_perturb[trial.trial_id] = t
        lower, upper = self._quantiles(runner)
        if trial.trial_id in {x.trial_id for x in lower} and upper:
            donor = self._rng.choice(upper)
            self._exploit(runner, trial, donor)
        return TrialScheduler.CONTINUE

    def _quantiles(self, runner):
        trials = [tr for tr in runner.trials
                  if tr.trial_id in self._scores
                  and tr.status in (Trial.RUNNING, Trial.PENDING,
                                    Trial.PAUSED)]
        if len(trials) <= 1:
            return [], []
        trials.sort(key=lambda tr: self._scores[tr.trial_id])
        n = max(1, int(math.ceil(len(trials) * self.quantile)))
        if n > len(trials) // 2:
            n = len(trials) // 2
        return trials[:n], trials[-n:] if n else []

    def _exploit(self, runner, trial: Trial, donor: Trial) -> None:
        checkpoint = runner.save_trial(donor)
        if checkpoint is None:
            return
        new_config = self._explore(dict(donor.config))
        trial.config = new_config
        runner.restart_trial_with(trial, new_config, checkpoint)
        self.num_perturbations += 1

    def _explore(self, config: Dict) -> Dict:
        from ray_tpu.tune.sample import Domain

        for key, spec in self.mutations.items():
            if self._rng.random() < self.resample_prob or \
                    key not in config:
                if isinstance(spec, list):
                    config[key] = self._rng.choice(spec)
                elif isinstance(spec, Domain):
                    config[key] = spec.sample(self._rng)
                elif callable(spec):
                    config[key] = spec()
            else:
                factor = self._rng.choice([0.8, 1.2])
                if isinstance(spec, list):
                    # move to a neighboring value
                    try:
                        i = spec.index(config[key])
                        i = max(0, min(len(spec) - 1,
                                       i + self._rng.choice([-1, 1])))
                        config[key] = spec[i]
                    except ValueError:
                        config[key] = self._rng.choice(spec)
                elif isinstance(config[key], (int, float)):
                    config[key] = type(config[key])(config[key] * factor)
        return config


class HyperBandForBOHB(HyperBandScheduler):
    """HyperBand variant for BOHB (reference: schedulers/hb_bohb.py):
    identical bracket math, but trial selection fills the round closest
    to completion first, so the BOHBSearcher's per-budget model gets
    whole rungs of feedback as early as possible instead of dribbling
    results across many half-filled brackets. Pair with
    suggest.bohb.BOHBSearcher as search_alg."""

    def choose_trial_to_run(self, runner) -> Optional[Trial]:
        candidates = [t for t in runner.trials
                      if t.status == Trial.PENDING
                      and runner.has_resources_for(t)]
        if not candidates:
            return None

        def missing_reports(t: Trial):
            bracket = self._trial_bracket.get(t.trial_id)
            if bracket is None:
                return (1, 0)
            live = [tid for tid, tr in bracket["trials"].items()
                    if tr.status not in (Trial.TERMINATED, Trial.ERROR)]
            missing = sum(1 for tid in live
                          if tid not in bracket["results"])
            return (0, missing)

        return min(candidates, key=missing_reports)


class PB2(PopulationBasedTraining):
    """Population Based Bandits (reference: schedulers/pb2.py, Parker-
    Holder et al. 2020): PBT's exploit step (clone a top trial's
    checkpoint) is kept, but the EXPLORE step replaces random
    perturbation with a Gaussian-process bandit — fit a GP to the
    population's (hyperparameters -> score) observations and take the
    UCB argmax inside ``hyperparam_bounds``. Numpy-native (RBF kernel
    ridge posterior), like the repo's other model-based searchers."""

    def __init__(self, time_attr: str = "training_iteration",
                 metric: str = "episode_reward_mean", mode: str = "max",
                 perturbation_interval: int = 5,
                 hyperparam_bounds: Optional[Dict] = None,
                 quantile_fraction: float = 0.25,
                 ucb_beta: float = 2.0,
                 n_candidates: int = 256,
                 seed: Optional[int] = None):
        super().__init__(time_attr=time_attr, metric=metric, mode=mode,
                         perturbation_interval=perturbation_interval,
                         hyperparam_mutations={},
                         quantile_fraction=quantile_fraction,
                         seed=seed)
        if not hyperparam_bounds:
            raise ValueError("PB2 requires hyperparam_bounds: "
                             "{key: (low, high)}")
        self.bounds = {k: (float(lo), float(hi))
                       for k, (lo, hi) in hyperparam_bounds.items()}
        self.ucb_beta = ucb_beta
        self.n_candidates = n_candidates
        self._runner = None

    def on_trial_result(self, runner, trial: Trial, result: Dict) -> str:
        self._runner = runner  # _explore needs population observations
        return super().on_trial_result(runner, trial, result)

    def _observations(self):
        import numpy as np

        keys = list(self.bounds)
        X, y = [], []
        for tr in (self._runner.trials if self._runner else []):
            score = self._scores.get(tr.trial_id)
            if score is None:
                continue
            row = []
            for k in keys:
                lo, hi = self.bounds[k]
                v = float(tr.config.get(k, lo))
                row.append((v - lo) / max(1e-12, hi - lo))
            X.append(row)
            y.append(score)
        return np.asarray(X, dtype=float), np.asarray(y, dtype=float)

    def _explore(self, config: Dict) -> Dict:
        import numpy as np

        keys = list(self.bounds)
        X, y = self._observations()
        rng = np.random.default_rng(self._rng.randrange(2 ** 31))
        cands = rng.uniform(size=(self.n_candidates, len(keys)))
        if len(y) >= 3 and float(y.std()) > 0:
            ys = (y - y.mean()) / y.std()

            def rbf(a, b, ls=0.2):
                d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
                return np.exp(-d2 / (2 * ls * ls))

            K = rbf(X, X) + 1e-3 * np.eye(len(X))
            Kinv = np.linalg.inv(K)
            ks = rbf(cands, X)
            mu = ks @ (Kinv @ ys)
            var = np.clip(1.0 - np.einsum("ci,ij,cj->c", ks, Kinv, ks),
                          1e-9, None)
            ucb = mu + self.ucb_beta * np.sqrt(var)
            best = cands[int(np.argmax(ucb))]
        else:  # cold start: uniform exploration inside the bounds
            best = cands[0]
        for i, k in enumerate(keys):
            lo, hi = self.bounds[k]
            value = lo + float(best[i]) * (hi - lo)
            if isinstance(config.get(k), int):
                value = int(round(value))
            config[k] = value
        return config
