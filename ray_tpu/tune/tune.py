"""tune.run — the experiment entry point.

Mirrors the reference's ray.tune.run (python/ray/tune/tune.py): expand
the config into trials (grid × num_samples), drive them through the
TrialRunner under the chosen scheduler, return an ExperimentAnalysis.
"""

from __future__ import annotations

import random
from typing import Any, Callable, Dict, List, Optional, Union

import ray_tpu
from ray_tpu.tune.analysis import ExperimentAnalysis
from ray_tpu.tune.schedulers import TrialScheduler
from ray_tpu.tune.trainable import Trainable, wrap_function
from ray_tpu.tune.trial import Trial
from ray_tpu.tune.trial_runner import TrialRunner
from ray_tpu.tune.variant_generator import generate_variants


def run(run_or_experiment: Union[Callable, type],
        *,
        config: Optional[Dict] = None,
        num_samples: int = 1,
        metric: Optional[str] = None,
        mode: Optional[str] = None,
        stop: Optional[Union[Dict, Callable]] = None,
        resources_per_trial: Optional[Dict[str, float]] = None,
        scheduler: Optional[TrialScheduler] = None,
        search_alg: Any = None,
        max_failures: int = 0,
        max_concurrent_trials: Optional[int] = None,
        callbacks: Optional[List] = None,
        verbose: int = 0,
        name: Optional[str] = None,
        seed: Optional[int] = None,
        **_ignored) -> ExperimentAnalysis:
    if not ray_tpu.is_initialized():
        ray_tpu.init()
    if isinstance(run_or_experiment, type) and \
            issubclass(run_or_experiment, Trainable):
        trainable_cls = run_or_experiment
    elif callable(run_or_experiment):
        trainable_cls = wrap_function(run_or_experiment)
    else:
        raise TypeError("run_or_experiment must be a callable or a "
                        "Trainable subclass")
    if scheduler is not None:
        # let the experiment's metric/mode flow into the scheduler like
        # the reference's set_search_properties
        if metric and getattr(scheduler, "metric", None) in (
                None, "episode_reward_mean"):
            scheduler.metric = metric
        if mode and getattr(scheduler, "mode", None) in (None, "max"):
            scheduler.mode = mode

    rng = random.Random(seed)
    config = config or {}
    if search_alg is not None:
        # Searcher-driven: trials are created lazily from suggestions
        # (reference: SearchGenerator). num_samples bounds the total.
        search_alg.set_search_properties(metric, mode, config)

        def _factory(variant: Dict, trial_id: str) -> Trial:
            trial = Trial(
                trainable_cls=trainable_cls,
                config=variant,
                experiment_tag=trial_id,
                resources=resources_per_trial,
                stopping_criterion=stop,
                max_failures=max_failures)
            trial.trial_id = trial_id
            return trial

        runner = TrialRunner(scheduler=scheduler,
                             max_concurrent_trials=max_concurrent_trials,
                             callbacks=callbacks,
                             search_alg=search_alg,
                             trial_factory=_factory,
                             max_trials=num_samples)
    else:
        runner = TrialRunner(scheduler=scheduler,
                             max_concurrent_trials=max_concurrent_trials,
                             callbacks=callbacks)
        trial_idx = 0
        for _ in range(num_samples):
            for tag, variant in generate_variants(config, rng):
                trial = Trial(
                    trainable_cls=trainable_cls,
                    config=variant,
                    experiment_tag=f"{trial_idx}" + (f"_{tag}" if tag else ""),
                    resources=resources_per_trial,
                    stopping_criterion=stop,
                    max_failures=max_failures)
                runner.add_trial(trial)
                trial_idx += 1
    runner.run_loop()
    if verbose:
        for t in runner.trials:
            print(f"{t}: {t.status} {t.last_result}")
    return ExperimentAnalysis(runner.trials, default_metric=metric,
                              default_mode=mode)


def with_parameters(trainable: Callable, **kwargs) -> Callable:
    """Bind large objects by object-store reference
    (reference tune/utils/trainable.py with_parameters)."""
    refs = {k: ray_tpu.put(v) for k, v in kwargs.items()}

    def inner(config, checkpoint_dir=None):
        resolved = {k: ray_tpu.get(r) for k, r in refs.items()}
        import inspect

        sig = inspect.signature(trainable)
        if "checkpoint_dir" in sig.parameters:
            return trainable(config, checkpoint_dir=checkpoint_dir,
                             **resolved)
        return trainable(config, **resolved)

    inner.__name__ = getattr(trainable, "__name__", "trainable")
    return inner
