"""tune.run — the experiment entry point.

Mirrors the reference's ray.tune.run (python/ray/tune/tune.py): expand
the config into trials (grid × num_samples), drive them through the
TrialRunner under the chosen scheduler, return an ExperimentAnalysis.
"""

from __future__ import annotations

import random
from typing import Any, Callable, Dict, List, Optional, Union

import ray_tpu
from ray_tpu.tune.analysis import ExperimentAnalysis
from ray_tpu.tune.schedulers import TrialScheduler
from ray_tpu.tune.trainable import Trainable, wrap_function
from ray_tpu.tune.trial import Trial
from ray_tpu.tune.trial_runner import TrialRunner
from ray_tpu.tune.variant_generator import generate_variants


def run(run_or_experiment: Union[Callable, type],
        *,
        config: Optional[Dict] = None,
        num_samples: int = 1,
        metric: Optional[str] = None,
        mode: Optional[str] = None,
        stop: Optional[Union[Dict, Callable]] = None,
        resources_per_trial: Optional[Dict[str, float]] = None,
        scheduler: Optional[TrialScheduler] = None,
        search_alg: Any = None,
        max_failures: int = 0,
        max_concurrent_trials: Optional[int] = None,
        callbacks: Optional[List] = None,
        verbose: int = 0,
        name: Optional[str] = None,
        seed: Optional[int] = None,
        local_dir: Optional[str] = None,
        resume: bool = False,
        sync_config: Optional[Dict] = None,
        **_ignored) -> ExperimentAnalysis:
    """``local_dir``/``name`` place the experiment directory;
    ``resume=True`` reloads a previous run's state from it (finished
    trials keep their results, unfinished ones restart from their last
    checkpoint — reference: tune.run(resume=...) over the trial_runner
    experiment checkpoint); ``sync_config={"upload_dir": ...}`` mirrors
    the experiment dir through a Syncer (tune/syncer.py)."""
    if not ray_tpu.is_initialized():
        ray_tpu.init()
    if isinstance(run_or_experiment, type) and \
            issubclass(run_or_experiment, Trainable):
        trainable_cls = run_or_experiment
    elif callable(run_or_experiment):
        trainable_cls = wrap_function(run_or_experiment)
    else:
        raise TypeError("run_or_experiment must be a callable or a "
                        "Trainable subclass")
    if scheduler is not None:
        # let the experiment's metric/mode flow into the scheduler like
        # the reference's set_search_properties
        if metric and getattr(scheduler, "metric", None) in (
                None, "episode_reward_mean"):
            scheduler.metric = metric
        if mode and getattr(scheduler, "mode", None) in (None, "max"):
            scheduler.mode = mode

    import os

    from ray_tpu.tune import syncer as sync_mod

    # experiment state persists only when the caller identified the
    # experiment (name/local_dir) or asked for durability — a bare
    # tune.run(train_fn) must not clobber another same-named function's
    # resume state in the shared default directory
    persist = bool(name or local_dir or resume or sync_config)
    exp_name = name or getattr(trainable_cls, "__name__", "experiment")
    exp_dir = os.path.join(local_dir or sync_mod.default_local_dir(),
                           exp_name)
    upload_dir = (sync_config or {}).get("upload_dir")
    the_syncer = sync_mod.get_syncer(upload_dir)
    restored: List[Trial] = []       # finished trials from a prior run
    resumable: dict = {}             # trial_id -> saved state to re-run
    if resume:
        if the_syncer is not None and upload_dir and \
                sync_mod.load_experiment_state(exp_dir) is None:
            the_syncer.sync_down(upload_dir, exp_dir)
        state = sync_mod.load_experiment_state(exp_dir)
        for saved in (state or {}).get("trials", []):
            if saved["status"] in (Trial.TERMINATED, Trial.ERROR):
                t = Trial(trainable_cls=trainable_cls,
                          config=saved["config"],
                          experiment_tag=saved["experiment_tag"])
                t.trial_id = saved["trial_id"]
                t.status = saved["status"]
                t.last_result = saved["last_result"]
                t.results = saved["results"]
                t.error = saved["error"]
                restored.append(t)
                for r in saved["results"]:  # get_best_trial(scope="all")
                    for k, v in r.items():
                        if isinstance(v, (int, float)):
                            t.metric_history.setdefault(k, []).append(
                                float(v))
            else:
                resumable[saved["experiment_tag"]] = saved

    rng = random.Random(seed)
    config = config or {}
    if search_alg is not None:
        # Searcher-driven: trials are created lazily from suggestions
        # (reference: SearchGenerator). num_samples bounds the total.
        search_alg.set_search_properties(metric, mode, config)

        def _factory(variant: Dict, trial_id: str) -> Trial:
            trial = Trial(
                trainable_cls=trainable_cls,
                config=variant,
                experiment_tag=trial_id,
                resources=resources_per_trial,
                stopping_criterion=stop,
                max_failures=max_failures)
            trial.trial_id = trial_id
            return trial

        # resume with a searcher: suggestions are not replayable by tag,
        # so completed trials simply reduce the remaining budget (their
        # results still reach the analysis via `restored`)
        remaining = max(0, num_samples - len(restored))
        runner = TrialRunner(scheduler=scheduler,
                             max_concurrent_trials=max_concurrent_trials,
                             callbacks=callbacks,
                             search_alg=search_alg,
                             trial_factory=_factory,
                             max_trials=remaining)
        # restored searcher trials were named trial_0..trial_{k-1}:
        # start new suggestions after them
        runner._trial_counter = len(restored)
        runner.trial_id_offset = len(restored)
    else:
        runner = TrialRunner(scheduler=scheduler,
                             max_concurrent_trials=max_concurrent_trials,
                             callbacks=callbacks)
        trial_idx = 0
        done_tags = {t.experiment_tag for t in restored}
        for _ in range(num_samples):
            for tag, variant in generate_variants(config, rng):
                full_tag = f"{trial_idx}" + (f"_{tag}" if tag else "")
                trial_idx += 1
                if full_tag in done_tags:
                    continue  # finished in the resumed run
                trial = Trial(
                    trainable_cls=trainable_cls,
                    config=variant,
                    experiment_tag=full_tag,
                    resources=resources_per_trial,
                    stopping_criterion=stop,
                    max_failures=max_failures)
                saved = resumable.get(full_tag)
                if saved is not None:  # continue from its checkpoint
                    trial.trial_id = saved["trial_id"]
                    trial.config = saved["config"]
                    trial.checkpoint = saved["checkpoint"]
                    trial.results = saved["results"]
                    trial.last_result = saved["last_result"]
                runner.add_trial(trial)
    checkpointer = None
    if persist:
        checkpointer = sync_mod.ExperimentCheckpointCallback(
            exp_dir, the_syncer, upload_dir, extra_trials=restored)
        runner.callbacks.append(checkpointer)
    try:
        runner.run_loop()
    finally:
        if checkpointer is not None:
            checkpointer.flush(runner.trials)
    if verbose:
        for t in runner.trials:
            print(f"{t}: {t.status} {t.last_result}")
    return ExperimentAnalysis(restored + runner.trials,
                              default_metric=metric, default_mode=mode)


def with_parameters(trainable: Callable, **kwargs) -> Callable:
    """Bind large objects by object-store reference
    (reference tune/utils/trainable.py with_parameters)."""
    refs = {k: ray_tpu.put(v) for k, v in kwargs.items()}

    def inner(config, checkpoint_dir=None):
        resolved = {k: ray_tpu.get(r) for k, r in refs.items()}
        import inspect

        sig = inspect.signature(trainable)
        if "checkpoint_dir" in sig.parameters:
            return trainable(config, checkpoint_dir=checkpoint_dir,
                             **resolved)
        return trainable(config, **resolved)

    inner.__name__ = getattr(trainable, "__name__", "trainable")
    return inner
