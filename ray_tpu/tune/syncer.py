"""Experiment checkpointing + checkpoint sync.

Reference: python/ray/tune/syncer.py + trial_runner.py's experiment
checkpointing — tune periodically persists every trial's state (config,
results, checkpoint) to the experiment directory so ``tune.run(...,
resume=True)`` continues an interrupted sweep, and a Syncer mirrors the
experiment directory to durable storage (the reference's cloud sync;
here a pluggable URI scheme with a directory backend — S3-style remotes
slot in behind the same two methods)."""

from __future__ import annotations

import os
import shutil
import time
from typing import Dict, List, Optional

try:
    import cloudpickle as pickle
except ImportError:  # pragma: no cover
    import pickle

EXPERIMENT_STATE = "experiment_state.pkl"


class Syncer:
    """Two-method plugin surface (reference: tune/syncer.py Syncer)."""

    def sync_up(self, local_dir: str, remote_uri: str) -> None:
        raise NotImplementedError

    def sync_down(self, remote_uri: str, local_dir: str) -> None:
        raise NotImplementedError


class DirSyncer(Syncer):
    """Mirror the experiment dir into another directory tree — the
    single-host stand-in for cloud storage (an NFS mount or fuse-mapped
    bucket path works unchanged)."""

    def sync_up(self, local_dir: str, remote_uri: str) -> None:
        if os.path.isdir(local_dir):
            shutil.copytree(local_dir, remote_uri, dirs_exist_ok=True)

    def sync_down(self, remote_uri: str, local_dir: str) -> None:
        if os.path.isdir(remote_uri):
            shutil.copytree(remote_uri, local_dir, dirs_exist_ok=True)


def get_syncer(upload_dir: Optional[str]) -> Optional[Syncer]:
    if not upload_dir:
        return None
    if "://" in upload_dir and not upload_dir.startswith("file://"):
        raise ValueError(
            f"no syncer for {upload_dir!r}: cloud object stores are not "
            "reachable from this environment; mount the bucket (fuse/"
            "NFS) and pass the mount path, or register a custom Syncer")
    return DirSyncer()


def default_local_dir() -> str:
    return os.environ.get(
        "RAY_TPU_RESULTS_DIR",
        os.path.join(os.path.expanduser("~"), "ray_tpu_results"))


# ---------------------------------------------------------------------------
# experiment state (trial_runner.checkpoint() role)
# ---------------------------------------------------------------------------

def save_experiment_state(exp_dir: str, trials: List) -> None:
    os.makedirs(exp_dir, exist_ok=True)
    state = []
    for t in trials:
        try:
            state.append({
                "trial_id": t.trial_id,
                "config": t.config,
                "experiment_tag": t.experiment_tag,
                "status": t.status,
                "last_result": t.last_result,
                "results": t.results,
                "checkpoint": t.checkpoint,
                "error": t.error,
                "num_failures": t.num_failures,
            })
        except Exception:
            continue  # an unpicklable trial must not sink the rest
    tmp = os.path.join(exp_dir, EXPERIMENT_STATE + ".tmp")
    with open(tmp, "wb") as f:
        pickle.dump({"version": 1, "time": time.time(),
                     "trials": state}, f)
    os.replace(tmp, os.path.join(exp_dir, EXPERIMENT_STATE))


def load_experiment_state(exp_dir: str) -> Optional[Dict]:
    path = os.path.join(exp_dir, EXPERIMENT_STATE)
    if not os.path.exists(path):
        return None
    with open(path, "rb") as f:
        return pickle.load(f)


class ExperimentCheckpointCallback:
    """Runner callback: persist experiment state (and sync it up) at a
    bounded cadence while trials report (reference: trial_runner
    checkpoints every checkpoint_period_s)."""

    def __init__(self, exp_dir: str, syncer: Optional[Syncer] = None,
                 upload_dir: Optional[str] = None,
                 period_s: float = 5.0,
                 extra_trials: Optional[List] = None):
        self.exp_dir = exp_dir
        self.syncer = syncer
        self.upload_dir = upload_dir
        self.period_s = period_s
        # finished trials restored from a previous run: EVERY save must
        # include them or a crash mid-resume would lose their results
        self.extra_trials = list(extra_trials or [])
        self._last = 0.0

    def on_trial_result(self, runner, trial, result) -> None:
        now = time.monotonic()
        if now - self._last < self.period_s:
            return
        self._last = now
        self.flush(runner.trials)

    def flush(self, trials: List) -> None:
        save_experiment_state(self.exp_dir, self.extra_trials
                              + [t for t in trials
                                 if t not in self.extra_trials])
        if self.syncer is not None and self.upload_dir:
            try:
                self.syncer.sync_up(self.exp_dir, self.upload_dir)
            except Exception:
                pass  # durable sync is best-effort mid-run
