"""Search-space primitives.

Mirrors the reference's ray.tune.sample (python/ray/tune/sample.py):
Domain objects (uniform/loguniform/randint/choice/...) plus the
``grid_search`` marker dict consumed by the variant generator.
"""

from __future__ import annotations

import random
from typing import Any, Dict, List, Optional, Sequence


class Domain:
    def sample(self, rng: Optional[random.Random] = None) -> Any:
        raise NotImplementedError


class Float(Domain):
    def __init__(self, lower: float, upper: float, log: bool = False):
        if log and lower <= 0:
            raise ValueError("loguniform needs lower > 0")
        self.lower, self.upper, self.log = lower, upper, log
        self._quantum: Optional[float] = None

    def quantized(self, q: float) -> "Float":
        self._quantum = q
        return self

    def sample(self, rng=None):
        rng = rng or random
        if self.log:
            import math

            v = math.exp(rng.uniform(math.log(self.lower),
                                     math.log(self.upper)))
        else:
            v = rng.uniform(self.lower, self.upper)
        if self._quantum:
            v = round(v / self._quantum) * self._quantum
        return v


class Integer(Domain):
    def __init__(self, lower: int, upper: int):
        self.lower, self.upper = lower, upper
        # grid step (qrandint); visible to model-based searchers so their
        # suggestions can snap back onto the grid, like Float._quantum
        self._quantum: Optional[int] = None

    def sample(self, rng=None):
        rng = rng or random
        return rng.randrange(self.lower, self.upper)


class Categorical(Domain):
    def __init__(self, categories: Sequence[Any]):
        self.categories = list(categories)

    def sample(self, rng=None):
        rng = rng or random
        return rng.choice(self.categories)


class Function(Domain):
    def __init__(self, func):
        self.func = func

    def sample(self, rng=None):
        try:
            return self.func(None)
        except TypeError:
            return self.func()


def uniform(lower: float, upper: float) -> Float:
    return Float(lower, upper)


def quniform(lower: float, upper: float, q: float) -> Float:
    return Float(lower, upper).quantized(q)


def loguniform(lower: float, upper: float) -> Float:
    return Float(lower, upper, log=True)


def randint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper)


def qrandint(lower: int, upper: int, q: int) -> Integer:
    class _Q(Integer):
        def sample(self, rng=None):
            v = super().sample(rng)
            return int(round(v / q) * q)
    dom = _Q(lower, upper)
    dom._quantum = q
    return dom


def choice(categories: Sequence[Any]) -> Categorical:
    return Categorical(categories)


def sample_from(func) -> Function:
    return Function(func)


def randn(mean: float = 0.0, sd: float = 1.0) -> Function:
    return Function(lambda _=None: random.gauss(mean, sd))


def grid_search(values: List[Any]) -> Dict[str, List[Any]]:
    """Marker consumed by the variant generator."""
    return {"grid_search": list(values)}
