"""Trainable — the unit a Tune trial runs.

Mirrors the reference's ray.tune.Trainable (python/ray/tune/trainable.py:
55; train:296, save_checkpoint:850, restore:461) plus the function-API
runner (python/ray/tune/function_runner.py): a function trainable runs on
its own thread and streams results through tune.report, one result per
``train()`` call.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Dict, Optional

# result keys (reference tune/result.py)
TRAINING_ITERATION = "training_iteration"
DONE = "done"
TIME_THIS_ITER_S = "time_this_iter_s"
TIME_TOTAL_S = "time_total_s"
TRIAL_ID = "trial_id"


class Trainable:
    """Class API: subclass and implement setup/step/save_checkpoint/
    load_checkpoint."""

    def __init__(self, config: Optional[Dict] = None, trial_id: str = ""):
        self.config = config or {}
        self.trial_id = trial_id
        self._iteration = 0
        self._time_total = 0.0
        self._start_time = time.time()
        self.setup(self.config)

    # ------------------------------------------------------- subclass API
    def setup(self, config: Dict) -> None:
        pass

    def step(self) -> Dict:
        raise NotImplementedError

    def save_checkpoint(self, checkpoint_dir: str = "") -> Any:
        return None

    def load_checkpoint(self, checkpoint: Any) -> None:
        pass

    def reset_config(self, new_config: Dict) -> bool:
        return False

    def cleanup(self) -> None:
        pass

    # --------------------------------------------------------- driver API
    def train(self) -> Dict:
        t0 = time.time()
        result = self.step() or {}
        self._iteration += 1
        dt = time.time() - t0
        self._time_total += dt
        result.setdefault(TRAINING_ITERATION, self._iteration)
        result.setdefault(TIME_THIS_ITER_S, dt)
        result.setdefault(TIME_TOTAL_S, self._time_total)
        result.setdefault(DONE, False)
        result.setdefault(TRIAL_ID, self.trial_id)
        return result

    def save(self) -> Dict:
        """In-memory checkpoint envelope (reference save_to_object)."""
        return {
            "data": self.save_checkpoint(),
            "iteration": self._iteration,
            "time_total": self._time_total,
        }

    def restore(self, checkpoint: Dict) -> None:
        self._iteration = checkpoint.get("iteration", 0)
        self._time_total = checkpoint.get("time_total", 0.0)
        self.load_checkpoint(checkpoint.get("data"))

    def reset(self, new_config: Dict, trial_id: str = None) -> bool:
        ok = self.reset_config(new_config)
        if ok:
            self.config = new_config
            if trial_id is not None:
                self.trial_id = trial_id
        return ok

    def stop(self) -> None:
        self.cleanup()

    @property
    def iteration(self) -> int:
        return self._iteration


# ---------------------------------------------------------------- function API
_fn_sessions: Dict[int, "FunctionRunner"] = {}


def report(**metrics) -> None:
    s = _fn_sessions.get(threading.get_ident())
    if s is None:
        raise RuntimeError(
            "tune.report() must be called from inside a Tune trainable")
    s._report(metrics)


class checkpoint_dir:
    """``with tune.checkpoint_dir(step=n) as d:`` context manager. The
    function API persists whatever the user writes into d; we keep the
    directory path in the in-memory checkpoint envelope."""

    def __init__(self, step: int):
        self.step = step

    def __enter__(self) -> str:
        import tempfile

        s = _fn_sessions.get(threading.get_ident())
        self._dir = tempfile.mkdtemp(prefix="tune_ckpt_")
        if s is not None:
            s._pending_checkpoint_dir = self._dir
        return self._dir

    def __exit__(self, *exc) -> None:
        s = _fn_sessions.get(threading.get_ident())
        if s is not None and exc[0] is None:
            s._checkpoint_taken(self._dir, self.step)


def get_trial_id() -> Optional[str]:
    s = _fn_sessions.get(threading.get_ident())
    return s.trial_id if s else None


class FunctionRunner(Trainable):
    """Adapts a train function to the Trainable interface: the function
    runs on a thread; each tune.report() unblocks one train() call."""

    _function: Callable = None  # set by wrap_function subclass

    def setup(self, config: Dict) -> None:
        self._result_q: "queue.Queue" = queue.Queue(1)
        self._continue = threading.Semaphore(0)
        self._error: Optional[BaseException] = None
        self._done = False
        self._pending_checkpoint_dir = None
        self._last_metrics: Dict = {}
        self._latest_checkpoint = None
        self._restore_checkpoint = None
        self._thread: Optional[threading.Thread] = None

    def _start_thread(self) -> None:
        def run():
            _fn_sessions[threading.get_ident()] = self
            try:
                import inspect

                sig = inspect.signature(self._function)
                if len(sig.parameters) >= 2:
                    self._function(self.config,
                                   checkpoint_dir=self._restore_checkpoint)
                else:
                    self._function(self.config)
            except BaseException as e:  # noqa: BLE001
                self._error = e
            finally:
                self._done = True
                _fn_sessions.pop(threading.get_ident(), None)
                self._result_q.put(None)

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def _report(self, metrics: Dict) -> None:
        self._result_q.put(dict(metrics))
        self._continue.acquire()

    def _checkpoint_taken(self, path: str, step: int) -> None:
        self._latest_checkpoint = {"dir": path, "step": step}

    def step(self) -> Dict:
        if self._thread is None:
            self._start_thread()
        result = self._result_q.get()
        if result is None:
            if self._error is not None:
                raise self._error
            # repeat the last reported metrics with the done flag set
            # (reference function_runner.py final-result handling)
            final = dict(self._last_metrics)
            final[DONE] = True
            return final
        self._last_metrics = dict(result)
        self._continue.release()
        return result

    def save_checkpoint(self, checkpoint_dir: str = "") -> Any:
        return self._latest_checkpoint

    def load_checkpoint(self, checkpoint: Any) -> None:
        if isinstance(checkpoint, dict):
            self._restore_checkpoint = checkpoint.get("dir")
        else:
            self._restore_checkpoint = checkpoint

    def cleanup(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            # let the function run to completion on its daemon thread
            self._continue.release()


def wrap_function(train_func: Callable) -> type:
    class _WrappedFunc(FunctionRunner):
        _function = staticmethod(train_func)
    _WrappedFunc.__name__ = getattr(train_func, "__name__", "func")
    return _WrappedFunc
