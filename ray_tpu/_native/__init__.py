"""Native (C++) runtime components, built from source on first use."""

from ray_tpu._native.shm_store import (  # noqa: F401
    NativeUnavailable,
    ShmStore,
    native_available,
)

__all__ = ["ShmStore", "NativeUnavailable", "native_available"]
