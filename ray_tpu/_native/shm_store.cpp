// shm_store — a shared-memory object store (plasma equivalent).
//
// Reference behavior being reimplemented (not copied):
//   src/ray/object_manager/plasma/{store.cc,object_store.cc,
//   eviction_policy.h,plasma_allocator.cc}: a node-local store backed by
//   one mmap'd segment, zero-copy reads by every process on the node,
//   create→seal object lifecycle, pin via refcount, LRU eviction of
//   sealed unreferenced objects when an allocation needs room.
//
// Design differences (TPU-first, and simpler where the reference's
// complexity served GPU/CUDA or legacy paths):
//   - All metadata (object table + free list) lives INSIDE the segment,
//     guarded by one process-shared robust pthread mutex, so any process
//     that maps the file has the full store — there is no store daemon
//     and no unix-socket protocol; the "client" IS the store.
//   - Allocation is first-fit over an offset-sorted free list with
//     coalescing on free (the reference uses dlmalloc; first-fit keeps
//     the whole allocator auditable and the free list lives in-band).
//   - Python maps the same file and reads/writes at returned offsets —
//     numpy/jax arrays view the segment directly (dlpack-free zero-copy).
//
// Built with: g++ -O2 -shared -fPIC -o libshm_store.so shm_store.cpp
// Exposed via ctypes (ray_tpu/_native/shm_store.py).

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x52415954505553ULL;  // "RAYTPUS"
constexpr uint32_t kMaxEntries = 1 << 16;
constexpr uint64_t kAlign = 64;  // cacheline; also friendly to device DMA
constexpr uint32_t kOidLen = 20;

enum EntryState : uint32_t {
  kEmpty = 0,
  kCreated = 1,    // allocated, writer still filling it
  kSealed = 2,     // immutable, readable by everyone
  kTombstone = 3,  // deleted; keeps linear-probe chains intact
  // delete arrived while readers hold pins: bytes stay mapped and
  // valid until the last release, then the block frees (reference:
  // plasma defers deletion of in-use objects until release —
  // object_lifecycle_manager "deletion happens when ref count is 0")
  kPendingDelete = 4,
};

struct Entry {
  uint8_t oid[kOidLen];
  uint32_t state;
  uint64_t offset;
  uint64_t size;
  int32_t refcount;
  uint32_t lru_tick;
};

// Free blocks are kept in-band: each free region starts with this header,
// linked in offset order so adjacent blocks coalesce on free.
struct FreeBlock {
  uint64_t size;
  uint64_t next;  // offset of next free block, 0 = end
};

struct Header {
  uint64_t magic;
  uint64_t capacity;      // bytes of the data region
  uint64_t data_start;    // offset of data region from segment base
  pthread_mutex_t mutex;  // process-shared, robust
  uint64_t free_head;     // offset of first free block (0 = none)
  uint64_t used_bytes;
  uint64_t num_objects;
  uint32_t lru_clock;
  uint64_t num_evictions;
  Entry entries[kMaxEntries];
};

struct Store {
  void* base = nullptr;
  uint64_t mapped_size = 0;
  Header* hdr = nullptr;
  int fd = -1;
  bool in_use = false;
};

constexpr int kMaxStores = 64;
Store g_stores[kMaxStores];
pthread_mutex_t g_stores_mutex = PTHREAD_MUTEX_INITIALIZER;

inline uint64_t align_up(uint64_t v) { return (v + kAlign - 1) & ~(kAlign - 1); }

inline uint8_t* seg(Store* s, uint64_t off) {
  return reinterpret_cast<uint8_t*>(s->base) + off;
}

uint32_t hash_oid(const uint8_t* oid) {
  // FNV-1a over the 20-byte id
  uint32_t h = 2166136261u;
  for (uint32_t i = 0; i < kOidLen; ++i) {
    h ^= oid[i];
    h *= 16777619u;
  }
  return h;
}

// Open-addressed lookup; returns entry index or the first empty slot
// (insert position) when not found. kMaxEntries is a power of two.
int32_t find_slot(Header* hdr, const uint8_t* oid, bool for_insert) {
  uint32_t idx = hash_oid(oid) & (kMaxEntries - 1);
  int32_t first_tomb = -1;
  for (uint32_t probe = 0; probe < kMaxEntries; ++probe) {
    Entry& e = hdr->entries[idx];
    if (e.state == kEmpty) {
      if (!for_insert) return -1;
      return first_tomb >= 0 ? first_tomb : static_cast<int32_t>(idx);
    }
    if (e.state == kTombstone) {
      if (first_tomb < 0) first_tomb = static_cast<int32_t>(idx);
    } else if (memcmp(e.oid, oid, kOidLen) == 0) {
      return static_cast<int32_t>(idx);
    }
    idx = (idx + 1) & (kMaxEntries - 1);
  }
  return for_insert ? first_tomb : -1;
}

void lock(Store* s) {
  int rc = pthread_mutex_lock(&s->hdr->mutex);
  if (rc == EOWNERDEAD) {
    // A process died holding the lock; the table may be mid-update but
    // every transition below is single-field-last, so recover.
    pthread_mutex_consistent(&s->hdr->mutex);
  }
}

void unlock(Store* s) { pthread_mutex_unlock(&s->hdr->mutex); }

// ---- allocator -----------------------------------------------------------

int64_t alloc_locked(Store* s, uint64_t want) {
  Header* hdr = s->hdr;
  want = align_up(want);
  uint64_t prev = 0;
  uint64_t cur = hdr->free_head;
  while (cur) {
    FreeBlock* fb = reinterpret_cast<FreeBlock*>(seg(s, cur));
    if (fb->size >= want) {
      uint64_t remaining = fb->size - want;
      uint64_t next = fb->next;
      if (remaining >= sizeof(FreeBlock) + kAlign) {
        uint64_t new_off = cur + want;
        FreeBlock* nb = reinterpret_cast<FreeBlock*>(seg(s, new_off));
        nb->size = remaining;
        nb->next = next;
        next = new_off;
      }
      if (prev) {
        reinterpret_cast<FreeBlock*>(seg(s, prev))->next = next;
      } else {
        hdr->free_head = next;
      }
      return static_cast<int64_t>(cur);
    }
    prev = cur;
    cur = fb->next;
  }
  return -1;
}

void free_locked(Store* s, uint64_t off, uint64_t size) {
  Header* hdr = s->hdr;
  size = align_up(size);
  // insert sorted by offset, coalescing with neighbors
  uint64_t prev = 0;
  uint64_t cur = hdr->free_head;
  while (cur && cur < off) {
    prev = cur;
    cur = reinterpret_cast<FreeBlock*>(seg(s, cur))->next;
  }
  FreeBlock* nb = reinterpret_cast<FreeBlock*>(seg(s, off));
  nb->size = size;
  nb->next = cur;
  if (cur && off + size == cur) {  // merge with next
    FreeBlock* nxt = reinterpret_cast<FreeBlock*>(seg(s, cur));
    nb->size += nxt->size;
    nb->next = nxt->next;
  }
  if (prev) {
    FreeBlock* pb = reinterpret_cast<FreeBlock*>(seg(s, prev));
    if (prev + pb->size == off) {  // merge with prev
      pb->size += nb->size;
      pb->next = nb->next;
    } else {
      pb->next = off;
    }
  } else {
    hdr->free_head = off;
  }
}

bool fits_locked(Store* s, uint64_t want) {
  want = align_up(want);
  for (uint64_t cur = s->hdr->free_head; cur;
       cur = reinterpret_cast<FreeBlock*>(seg(s, cur))->next) {
    if (reinterpret_cast<FreeBlock*>(seg(s, cur))->size >= want) return true;
  }
  return false;
}

// Evict sealed refcount-0 objects, oldest LRU tick first, until `want`
// bytes fit in one free block (reference: eviction_policy.h
// LRUCache::ChooseObjectsToEvict).
bool evict_locked(Store* s, uint64_t want) {
  Header* hdr = s->hdr;
  while (!fits_locked(s, want)) {
    int32_t victim = -1;
    uint32_t oldest = 0xFFFFFFFFu;
    for (uint32_t i = 0; i < kMaxEntries; ++i) {
      Entry& e = hdr->entries[i];
      if (e.state == kSealed && e.refcount == 0 && e.lru_tick < oldest) {
        oldest = e.lru_tick;
        victim = static_cast<int32_t>(i);
      }
    }
    if (victim < 0) return false;
    Entry& e = hdr->entries[victim];
    free_locked(s, e.offset, e.size ? e.size : kAlign);
    hdr->used_bytes -= align_up(e.size ? e.size : kAlign);
    hdr->num_objects--;
    hdr->num_evictions++;
    e.state = kTombstone;
  }
  return true;
}

}  // namespace

extern "C" {

// Returns handle >= 0, or -1 on failure.
int64_t shm_store_create(const char* path, uint64_t capacity) {
  pthread_mutex_lock(&g_stores_mutex);
  int64_t handle = -1;
  for (int i = 0; i < kMaxStores; ++i) {
    if (!g_stores[i].in_use) {
      handle = i;
      break;
    }
  }
  if (handle < 0) {
    pthread_mutex_unlock(&g_stores_mutex);
    return -1;
  }
  Store* s = &g_stores[handle];
  uint64_t data_start = align_up(sizeof(Header));
  uint64_t total = data_start + align_up(capacity);
  int fd = open(path, O_RDWR | O_CREAT, 0600);
  if (fd < 0 || ftruncate(fd, static_cast<off_t>(total)) != 0) {
    if (fd >= 0) close(fd);
    pthread_mutex_unlock(&g_stores_mutex);
    return -1;
  }
  void* base =
      mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    pthread_mutex_unlock(&g_stores_mutex);
    return -1;
  }
  Header* hdr = reinterpret_cast<Header*>(base);
  memset(hdr, 0, sizeof(Header));
  hdr->capacity = align_up(capacity);
  hdr->data_start = data_start;
  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&hdr->mutex, &attr);
  pthread_mutexattr_destroy(&attr);
  // one free block spanning the data region
  FreeBlock* fb = reinterpret_cast<FreeBlock*>(
      reinterpret_cast<uint8_t*>(base) + data_start);
  fb->size = hdr->capacity;
  fb->next = 0;
  hdr->free_head = data_start;
  hdr->magic = kMagic;  // written last: openers spin on it
  s->base = base;
  s->mapped_size = total;
  s->hdr = hdr;
  s->fd = fd;
  s->in_use = true;
  pthread_mutex_unlock(&g_stores_mutex);
  return handle;
}

int64_t shm_store_open(const char* path) {
  pthread_mutex_lock(&g_stores_mutex);
  int64_t handle = -1;
  for (int i = 0; i < kMaxStores; ++i) {
    if (!g_stores[i].in_use) {
      handle = i;
      break;
    }
  }
  if (handle < 0) {
    pthread_mutex_unlock(&g_stores_mutex);
    return -1;
  }
  int fd = open(path, O_RDWR);
  if (fd < 0) {
    pthread_mutex_unlock(&g_stores_mutex);
    return -1;
  }
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < (off_t)sizeof(Header)) {
    close(fd);
    pthread_mutex_unlock(&g_stores_mutex);
    return -1;
  }
  void* base = mmap(nullptr, static_cast<size_t>(st.st_size),
                    PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    pthread_mutex_unlock(&g_stores_mutex);
    return -1;
  }
  Header* hdr = reinterpret_cast<Header*>(base);
  if (hdr->magic != kMagic) {
    munmap(base, static_cast<size_t>(st.st_size));
    close(fd);
    pthread_mutex_unlock(&g_stores_mutex);
    return -1;
  }
  Store* s = &g_stores[handle];
  s->base = base;
  s->mapped_size = static_cast<uint64_t>(st.st_size);
  s->hdr = hdr;
  s->fd = fd;
  s->in_use = true;
  pthread_mutex_unlock(&g_stores_mutex);
  return handle;
}

void shm_store_close(int64_t handle) {
  pthread_mutex_lock(&g_stores_mutex);
  if (handle >= 0 && handle < kMaxStores && g_stores[handle].in_use) {
    Store* s = &g_stores[handle];
    munmap(s->base, s->mapped_size);
    close(s->fd);
    s->in_use = false;
    s->base = nullptr;
    s->hdr = nullptr;
  }
  pthread_mutex_unlock(&g_stores_mutex);
}

uint64_t shm_store_total_size(int64_t handle) {
  return g_stores[handle].mapped_size;
}

// Create an object: returns data offset (for the writer to fill) or
// -1 = out of memory (after eviction), -2 = already exists, -3 = table full.
int64_t shm_create(int64_t handle, const uint8_t* oid, uint64_t size) {
  Store* s = &g_stores[handle];
  lock(s);
  Header* hdr = s->hdr;
  int32_t existing = find_slot(hdr, oid, false);
  if (existing >= 0) {
    unlock(s);
    return -2;
  }
  int32_t slot = find_slot(hdr, oid, true);
  if (slot < 0) {
    unlock(s);
    return -3;
  }
  int64_t off = alloc_locked(s, size ? size : kAlign);
  if (off < 0) {
    if (!evict_locked(s, size ? size : kAlign)) {
      unlock(s);
      return -1;
    }
    // evict_locked proved a fit exists (and freed its probe allocation
    // path by construction); re-run the allocator for real.
    off = alloc_locked(s, size ? size : kAlign);
    if (off < 0) {
      unlock(s);
      return -1;
    }
  }
  Entry& e = hdr->entries[slot];
  memcpy(e.oid, oid, kOidLen);
  e.offset = static_cast<uint64_t>(off);
  e.size = size;
  e.refcount = 1;  // writer holds a ref until seal+release
  e.lru_tick = ++hdr->lru_clock;
  e.state = kCreated;
  hdr->used_bytes += align_up(size ? size : kAlign);
  hdr->num_objects++;
  unlock(s);
  return off;
}

int32_t shm_seal(int64_t handle, const uint8_t* oid) {
  Store* s = &g_stores[handle];
  lock(s);
  int32_t slot = find_slot(s->hdr, oid, false);
  if (slot < 0 || s->hdr->entries[slot].state != kCreated) {
    unlock(s);
    return -1;
  }
  s->hdr->entries[slot].state = kSealed;
  unlock(s);
  return 0;
}

// Get a sealed object: returns offset, fills *size; pins (refcount+1).
// -1 = not found / not sealed.
int64_t shm_get(int64_t handle, const uint8_t* oid, uint64_t* size) {
  Store* s = &g_stores[handle];
  lock(s);
  Header* hdr = s->hdr;
  int32_t slot = find_slot(hdr, oid, false);
  if (slot < 0 || hdr->entries[slot].state != kSealed) {
    unlock(s);
    return -1;
  }
  Entry& e = hdr->entries[slot];
  e.refcount++;
  e.lru_tick = ++hdr->lru_clock;
  if (size) *size = e.size;
  unlock(s);
  return static_cast<int64_t>(e.offset);
}

int32_t shm_release(int64_t handle, const uint8_t* oid) {
  Store* s = &g_stores[handle];
  lock(s);
  int32_t slot = find_slot(s->hdr, oid, false);
  if (slot < 0) {
    unlock(s);
    return -1;
  }
  Entry& e = s->hdr->entries[slot];
  if (e.refcount > 0) e.refcount--;
  if (e.state == kPendingDelete && e.refcount == 0) {
    // last reader gone: complete the deferred delete
    free_locked(s, e.offset, e.size ? e.size : kAlign);
    s->hdr->used_bytes -= align_up(e.size ? e.size : kAlign);
    s->hdr->num_objects--;
    e.state = kTombstone;
  }
  unlock(s);
  return 0;
}

int32_t shm_contains(int64_t handle, const uint8_t* oid) {
  Store* s = &g_stores[handle];
  lock(s);
  int32_t slot = find_slot(s->hdr, oid, false);
  int32_t sealed =
      (slot >= 0 && s->hdr->entries[slot].state == kSealed) ? 1 : 0;
  unlock(s);
  return sealed;
}

// Owner-driven GC. With readers pinned (refcount > 0) the delete is
// DEFERRED: the entry stops being gettable but its bytes stay valid
// until the last shm_release (plasma's delete-while-in-use rule) — a
// same-host peer reading this object through its own mapping must
// never observe the block recycled under it. -1 = not found.
int32_t shm_delete(int64_t handle, const uint8_t* oid) {
  Store* s = &g_stores[handle];
  lock(s);
  Header* hdr = s->hdr;
  int32_t slot = find_slot(hdr, oid, false);
  if (slot < 0) {
    unlock(s);
    return -1;
  }
  Entry& e = hdr->entries[slot];
  if (e.state == kPendingDelete) {
    // repeated delete (e.g. a peer retrying after an RPC timeout):
    // already deferred; freeing now would recycle the block under the
    // readers the deferral protects
    unlock(s);
    return 0;
  }
  if (e.refcount > 0 && e.state == kSealed) {
    e.state = kPendingDelete;
    unlock(s);
    return 0;
  }
  free_locked(s, e.offset, e.size ? e.size : kAlign);
  hdr->used_bytes -= align_up(e.size ? e.size : kAlign);
  hdr->num_objects--;
  e.state = kTombstone;
  unlock(s);
  return 0;
}

void shm_stats(int64_t handle, uint64_t* capacity, uint64_t* used,
               uint64_t* num_objects, uint64_t* num_evictions) {
  Store* s = &g_stores[handle];
  lock(s);
  if (capacity) *capacity = s->hdr->capacity;
  if (used) *used = s->hdr->used_bytes;
  if (num_objects) *num_objects = s->hdr->num_objects;
  if (num_evictions) *num_evictions = s->hdr->num_evictions;
  unlock(s);
}

}  // extern "C"
