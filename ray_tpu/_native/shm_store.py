"""ctypes binding for the native shm store (shm_store.cpp).

Reference: the plasma client API (src/ray/object_manager/plasma/client.h
Create/Seal/Get/Release/Delete/Contains) — minus the daemon: every
process maps the segment and the C library arbitrates through a
process-shared mutex.

Zero-copy path: ``get_numpy`` returns an ndarray viewing the mmap'd
segment directly; ``jax.device_put`` of that view is the host→HBM feed.
The .so is compiled from source with g++ on first use and cached next to
this file (no pip deps, per the environment's rules).
"""

from __future__ import annotations

import ctypes
import mmap as _mmap
import os
import subprocess
import tempfile
import threading
from typing import Optional, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "shm_store.cpp")
_SO = os.path.join(_HERE, "libshm_store.so")
_build_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None

OID_LEN = 20


class NativeUnavailable(RuntimeError):
    pass


def _build(force: bool = False) -> str:
    # Sanitizer/CI hook: point the loader at a pre-built .so (e.g. an
    # ASAN/TSAN-instrumented build from cpp/run_sanitizers.sh).
    override = os.environ.get("RAY_TPU_SHM_SO")
    if override:
        return override
    with _build_lock:
        if (not force and os.path.exists(_SO)
                and os.path.getmtime(_SO) >= os.path.getmtime(_SRC)):
            return _SO
        tmp = _SO + f".tmp{os.getpid()}"
        cmd = ["g++", "-O2", "-shared", "-fPIC", "-o", tmp, _SRC,
               "-lpthread"]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        except (subprocess.CalledProcessError, FileNotFoundError,
                subprocess.TimeoutExpired) as e:
            detail = getattr(e, "stderr", b"")
            raise NativeUnavailable(
                f"building shm_store failed: {e} {detail!r}") from e
        os.replace(tmp, _SO)
        return _SO


def _load() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    try:
        lib = ctypes.CDLL(_build())
    except OSError as e:
        # A cached/checked-in .so built on another machine (newer glibc,
        # different arch) fails dlopen with mtime evidence that says
        # "fresh" — rebuild from source on THIS machine and retry once.
        try:
            lib = ctypes.CDLL(_build(force=True))
        except OSError:
            raise NativeUnavailable(
                f"loading shm_store failed: {e}") from e
    u64p = ctypes.POINTER(ctypes.c_uint64)
    lib.shm_store_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
    lib.shm_store_create.restype = ctypes.c_int64
    lib.shm_store_open.argtypes = [ctypes.c_char_p]
    lib.shm_store_open.restype = ctypes.c_int64
    lib.shm_store_close.argtypes = [ctypes.c_int64]
    lib.shm_store_total_size.argtypes = [ctypes.c_int64]
    lib.shm_store_total_size.restype = ctypes.c_uint64
    lib.shm_create.argtypes = [ctypes.c_int64, ctypes.c_char_p,
                               ctypes.c_uint64]
    lib.shm_create.restype = ctypes.c_int64
    lib.shm_seal.argtypes = [ctypes.c_int64, ctypes.c_char_p]
    lib.shm_seal.restype = ctypes.c_int32
    lib.shm_get.argtypes = [ctypes.c_int64, ctypes.c_char_p, u64p]
    lib.shm_get.restype = ctypes.c_int64
    lib.shm_release.argtypes = [ctypes.c_int64, ctypes.c_char_p]
    lib.shm_release.restype = ctypes.c_int32
    lib.shm_contains.argtypes = [ctypes.c_int64, ctypes.c_char_p]
    lib.shm_contains.restype = ctypes.c_int32
    lib.shm_delete.argtypes = [ctypes.c_int64, ctypes.c_char_p]
    lib.shm_delete.restype = ctypes.c_int32
    lib.shm_stats.argtypes = [ctypes.c_int64, u64p, u64p, u64p, u64p]
    _lib = lib
    return lib


def _norm_oid(object_id) -> bytes:
    if hasattr(object_id, "binary"):
        raw = object_id.binary()
    elif isinstance(object_id, str):
        raw = bytes.fromhex(object_id)[:OID_LEN]
    else:
        raw = bytes(object_id)
    if len(raw) < OID_LEN:
        raw = raw.ljust(OID_LEN, b"\0")
    return raw[:OID_LEN]


class ShmStore:
    """One node-local shared-memory store segment."""

    def __init__(self, path: Optional[str] = None,
                 capacity: int = 256 * 1024 * 1024,
                 create: bool = True):
        self._lib = _load()
        if path is None:
            shm_dir = "/dev/shm" if os.path.isdir("/dev/shm") else \
                tempfile.gettempdir()
            path = os.path.join(
                shm_dir, f"ray_tpu_store_{os.getpid()}_{id(self):x}")
        self.path = path
        if create:
            self._handle = self._lib.shm_store_create(
                path.encode(), capacity)
        else:
            self._handle = self._lib.shm_store_open(path.encode())
        if self._handle < 0:
            raise NativeUnavailable(f"could not map store at {path}")
        total = self._lib.shm_store_total_size(self._handle)
        self._fd = os.open(path, os.O_RDWR)
        self._mm = _mmap.mmap(self._fd, total)
        self._owner = create
        if create and os.environ.get("RAY_TPU_SHM_PREFAULT", "1") == "1":
            self._prefault()

    def _prefault(self) -> None:
        """Touch one byte per page so physical tmpfs pages exist before
        the data path runs — the same pay-at-boot choice plasma makes by
        allocating its pool up front. First-touch shmem faults measured
        132 us/page on the r05 build VM: a 1 GiB put crawled at 30-260
        MiB/s while warm copies ran 1.7-5.6 GiB/s. ``|= 0`` preserves
        the C store's freshly initialized header (single-threaded here:
        the segment is not yet announced to any peer)."""
        import numpy as np

        np.frombuffer(self._mm, dtype=np.uint8)[::_mmap.PAGESIZE] |= 0

    # ----------------------------------------------------------- lifecycle
    @classmethod
    def open(cls, path: str) -> "ShmStore":
        return cls(path=path, create=False)

    def close(self, unlink: bool = False) -> None:
        if self._handle >= 0:
            self._lib.shm_store_close(self._handle)
            self._handle = -1
            self._mm.close()
            os.close(self._fd)
            if (unlink or self._owner) and os.path.exists(self.path):
                try:
                    os.unlink(self.path)
                except OSError:
                    pass

    def __del__(self):
        try:
            if getattr(self, "_handle", -1) >= 0:
                self.close()
        except Exception:
            pass

    # ----------------------------------------------------------------- API
    def create(self, object_id, size: int) -> memoryview:
        """Allocate; returns a writable view. Follow with seal()."""
        oid = _norm_oid(object_id)
        off = self._lib.shm_create(self._handle, oid, size)
        if off == -2:
            raise KeyError(f"object {oid.hex()} already exists")
        if off < 0:
            raise MemoryError(
                f"store full (create of {size} bytes failed: {off})")
        return memoryview(self._mm)[off:off + size]

    def seal(self, object_id) -> None:
        oid = _norm_oid(object_id)
        if self._lib.shm_seal(self._handle, oid) != 0:
            raise KeyError(f"cannot seal {oid.hex()}")
        # the writer's implicit ref drops at seal time
        self._lib.shm_release(self._handle, oid)

    def put_bytes(self, object_id, data: bytes) -> None:
        buf = self.create(object_id, len(data))
        buf[:] = data
        self.seal(object_id)

    def put_numpy(self, object_id, arr: np.ndarray) -> None:
        arr = np.ascontiguousarray(arr)
        buf = self.create(object_id, arr.nbytes)
        np.frombuffer(buf, dtype=arr.dtype).reshape(arr.shape)[...] = arr
        self.seal(object_id)

    def get_buffer(self, object_id) -> Optional[memoryview]:
        """Pins the object; pair with release()."""
        region = self.pin_region(object_id)
        if region is None:
            return None
        off, size = region
        return memoryview(self._mm)[off:off + size]

    def pin_region(self, object_id) -> Optional[Tuple[int, int]]:
        """Pin the object and return its (offset, size) in the segment.
        The caller (or another process holding the same segment mapping)
        can then read the block via region() WITHOUT a state lookup —
        valid until release(), even if the entry is deleted meanwhile
        (deferred delete keeps pinned blocks intact). This is the
        plasma handoff: the store pins, clients read (offset, size)
        through their own mapping."""
        oid = _norm_oid(object_id)
        size = ctypes.c_uint64()
        off = self._lib.shm_get(self._handle, oid, ctypes.byref(size))
        if off < 0:
            return None
        return off, size.value

    def region(self, offset: int, size: int) -> memoryview:
        """Raw view of a pinned block (see pin_region)."""
        return memoryview(self._mm)[offset:offset + size]

    def get_bytes(self, object_id) -> Optional[bytes]:
        buf = self.get_buffer(object_id)
        if buf is None:
            return None
        try:
            return bytes(buf)
        finally:
            self.release(object_id)

    def get_numpy(self, object_id, dtype, shape) -> Optional[np.ndarray]:
        """Zero-copy ndarray over the shm segment (caller must release()
        after the array's last use)."""
        buf = self.get_buffer(object_id)
        if buf is None:
            return None
        return np.frombuffer(buf, dtype=dtype).reshape(shape)

    def release(self, object_id) -> None:
        self._lib.shm_release(self._handle, _norm_oid(object_id))

    def contains(self, object_id) -> bool:
        return bool(self._lib.shm_contains(self._handle,
                                           _norm_oid(object_id)))

    def delete(self, object_id) -> bool:
        return self._lib.shm_delete(self._handle,
                                    _norm_oid(object_id)) == 0

    def stats(self) -> dict:
        cap = ctypes.c_uint64()
        used = ctypes.c_uint64()
        num = ctypes.c_uint64()
        ev = ctypes.c_uint64()
        self._lib.shm_stats(self._handle, ctypes.byref(cap),
                            ctypes.byref(used), ctypes.byref(num),
                            ctypes.byref(ev))
        return {"capacity": cap.value, "used": used.value,
                "num_objects": num.value, "num_evictions": ev.value}


def native_available() -> bool:
    try:
        _load()
        return True
    except NativeUnavailable:
        return False
