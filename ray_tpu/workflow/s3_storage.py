"""S3-style workflow storage backend.

Reference: python/ray/workflow/storage/s3.py (aioboto3 against a
bucket/prefix) alongside the filesystem backend. This backend speaks
the boto3 S3 client surface — ``put_object`` / ``get_object`` /
``list_objects_v2`` / ``delete_object`` / ``head_object`` — through an
injected client, so it runs against real S3 (pass a ``boto3`` client),
any S3-compatible object store (MinIO et al.), or the in-process
:class:`FakeS3Client` used by the test suite (this image has no boto3
and no egress; the seam is what parity requires).

``Storage.update`` needs cross-client atomicity that base S3 lacks; it
is implemented with a conditional-put lock object (``If-None-Match:
*``, supported by S3 since 2024 and by the fake) with TTL takeover for
crashed holders.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

try:
    import cloudpickle as pickle
except ImportError:  # pragma: no cover
    import pickle

from ray_tpu.workflow.storage import Storage


class _ClientError(Exception):
    """Stand-in for botocore.exceptions.ClientError when botocore is
    absent; carries the same ``response['Error']['Code']`` shape."""

    def __init__(self, code: str):
        super().__init__(code)
        self.response = {"Error": {"Code": code}}


def _error_code(exc: Exception) -> str:
    response = getattr(exc, "response", None)
    if isinstance(response, dict):
        return str(response.get("Error", {}).get("Code", ""))
    return ""


class FakeS3Client:
    """In-memory boto3-shaped S3 client: enough of the surface for
    S3Storage, with real If-None-Match conditional-put semantics so the
    lock protocol is exercised honestly. Thread-safe."""

    def __init__(self, page_size: int = 1000):
        self._buckets: Dict[str, Dict[str, bytes]] = {}
        self._lock = threading.Lock()
        self._page_size = page_size  # small in tests: exercises paging

    def _bucket(self, name: str) -> Dict[str, bytes]:
        return self._buckets.setdefault(name, {})

    def put_object(self, Bucket: str, Key: str, Body: bytes,
                   IfNoneMatch: Optional[str] = None, **_):
        with self._lock:
            bucket = self._bucket(Bucket)
            if IfNoneMatch == "*" and Key in bucket:
                raise _ClientError("PreconditionFailed")
            bucket[Key] = bytes(Body)
        return {}

    def get_object(self, Bucket: str, Key: str, **_):
        import io

        with self._lock:
            bucket = self._bucket(Bucket)
            if Key not in bucket:
                raise _ClientError("NoSuchKey")
            return {"Body": io.BytesIO(bucket[Key])}

    def head_object(self, Bucket: str, Key: str, **_):
        with self._lock:
            if Key not in self._bucket(Bucket):
                raise _ClientError("404")
            return {"ContentLength": len(self._bucket(Bucket)[Key])}

    def delete_object(self, Bucket: str, Key: str, **_):
        with self._lock:
            self._bucket(Bucket).pop(Key, None)
        return {}

    def list_objects_v2(self, Bucket: str, Prefix: str = "",
                        ContinuationToken: Optional[str] = None, **_):
        with self._lock:
            keys = sorted(k for k in self._bucket(Bucket)
                          if k.startswith(Prefix))
        start = int(ContinuationToken) if ContinuationToken else 0
        page = keys[start:start + self._page_size]
        truncated = start + self._page_size < len(keys)
        out = {"Contents": [{"Key": k} for k in page],
               "IsTruncated": truncated}
        if truncated:
            out["NextContinuationToken"] = str(start + self._page_size)
        return out


class S3Storage(Storage):
    """Workflow storage over an S3 bucket/prefix.

    client: a boto3-compatible S3 client (injected — real boto3, an
    S3-compatible store's client, or FakeS3Client).
    """

    LOCK_TTL_S = 30.0

    def __init__(self, client, bucket: str, prefix: str = "workflows"):
        self.client = client
        self.bucket = bucket
        self.prefix = prefix.strip("/")

    def _key(self, key: str) -> str:
        return f"{self.prefix}/{key}" if self.prefix else key

    # ------------------------------------------------------------ Storage
    def put(self, key: str, value: Any) -> None:
        self.client.put_object(Bucket=self.bucket, Key=self._key(key),
                               Body=pickle.dumps(value))

    def get(self, key: str, default: Any = None) -> Any:
        try:
            obj = self.client.get_object(Bucket=self.bucket,
                                         Key=self._key(key))
        except Exception as e:  # noqa: BLE001 — keyed miss only
            if _error_code(e) in ("NoSuchKey", "404"):
                return default
            raise
        return pickle.loads(obj["Body"].read())

    def exists(self, key: str) -> bool:
        try:
            self.client.head_object(Bucket=self.bucket,
                                    Key=self._key(key))
            return True
        except Exception as e:  # noqa: BLE001
            if _error_code(e) in ("NoSuchKey", "404", "NotFound"):
                return False
            raise

    def _list_all(self, prefix: str) -> List[str]:
        """Every key under the prefix, following pagination — real S3
        truncates at 1000 keys per page."""
        keys: List[str] = []
        token = None
        while True:
            kwargs = {"Bucket": self.bucket, "Prefix": prefix}
            if token:
                kwargs["ContinuationToken"] = token
            listing = self.client.list_objects_v2(**kwargs)
            keys.extend(i["Key"] for i in listing.get("Contents", []))
            if not listing.get("IsTruncated"):
                return keys
            token = listing.get("NextContinuationToken")
            if not token:
                return keys

    def delete_prefix(self, prefix: str) -> None:
        """Directory semantics like FilesystemStorage: the key itself
        plus everything under '<key>/' — NOT bare string-prefix
        matching, which would let delete('wf1') destroy 'wf10'."""
        full = self._key(prefix).rstrip("/")
        for key in self._list_all(full):
            if key == full or key.startswith(full + "/"):
                self.client.delete_object(Bucket=self.bucket, Key=key)

    def list_prefix(self, prefix: str) -> List[str]:
        """Immediate children under the prefix (directory-listing
        semantics, matching FilesystemStorage.list_prefix)."""
        full = self._key(prefix).rstrip("/") + "/"
        children = set()
        for key in self._list_all(full):
            rest = key[len(full):]
            if rest:
                children.add(rest.split("/", 1)[0])
        return sorted(children)

    def update(self, key: str, fn) -> Any:
        """Atomic read-modify-write via a conditional-put lock object:
        the writer that creates ``<key>.lock`` with If-None-Match:*
        wins; losers poll. A lock older than LOCK_TTL_S is presumed
        crashed and TAKEN OVER by overwrite-with-token + read-back:
        every contender writes its unique token and only the one whose
        token survives a settle window holds the lock — an
        unconditional delete here would let two waiters both "free" the
        lock (the second deleting the first winner's fresh lock) and
        run the critical section concurrently."""
        import uuid

        lock_key = self._key(key) + ".lock"
        token = uuid.uuid4().hex
        deadline = time.monotonic() + 60.0

        def lock_body() -> bytes:
            return f"{time.time()}|{token}".encode()

        while True:
            try:
                self.client.put_object(
                    Bucket=self.bucket, Key=lock_key,
                    Body=lock_body(), IfNoneMatch="*")
                break
            except Exception as e:  # noqa: BLE001 — contended lock
                if _error_code(e) not in ("PreconditionFailed", "412"):
                    raise
                took_over = False
                try:
                    obj = self.client.get_object(Bucket=self.bucket,
                                                 Key=lock_key)
                    held_since = float(
                        obj["Body"].read().split(b"|")[0])
                    if time.time() - held_since > self.LOCK_TTL_S:
                        # stale: overwrite with MY token, settle, and
                        # read back — exactly one contender survives
                        self.client.put_object(Bucket=self.bucket,
                                               Key=lock_key,
                                               Body=lock_body())
                        time.sleep(0.05)
                        obj = self.client.get_object(
                            Bucket=self.bucket, Key=lock_key)
                        took_over = obj["Body"].read().split(
                            b"|")[-1].decode() == token
                except Exception:  # noqa: BLE001 — holder released
                    continue
                if took_over:
                    break
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"workflow storage lock {lock_key} held past "
                        "deadline") from None
                time.sleep(0.05)
        try:
            value = fn(self.get(key))
            self.put(key, value)
            return value
        finally:
            self.client.delete_object(Bucket=self.bucket, Key=lock_key)


def storage_from_url(url: str) -> Storage:
    """``s3://bucket/prefix`` -> S3Storage over a real boto3 client
    (raises a clear error when boto3 is absent); anything else ->
    FilesystemStorage on that path."""
    from ray_tpu.workflow.storage import FilesystemStorage

    if url.startswith("s3://"):
        rest = url[len("s3://"):]
        bucket, _, prefix = rest.partition("/")
        try:
            import boto3  # type: ignore
        except ImportError as e:
            raise RuntimeError(
                "s3:// workflow storage needs boto3; install it or "
                "inject an S3-compatible client via "
                "S3Storage(client, bucket, prefix)") from e
        return S3Storage(boto3.client("s3"), bucket, prefix)
    return FilesystemStorage(url)
