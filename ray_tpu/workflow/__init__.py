"""ray_tpu.workflow — durable workflows with checkpointed steps.

Reference surface: python/ray/workflow/__init__.py (@workflow.step,
run/resume, virtual actors, storage backends).
"""

from ray_tpu.workflow.api import (  # noqa: F401
    EventListener,
    WorkflowStep,
    WorkflowStepNode,
    cancel,
    delete,
    get_actor,
    get_output,
    get_status,
    init,
    list_all,
    resume,
    run,
    sleep,
    step,
    virtual_actor,
    wait_for_event,
)
from ray_tpu.workflow.storage import (  # noqa: F401
    FilesystemStorage,
    Storage,
    get_global_storage,
    set_global_storage,
)

__all__ = [
    "step", "init", "resume", "run", "cancel", "get_status",
    "get_output", "list_all", "delete", "virtual_actor", "get_actor",
    "sleep", "wait_for_event", "EventListener",
    "WorkflowStep", "WorkflowStepNode",
    "Storage", "FilesystemStorage", "get_global_storage",
    "set_global_storage",
]
