"""ray_tpu.workflow — durable workflows.

Reference: python/ray/workflow/api.py (@workflow.step:94, run/resume:196,
virtual_actor:130), step_executor.py, recovery.py. Semantics:

  - ``@workflow.step`` wraps a function; ``.step(args)`` builds a DAG node
    lazily; ``.run(workflow_id)`` executes it with every step's output
    checkpointed to storage.
  - A step whose argument is another step runs after that dependency;
    dependency outputs are substituted in.
  - A step may *return* another step (continuation); the workflow's
    result is the continuation's result.
  - ``workflow.resume(workflow_id)`` replays the DAG: finished steps are
    loaded from their checkpoints, unfinished ones re-execute.
  - Virtual actors: durable state checkpointed after every method call.
"""

from __future__ import annotations

import functools
import uuid
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.workflow.storage import (
    FilesystemStorage,
    Storage,
    get_global_storage,
    set_global_storage,
)

_STATUS_RUNNING = "RUNNING"
_STATUS_SUCCESSFUL = "SUCCESSFUL"
_STATUS_FAILED = "FAILED"
_STATUS_CANCELED = "CANCELED"


def init(storage: Optional[str] = None) -> None:
    """Set the workflow storage root — a filesystem path or an
    ``s3://bucket/prefix`` URL (reference: workflow.init + storage/)."""
    if storage is not None:
        from ray_tpu.workflow.s3_storage import storage_from_url

        set_global_storage(storage_from_url(storage))
    if not ray_tpu.is_initialized():
        ray_tpu.init()


class WorkflowStepNode:
    """A node in the (lazy) workflow DAG."""

    def __init__(self, func, args: tuple, kwargs: dict,
                 step_id: Optional[str] = None, max_retries: int = 0,
                 catch_exceptions: bool = False):
        self.func = func
        self.args = args
        self.kwargs = kwargs
        self.step_id = step_id or f"{func.__name__}_{uuid.uuid4().hex[:8]}"
        self.max_retries = max_retries
        self.catch_exceptions = catch_exceptions

    # ------------------------------------------------------------ execution
    def _execute(self, workflow_id: str, storage: Storage) -> Any:
        meta = storage.get(f"{workflow_id}/meta.json") or {}
        if meta.get("status") == _STATUS_CANCELED:
            # checkpoint-boundary stop: no further steps launch
            raise RuntimeError(f"workflow {workflow_id!r} was canceled")
        key_out = f"{workflow_id}/steps/{self.step_id}/output.pkl"
        if storage.exists(key_out):
            return storage.get(key_out)

        # resolve upstream dependencies first (post-order DAG walk)
        def resolve(v):
            if isinstance(v, WorkflowStepNode):
                return v._execute(workflow_id, storage)
            return v

        args = tuple(resolve(a) for a in self.args)
        kwargs = {k: resolve(v) for k, v in self.kwargs.items()}
        # dependencies may have run for a while: re-check cancellation
        # right before launching THIS step (the DAG-descent check above
        # happens within milliseconds of run start)
        meta = storage.get(f"{workflow_id}/meta.json") or {}
        if meta.get("status") == _STATUS_CANCELED:
            raise RuntimeError(f"workflow {workflow_id!r} was canceled")
        storage.put(f"{workflow_id}/steps/{self.step_id}/input.pkl",
                    (self.func, args, kwargs))

        @ray_tpu.remote(max_retries=self.max_retries, retry_exceptions=True)
        def _run_step(func, a, kw):
            return func(*a, **kw)

        try:
            result = ray_tpu.get([_run_step.remote(self.func, args,
                                                   kwargs)])[0]
        except Exception as e:  # noqa: BLE001
            if self.catch_exceptions:
                result = (None, e)
                storage.put(key_out, result)
                return result
            raise
        if isinstance(result, WorkflowStepNode):
            # continuation: the step returned another step
            result = result._execute(workflow_id, storage)
        if self.catch_exceptions:
            result = (result, None)
        storage.put(key_out, result)
        return result

    def run(self, workflow_id: Optional[str] = None) -> Any:
        return ray_tpu.get([self.run_async(workflow_id)])[0]

    def run_async(self, workflow_id: Optional[str] = None
                  ) -> "ray_tpu.ObjectRef":
        workflow_id = workflow_id or uuid.uuid4().hex
        storage = get_global_storage()
        storage.put(f"{workflow_id}/meta.json",
                    {"status": _STATUS_RUNNING})
        storage.put(f"{workflow_id}/entry.pkl", self)
        node = self

        @ray_tpu.remote
        def _drive():
            def finish(status: str) -> None:
                # CANCELED is terminal and must win every race: the
                # check-and-write is one atomic update (a cancel landing
                # between a separate get and put would be overwritten
                # and the workflow would resume as if never canceled)
                def fn(meta):
                    meta = dict(meta or {})
                    if meta.get("status") != _STATUS_CANCELED:
                        meta["status"] = status
                    return meta

                storage.update(f"{workflow_id}/meta.json", fn)

            try:
                result = node._execute(workflow_id, storage)
            except Exception:
                finish(_STATUS_FAILED)
                raise
            storage.put(f"{workflow_id}/result.pkl", result)
            finish(_STATUS_SUCCESSFUL)
            return result

        return _drive.remote()


class WorkflowStep:
    """The ``@workflow.step`` wrapper; ``.step(...)`` builds DAG nodes."""

    def __init__(self, func, max_retries: int = 0,
                 catch_exceptions: bool = False):
        self.func = func
        self.max_retries = max_retries
        self.catch_exceptions = catch_exceptions
        functools.update_wrapper(self, func)

    def step(self, *args, **kwargs) -> WorkflowStepNode:
        return WorkflowStepNode(self.func, args, kwargs,
                                max_retries=self.max_retries,
                                catch_exceptions=self.catch_exceptions)

    def options(self, *, max_retries: Optional[int] = None,
                catch_exceptions: Optional[bool] = None) -> "WorkflowStep":
        return WorkflowStep(
            self.func,
            self.max_retries if max_retries is None else max_retries,
            self.catch_exceptions if catch_exceptions is None
            else catch_exceptions)

    def __call__(self, *args, **kwargs):
        raise TypeError("workflow steps cannot be called directly; "
                        "use .step(...)")


def step(_func=None, *, max_retries: int = 0, catch_exceptions: bool = False):
    def wrap(func):
        return WorkflowStep(func, max_retries, catch_exceptions)

    if _func is not None:
        return wrap(_func)
    return wrap


# ---------------------------------------------------------------- recovery
def resume(workflow_id: str) -> Any:
    """Re-run a workflow; finished steps short-circuit to their
    checkpoints (reference: workflow/recovery.py resume)."""
    storage = get_global_storage()
    entry: Optional[WorkflowStepNode] = storage.get(
        f"{workflow_id}/entry.pkl")
    if entry is None:
        raise ValueError(f"no workflow with id {workflow_id!r}")
    meta = storage.get(f"{workflow_id}/meta.json") or {}
    if meta.get("status") == _STATUS_SUCCESSFUL:
        return storage.get(f"{workflow_id}/result.pkl")
    if meta.get("status") == _STATUS_CANCELED:
        raise ValueError(f"workflow {workflow_id!r} was canceled")
    return entry.run(workflow_id)


def get_status(workflow_id: str) -> Optional[str]:
    meta = get_global_storage().get(f"{workflow_id}/meta.json")
    return None if meta is None else meta.get("status")


def get_output(workflow_id: str) -> Any:
    storage = get_global_storage()
    meta = storage.get(f"{workflow_id}/meta.json") or {}
    if meta.get("status") != _STATUS_SUCCESSFUL:
        raise ValueError(f"workflow {workflow_id!r} has not finished "
                         f"(status={meta.get('status')})")
    return storage.get(f"{workflow_id}/result.pkl")


def list_all() -> List[str]:
    return get_global_storage().list_prefix("")


def delete(workflow_id: str) -> None:
    get_global_storage().delete_prefix(workflow_id)


# ------------------------------------------------------------ virtual actor
class VirtualActorClass:
    def __init__(self, cls):
        self._cls = cls

    def get_or_create(self, actor_id: str) -> "VirtualActorHandle":
        return VirtualActorHandle(self._cls, actor_id)


class VirtualActorHandle:
    """Durable actor: state is loaded from storage before each call and
    checkpointed after (reference: workflow virtual actors — state lives
    in storage, compute is stateless)."""

    def __init__(self, cls, actor_id: str):
        self._cls = cls
        self._actor_id = actor_id
        storage = get_global_storage()
        key = f"virtual_actors/{actor_id}/state.pkl"
        if not storage.exists(key):
            instance = cls.__new__(cls)
            instance.__init__()
            storage.put(key, instance.__getstate__()
                        if hasattr(instance, "__getstate__")
                        else instance.__dict__)
            # recorded so get_actor(actor_id) works without the class
            storage.put(f"virtual_actors/{actor_id}/class.pkl", cls)

    def __getattr__(self, method_name: str):
        if method_name.startswith("_"):
            raise AttributeError(method_name)
        cls, actor_id = self._cls, self._actor_id

        class _Caller:
            def run(self, *args, **kwargs):
                storage = get_global_storage()
                key = f"virtual_actors/{actor_id}/state.pkl"

                @ray_tpu.remote
                def _call(state, a, kw):
                    instance = cls.__new__(cls)
                    instance.__dict__.update(state)
                    result = getattr(instance, method_name)(*a, **kw)
                    return result, dict(instance.__dict__)

                result, new_state = ray_tpu.get(
                    [_call.remote(storage.get(key), args, kwargs)])[0]
                storage.put(key, new_state)
                return result

        return _Caller()


def virtual_actor(cls) -> VirtualActorClass:
    return VirtualActorClass(cls)


def get_actor(actor_id: str, cls=None) -> VirtualActorHandle:
    """Handle to an existing virtual actor by id (reference:
    workflow.get_actor). The class is recorded at creation so plain
    lookups don't need it."""
    storage = get_global_storage()
    if not storage.exists(f"virtual_actors/{actor_id}/state.pkl"):
        # lookups never create: a typo'd id must not mint a fresh actor
        raise KeyError(f"no virtual actor {actor_id!r}")
    if cls is None:
        cls = storage.get(f"virtual_actors/{actor_id}/class.pkl")
        if cls is None:
            raise KeyError(f"no virtual actor {actor_id!r}")
    return VirtualActorHandle(cls, actor_id)


def run(node: WorkflowStepNode, workflow_id: Optional[str] = None) -> Any:
    """Module-level alias of node.run (reference: workflow.run)."""
    return node.run(workflow_id)



def cancel(workflow_id: str) -> None:
    """Mark a workflow CANCELED: get_output refuses and resume will not
    restart it (reference: workflow.cancel — steps already running are
    not preempted, matching the reference's checkpoint-boundary
    semantics)."""
    storage = get_global_storage()
    if storage.get(f"{workflow_id}/meta.json") is None:
        raise ValueError(f"no workflow with id {workflow_id!r}")

    def fn(meta):
        meta = dict(meta or {})
        meta["status"] = _STATUS_CANCELED
        return meta

    storage.update(f"{workflow_id}/meta.json", fn)


class EventListener:
    """Poll-based event source (reference: workflow/event_listener.py —
    the async listener's poll_for_event, sync here). Subclass and
    implement poll_for_event(*args) to return the event payload or None
    while the event has not happened."""

    def poll_for_event(self, *args) -> Any:
        raise NotImplementedError


def wait_for_event(listener_cls, *args, poll_interval_s: float = 0.1,
                   timeout_s: Optional[float] = None) -> WorkflowStepNode:
    """A step that completes when the listener observes its event —
    composable with other steps (reference: workflow.wait_for_event)."""
    import time as _time

    @step
    def _wait(listener_args):
        listener = listener_cls()
        deadline = (None if timeout_s is None
                    else _time.monotonic() + timeout_s)
        while True:
            payload = listener.poll_for_event(*listener_args)
            if payload is not None:
                return payload
            if deadline is not None and _time.monotonic() > deadline:
                raise TimeoutError(
                    f"event from {listener_cls.__name__} not observed "
                    f"within {timeout_s}s")
            _time.sleep(poll_interval_s)

    return _wait.step(args)


def sleep(duration_s: float) -> WorkflowStepNode:
    """A durable pause step (reference: workflow.sleep)."""
    import time as _time

    @step
    def _sleep(d):
        _time.sleep(d)
        return None

    return _sleep.step(duration_s)
