"""Workflow storage — durable checkpoints for steps and virtual actors.

Reference: python/ray/workflow/storage/ (base + filesystem) and
workflow_storage.py. Layout on disk:

    <root>/<workflow_id>/
        steps/<step_id>/
            input.pkl      (func, args, kwargs — enough to re-execute)
            output.pkl     (present only once the step finished)
        state.pkl          (virtual-actor state)
        meta.json          (entry step, status)
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Any, List, Optional

# cloudpickle so steps defined in local scopes (closures, lambdas) are
# durable, matching the reference's serializer choice
try:
    import cloudpickle as pickle
except ImportError:  # pragma: no cover
    import pickle


class Storage:
    def put(self, key: str, value: Any) -> None:
        raise NotImplementedError

    def get(self, key: str, default: Any = None) -> Any:
        raise NotImplementedError

    def exists(self, key: str) -> bool:
        raise NotImplementedError

    def delete_prefix(self, prefix: str) -> None:
        raise NotImplementedError

    def list_prefix(self, prefix: str) -> List[str]:
        raise NotImplementedError

    def update(self, key: str, fn) -> Any:
        """Atomic read-modify-write: apply ``fn(current_value)`` and
        store the result, excluding concurrent updaters. Backends MUST
        implement this with a real mutual-exclusion primitive; status
        transitions (RUNNING -> CANCELED vs -> SUCCESSFUL) depend on it
        being atomic across processes — a get+put fallback here would
        silently reintroduce the cancel-overwrite race."""
        raise NotImplementedError


class FilesystemStorage(Storage):
    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.root, key)

    def put(self, key: str, value: Any) -> None:
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        # atomic write: tmp file + rename, so a crash never leaves a
        # half-written checkpoint (reference: filesystem storage does the
        # same dance)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path))
        try:
            with os.fdopen(fd, "wb") as f:
                pickle.dump(value, f)
            os.replace(tmp, path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    def get(self, key: str, default: Any = None) -> Any:
        path = self._path(key)
        if not os.path.exists(path):
            return default
        with open(path, "rb") as f:
            return pickle.load(f)

    def exists(self, key: str) -> bool:
        return os.path.exists(self._path(key))

    def delete_prefix(self, prefix: str) -> None:
        import shutil

        path = self._path(prefix)
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.unlink(path)

    def list_prefix(self, prefix: str) -> List[str]:
        path = self._path(prefix)
        if not os.path.isdir(path):
            return []
        return sorted(os.listdir(path))

    def update(self, key: str, fn) -> Any:
        """Cross-process atomic read-modify-write via flock on a
        sidecar lock file (the meta file itself is replaced by put's
        atomic rename, so it cannot carry the lock)."""
        import fcntl

        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path + ".lock", "w") as lockf:
            fcntl.flock(lockf, fcntl.LOCK_EX)
            try:
                value = fn(self.get(key))
                self.put(key, value)
                return value
            finally:
                fcntl.flock(lockf, fcntl.LOCK_UN)


_global_storage: Optional[Storage] = None


def set_global_storage(storage: Storage) -> None:
    global _global_storage
    _global_storage = storage


def get_global_storage() -> Storage:
    global _global_storage
    if _global_storage is None:
        root = os.environ.get(
            "RAY_TPU_WORKFLOW_STORAGE",
            os.path.join(tempfile.gettempdir(), "ray_tpu_workflows"))
        # s3://bucket/prefix routes to the S3 backend (reference ships
        # storage/s3.py next to filesystem); plain paths stay local
        from ray_tpu.workflow.s3_storage import storage_from_url

        _global_storage = storage_from_url(root)
    return _global_storage
