"""Training callbacks.

Mirrors the reference's ray.train callbacks
(python/ray/train/callbacks/): TrainingCallback protocol plus JSON and
print loggers; results flow in once per lock-step round.
"""

from __future__ import annotations

import json
import logging
from pathlib import Path
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)


class TrainingCallback:
    def start_training(self, logdir: str, config: Optional[Dict] = None,
                       **info) -> None:
        pass

    def handle_result(self, results: List[Dict], **info) -> None:
        pass

    def finish_training(self, error: bool = False, **info) -> None:
        pass


class PrintCallback(TrainingCallback):
    def handle_result(self, results: List[Dict], **info) -> None:
        print(json.dumps(results, default=str))


class JsonLoggerCallback(TrainingCallback):
    """Appends one JSON line per round to results.json in the run dir."""

    def __init__(self, filename: str = "results.json"):
        self.filename = filename
        self.logdir: Optional[Path] = None
        self._results: List[List[Dict]] = []

    @property
    def log_path(self) -> Optional[Path]:
        return self.logdir / self.filename if self.logdir else None

    def start_training(self, logdir: str, config: Optional[Dict] = None,
                       **info) -> None:
        self.logdir = Path(logdir)
        self.logdir.mkdir(parents=True, exist_ok=True)
        self._results = []
        with open(self.log_path, "w") as f:
            json.dump([], f)

    def handle_result(self, results: List[Dict], **info) -> None:
        self._results.append(results)
        with open(self.log_path, "w") as f:
            json.dump(self._results, f, default=str)

    def finish_training(self, error: bool = False, **info) -> None:
        pass
