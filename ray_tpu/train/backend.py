"""Backend executor — orchestration core of ray_tpu.train.

Mirrors the reference's ray.train BackendExecutor
(python/ray/train/backend.py:104): creates the placement group
(backend.py:190), starts the worker group, initializes the per-worker
session, streams results, and restarts workers from the latest checkpoint
on failure (handle_failure, backend.py:60).

TPU-first: the default backend is ``JaxConfig`` — workers learn their
(world_rank, world_size) and, on multi-host TPU pods, each worker process
maps to one host of the pod with jax.distributed-style coordination; in
in-process mode they share the host's chips through one mesh.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, TypeVar

import ray_tpu
from ray_tpu.train.session import TrainingResult, TrainingResultType
from ray_tpu.train.worker_group import WorkerGroup

T = TypeVar("T")
logger = logging.getLogger(__name__)


class TrainBackendError(Exception):
    pass


class TrainingWorkerError(Exception):
    """A worker died during training; the executor restarts the group."""


@dataclass
class BackendConfig:
    """Base config; subclasses pick the backend class."""

    @property
    def backend_cls(self):
        return Backend


class Backend:
    """Startup/teardown hooks around the worker group."""

    share_cuda_visible_devices: bool = False

    def on_start(self, worker_group: WorkerGroup,
                 backend_config: BackendConfig) -> None:
        pass

    def on_shutdown(self, worker_group: WorkerGroup,
                    backend_config: BackendConfig) -> None:
        pass

    @staticmethod
    def encode_data(data_dict: Dict) -> Dict:
        return data_dict

    @staticmethod
    def decode_data(data_dict: Dict) -> Dict:
        return data_dict


@dataclass
class JaxConfig(BackendConfig):
    """TPU-native backend: per-worker mesh context.

    Replaces the reference's TorchConfig/process-group bootstrap
    (train/torch.py:57 setup_torch_process_group): JAX workers need no
    NCCL rendezvous — collective layout comes from the mesh — so on_start
    only records topology env for the train function to read.
    """

    @property
    def backend_cls(self):
        return _JaxBackend


class _JaxBackend(Backend):
    def on_start(self, worker_group: WorkerGroup,
                 backend_config: "JaxConfig") -> None:
        n = len(worker_group)

        def setup(rank: int, world: int):
            # per-actor topology registry, NOT os.environ: workers share
            # a process in in-process mode, so env writes would race and
            # every rank would read the last writer's value
            _worker_topology[_actor_key()] = (rank, world)
        futures = [
            worker_group.execute_single_async(i, setup, i, n)
            for i in range(n)]
        ray_tpu.get(futures)


@dataclass
class TorchConfig(BackendConfig):
    """Torch DDP backend (reference: train/torch.py:57
    setup_torch_process_group): each worker joins a gloo process group
    rendezvoused over TCP, after which the train function can use
    torch.distributed / DistributedDataParallel directly. Requires
    process-backed workers (``ray_tpu.init(worker_mode="process")``) —
    one OS process per rank is what torch.distributed assumes; thread
    workers share a process and are rejected with guidance."""

    backend: str = "gloo"
    init_method: Optional[str] = None  # default: tcp on a free port
    timeout_s: float = 120.0

    @property
    def backend_cls(self):
        return _TorchBackend


def _require_process_workers(worker_group: WorkerGroup,
                             backend_name: str) -> None:
    """torch.distributed and TF_CONFIG are per-PROCESS mechanisms: a
    rank per OS process is the contract. Thread workers share one
    process and are rejected with guidance."""
    n = len(worker_group)
    pids = ray_tpu.get([
        worker_group.execute_single_async(
            i, lambda _r: __import__("os").getpid(), i)
        for i in range(n)])
    if len(set(pids)) != n:
        raise TrainBackendError(
            f"backend={backend_name!r} needs one OS process per rank; "
            "start the runtime with ray_tpu.init("
            "worker_mode='process', num_process_workers>=num_workers)")


def _pick_free_ports(count: int) -> list:
    """Distinct free ports: every picker socket stays open until the
    whole list is chosen, so the kernel cannot re-issue an earlier
    pick to a later one."""
    import socket

    socks = []
    try:
        for _ in range(count):
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            socks.append(s)
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


class _TorchBackend(Backend):
    def on_start(self, worker_group: WorkerGroup,
                 backend_config: "TorchConfig") -> None:
        n = len(worker_group)
        _require_process_workers(worker_group, "torch")
        init_method = backend_config.init_method
        if init_method is None:
            init_method = f"tcp://127.0.0.1:{_pick_free_ports(1)[0]}"

        def setup(rank: int, world: int, method: str, dist_backend: str,
                  timeout_s: float):
            import datetime

            import torch.distributed as dist

            dist.init_process_group(
                dist_backend, init_method=method, rank=rank,
                world_size=world,
                timeout=datetime.timedelta(seconds=timeout_s))
            _worker_topology[_actor_key()] = (rank, world)

        ray_tpu.get([
            worker_group.execute_single_async(
                i, setup, i, n, init_method, backend_config.backend,
                backend_config.timeout_s)
            for i in range(n)])

    def on_shutdown(self, worker_group: WorkerGroup,
                    backend_config: "TorchConfig") -> None:
        def teardown():
            import torch.distributed as dist

            if dist.is_initialized():
                dist.destroy_process_group()

        try:
            ray_tpu.get([
                worker_group.execute_single_async(i, teardown)
                for i in range(len(worker_group))])
        except Exception:
            pass  # workers may already be dead at shutdown


@dataclass
class TensorflowConfig(BackendConfig):
    """TF MultiWorkerMirrored backend (reference: train/tensorflow.py):
    each worker gets a TF_CONFIG describing the whole cluster and its
    own index, the contract tf.distribute.MultiWorkerMirroredStrategy
    reads at construction. Requires process-backed workers (TF_CONFIG
    is per-process env)."""

    port_base: int = 0  # 0 = pick free ports

    @property
    def backend_cls(self):
        return _TensorflowBackend


class _TensorflowBackend(Backend):
    def on_start(self, worker_group: WorkerGroup,
                 backend_config: "TensorflowConfig") -> None:
        n = len(worker_group)
        _require_process_workers(worker_group, "tensorflow")
        if backend_config.port_base:
            ports = [backend_config.port_base + i for i in range(n)]
        else:
            ports = _pick_free_ports(n)
        workers = [f"127.0.0.1:{p}" for p in ports]

        def setup(rank: int, world: int, worker_list):
            import json as _json
            import os as _os

            _os.environ["TF_CONFIG"] = _json.dumps({
                "cluster": {"worker": list(worker_list)},
                "task": {"type": "worker", "index": rank},
            })
            _worker_topology[_actor_key()] = (rank, world)

        ray_tpu.get([
            worker_group.execute_single_async(i, setup, i, n, workers)
            for i in range(n)])


def get_worker_topology() -> Optional[tuple]:
    """(world_rank, world_size) of the calling worker actor, if set up."""
    try:
        return _worker_topology.get(_actor_key())
    except TrainBackendError:
        return None


class BackendExecutor:
    def __init__(self, backend_config: BackendConfig,
                 num_workers: int = 1,
                 num_cpus_per_worker: float = 1,
                 num_gpus_per_worker: float = 0,
                 additional_resources_per_worker: Optional[Dict] = None,
                 max_retries: int = 3,
                 min_workers: Optional[int] = None):
        self._backend_config = backend_config
        self._backend: Backend = backend_config.backend_cls()
        self._num_workers = num_workers
        # elastic when min_workers < num_workers: a shrunken cluster
        # restarts the group at any size in [min_workers, num_workers]
        # and grows back when capacity returns (the multihost
        # slice-restart story: lose a slice, keep training on the rest)
        self._target_workers = num_workers
        self._min_workers = (num_workers if min_workers is None
                             else max(1, min(min_workers, num_workers)))
        self._num_cpus_per_worker = num_cpus_per_worker
        self._num_gpus_per_worker = num_gpus_per_worker
        self._additional_resources_per_worker = \
            additional_resources_per_worker
        self._max_failures = (max_retries if max_retries >= 0
                              else float("inf"))
        self._num_failures = 0
        self._initialization_hook = None
        self._placement_group = None
        self.worker_group: Optional[WorkerGroup] = None
        self._latest_checkpoint: Optional[Dict] = None
        self._resize_floor = 0  # scale-up restarts must not shrink

    @property
    def elastic(self) -> bool:
        return self._min_workers < self._target_workers

    def _per_worker_demand(self) -> Dict[str, float]:
        demand: Dict[str, float] = {}
        if self._num_cpus_per_worker:
            demand["CPU"] = self._num_cpus_per_worker
        if self._num_gpus_per_worker:
            demand["GPU"] = self._num_gpus_per_worker
        for k, v in (self._additional_resources_per_worker or {}).items():
            demand[k] = demand.get(k, 0.0) + v
        return demand

    def _feasible_workers(self) -> int:
        """How many workers the cluster can host RIGHT NOW, capped at
        the target size. Computed PER NODE (whole bundles): aggregate
        availability overcounts fractional leftovers no PACK bundle can
        actually occupy."""
        demand = self._per_worker_demand()
        if not demand:
            return self._target_workers
        from ray_tpu.core import runtime as rt_mod

        rt = rt_mod.global_runtime
        if rt is None:
            return 0
        fit = 0
        for raylet in rt.cluster_state.alive_raylets():
            avail = raylet.local_resources.to_map(
                rt.cluster_state.ids, available=True)
            fit += min(int(avail.get(k, 0.0) / v)
                       for k, v in demand.items())
        return min(self._target_workers, fit)

    def _resolve_group_size(self, timeout: float = 15.0) -> int:
        """Elastic start sizing: wait for at least the floor of capacity
        (a dying node's actors free resources asynchronously; a scale-up
        restart must wait for its OWN former resources to return or it
        would 'grow' into a smaller group), then take everything
        available up to target."""
        if not self.elastic:
            return self._target_workers
        floor = max(self._min_workers, self._resize_floor)
        self._resize_floor = 0
        start = time.monotonic()
        deadline = start + timeout
        fit = self._feasible_workers()
        last_fit, stable = fit, 0
        while fit < floor and time.monotonic() < deadline:
            time.sleep(0.2)
            fit = self._feasible_workers()
            # settle early once capacity stops changing at a viable
            # size: a worker crash frees the whole old group back (keep
            # waiting, fit is climbing); a node loss plateaus below the
            # floor (restart now, do not burn the full timeout). The
            # grace period + 2s plateau guard against sampling BEFORE
            # the old group's resources started coming back.
            if fit == last_fit:
                stable += 1
                if (fit >= self._min_workers and stable >= 10
                        and time.monotonic() - start >= 3.0):
                    break
            else:
                last_fit, stable = fit, 0
        if fit < self._min_workers:
            raise TrainBackendError(
                f"cluster can host only {fit} workers; elastic minimum "
                f"is {self._min_workers}")
        return max(fit, self._min_workers)

    def should_scale_up(self) -> bool:
        """True when the group runs below target, capacity for at least
        one MORE worker exists beyond what the group already holds (its
        own resources come back on restart), and a checkpoint exists to
        resume from (resizing without one would lose progress)."""
        if not self.elastic or self.worker_group is None:
            return False
        if len(self.worker_group) >= self._target_workers:
            return False
        if self._latest_checkpoint is None:
            return False
        if self._feasible_workers() < 1:
            return False
        # the restart must come back STRICTLY larger or it's pure churn
        self._resize_floor = len(self.worker_group) + 1
        return True

    # ------------------------------------------------------------ lifecycle
    def start(self, initialization_hook: Optional[Callable[[], None]] = None,
              train_cls=None, train_cls_args=None, train_cls_kwargs=None
              ) -> None:
        self._num_workers = self._resolve_group_size()
        self._create_placement_group()
        self.worker_group = WorkerGroup(
            num_workers=self._num_workers,
            num_cpus_per_worker=self._num_cpus_per_worker,
            num_gpus_per_worker=self._num_gpus_per_worker,
            additional_resources_per_worker=(
                self._additional_resources_per_worker),
            placement_group=self._placement_group)
        if initialization_hook:
            self._initialization_hook = initialization_hook
            self.worker_group.execute(initialization_hook)
        self._backend.on_start(self.worker_group, self._backend_config)

    def _create_placement_group(self) -> None:
        """PACK the workers (reference backend.py:190)."""
        from ray_tpu.util.placement_group import placement_group

        bundle = {"CPU": self._num_cpus_per_worker}
        if self._num_gpus_per_worker:
            bundle["GPU"] = self._num_gpus_per_worker
        if self._additional_resources_per_worker:
            bundle.update(self._additional_resources_per_worker)
        bundles = [dict(bundle) for _ in range(self._num_workers)]
        pg = placement_group(bundles, strategy="PACK")
        ray_tpu.get(pg.ready(), timeout=30)
        self._placement_group = pg

    # ------------------------------------------------------------- training
    def start_training(self, train_func: Callable[[], T],
                       checkpoint: Optional[Dict] = None,
                       dataset_shards: Optional[List] = None) -> None:
        if self.worker_group is None:
            raise TrainBackendError("start() must be called before training")
        checkpoint = checkpoint or self._latest_checkpoint
        n = len(self.worker_group)
        futures = []
        for i in range(n):
            shard = dataset_shards[i] if dataset_shards else None
            futures.append(self.worker_group.execute_single_async(
                i, _start_session_on_worker, train_func, i, n, checkpoint,
                shard))
        ray_tpu.get(futures)

    def get_next_results(self) -> Optional[List[TrainingResult]]:
        """One lock-step round of results from every worker (or None when
        all train functions finished)."""
        futures = self.worker_group.execute_async(_session_get_next)
        try:
            # Incremental fetch: a worker whose train function died
            # raises from its get_next immediately, while healthy peers
            # may still be blocked in a collective waiting for the dead
            # rank (they only unblock at collective_op_timeout_s).
            # ray_tpu.get over ALL futures would stall the driver on
            # those peers before surfacing the real error; consuming
            # futures as they complete surfaces it in milliseconds.
            by_ref = {}
            pending = list(futures)
            while pending:
                done, pending = ray_tpu.wait(pending, num_returns=1)
                for ref in done:
                    by_ref[ref] = ray_tpu.get(ref)  # raises the error NOW
            results = [by_ref[ref] for ref in futures]
        except ray_tpu.exceptions.RayActorError as e:
            self._increment_failures(e)
            raise TrainingWorkerError from e
        if any(r is None for r in results):
            if not all(r is None for r in results):
                raise RuntimeError(
                    "Some workers returned results while others didn't. "
                    "Make sure train.report/save_checkpoint are called the "
                    "same number of times on all workers.")
            return None
        first_type = results[0].type
        if any(r.type is not first_type for r in results):
            raise RuntimeError(
                "Mismatched result types across workers in one round.")
        if first_type is TrainingResultType.CHECKPOINT:
            self._latest_checkpoint = results[0].data or next(
                (r.data for r in results if r.data), {})
        return results

    def finish_training(self) -> List[Any]:
        try:
            return self.worker_group.execute(_session_finish)
        except ray_tpu.exceptions.RayActorError as e:
            self._increment_failures(e)
            raise TrainingWorkerError from e

    # -------------------------------------------------------------- failure
    def handle_failure(self, error: BaseException) -> None:
        """Tear down and restart the group; training resumes from the
        latest checkpoint (reference Backend.handle_failure)."""
        logger.warning("worker failure detected; restarting group: %s", error)
        if self.elastic and not self._resize_floor and \
                self.worker_group is not None:
            # prefer coming back at the previous size: a transient
            # worker crash should not shrink-then-regrow the group
            self._resize_floor = len(self.worker_group)
        self.shutdown(keep_checkpoint=True)
        self.start(self._initialization_hook)

    def reset_checkpoint(self) -> None:
        """A new run must not silently resume the previous run's state."""
        self._latest_checkpoint = None

    def _increment_failures(self, error: BaseException) -> None:
        self._num_failures += 1
        if self._num_failures > self._max_failures:
            raise RuntimeError(
                f"Training failed {self._num_failures} times, exceeding "
                f"max_retries={self._max_failures}.") from error

    @property
    def latest_checkpoint(self) -> Optional[Dict]:
        return self._latest_checkpoint

    def shutdown(self, keep_checkpoint: bool = False) -> None:
        if self.worker_group is not None:
            try:
                self._backend.on_shutdown(self.worker_group,
                                          self._backend_config)
            except Exception:  # noqa: BLE001
                pass
            self.worker_group.shutdown()
            self.worker_group = None
        if self._placement_group is not None:
            from ray_tpu.util.placement_group import remove_placement_group

            remove_placement_group(self._placement_group)
            self._placement_group = None
        if not keep_checkpoint:
            self._latest_checkpoint = None


# ---- closures executed on worker actors (module-level so they pickle).
# The active session is registered per worker-actor id: actors may share a
# process (in-process mode), so the registry is keyed, not global.
_worker_sessions: Dict[str, Any] = {}
_worker_topology: Dict[str, tuple] = {}


def _actor_key() -> str:
    import os

    # PROCESS-backed actor first: the method body runs in the actor's
    # dedicated OS process, where the runtime context (and actor id)
    # live parent-side. Consulting get_runtime_context() here would
    # AUTO-INIT a whole nested runtime inside every worker process just
    # to learn the actor id is None. One actor per dedicated process
    # makes the pid a stable worker identity for the registries.
    if os.environ.get("RAY_TPU_WORKER_PROCESS") == "1":
        return f"proc-{os.getpid()}"
    aid = ray_tpu.get_runtime_context().get_actor_id()
    if aid is None:
        raise TrainBackendError(
            "session closures must run on a worker actor")
    return aid


def _start_session_on_worker(train_func, rank, world, checkpoint, shard):
    from ray_tpu.train import session as session_mod

    s = session_mod.init_session(
        training_func=train_func, world_rank=rank, local_rank=rank,
        world_size=world, checkpoint=checkpoint, dataset_shard=shard)
    _worker_sessions[_actor_key()] = s
    s.start()


def _session_get_next(worker_self=None):
    s = _worker_sessions.get(_actor_key())
    if s is None:
        raise TrainBackendError("no session active on worker")
    return s.get_next()


def _session_finish(worker_self=None):
    key = _actor_key()
    s = _worker_sessions.get(key)
    if s is None:
        raise TrainBackendError("no session active on worker")
    try:
        return s.finish()
    finally:
        _worker_sessions.pop(key, None)
