"""Worker-side training session.

Mirrors the reference's ray.train session (python/ray/train/session.py):
the train function runs on a thread inside each worker actor; ``report``
and ``save_checkpoint`` hand results back to the driver through a
producer/consumer queue, pausing the train thread until the driver has
consumed the result (lock-step heartbeat, as the reference does).
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass
from enum import Enum
from typing import Any, Callable, Dict, Optional


class TrainingResultType(Enum):
    REPORT = "REPORT"
    CHECKPOINT = "CHECKPOINT"


@dataclass
class TrainingResult:
    type: TrainingResultType
    data: Dict[str, Any]


class Session:
    def __init__(self, training_func: Callable[[], Any], world_rank: int,
                 local_rank: int, world_size: int,
                 checkpoint: Optional[Dict] = None,
                 dataset_shard: Any = None,
                 detailed_autofilled_metrics: bool = False):
        self.training_func = training_func
        self.world_rank = world_rank
        self.local_rank = local_rank
        self.world_size = world_size
        self.loaded_checkpoint = checkpoint
        self.dataset_shard = dataset_shard
        # lock-step: train thread blocks in report() until driver fetches
        self.result_queue: "queue.Queue[TrainingResult]" = queue.Queue(1)
        self.continue_lock = threading.Semaphore(0)
        self.training_thread: Optional[threading.Thread] = None
        self.finished = False
        self.error: Optional[BaseException] = None
        self.output = None
        self.iteration = 0
        self.time_start = time.time()

    def start(self) -> None:
        def run():
            # Sessions are looked up by training-thread ident: worker
            # actors share one process in in-process mode, so a single
            # module global would collide across concurrent workers.
            _sessions[threading.get_ident()] = self
            try:
                self.output = self.training_func()
            except BaseException as e:  # noqa: BLE001
                self.error = e
            finally:
                self.finished = True
                _sessions.pop(threading.get_ident(), None)
                # unblock a driver waiting in get_next
                self.result_queue.put(None)

        self.training_thread = threading.Thread(target=run, daemon=True)
        self.training_thread.start()

    def pause_reporting(self) -> None:
        self.continue_lock.release()

    def finish(self) -> Any:
        self.continue_lock.release()
        if self.training_thread is not None:
            self.training_thread.join()
        if self.error is not None:
            raise self.error
        return self.output

    def get_next(self) -> Optional[TrainingResult]:
        if self.finished and self.result_queue.empty():
            if self.error is not None:
                raise self.error
            return None
        result = self.result_queue.get()
        if result is None and self.error is not None:
            # The train thread died (its finally put the None marker):
            # surface the real error NOW. Deferring it to finish() wedges
            # the lock-step driver — healthy peers block in collectives
            # waiting for this rank, so their get_next never returns and
            # finish_training is never reached (the r05 dryrun hang).
            raise self.error
        if result is not None:
            # let the train thread continue past report()
            self.continue_lock.release()
        return result

    # ------------------------------------------------- called by train fn
    def _autofill(self, metrics: Dict) -> Dict:
        out = dict(metrics)
        out.setdefault("_timestamp", int(time.time()))
        out.setdefault("_time_this_iter_s", time.time() - self.time_start)
        out.setdefault("_training_iteration", self.iteration)
        return out

    def report(self, **kwargs) -> None:
        self.iteration += 1
        self.result_queue.put(TrainingResult(
            TrainingResultType.REPORT, self._autofill(kwargs)))
        self.continue_lock.acquire()

    def checkpoint(self, **kwargs) -> None:
        # only rank 0's checkpoint is persisted (reference session.py)
        data = kwargs if self.world_rank == 0 else {}
        self.result_queue.put(TrainingResult(
            TrainingResultType.CHECKPOINT, data))
        self.continue_lock.acquire()


_sessions: Dict[int, Session] = {}


def init_session(*args, **kwargs) -> Session:
    return Session(*args, **kwargs)


def get_session() -> Session:
    s = _sessions.get(threading.get_ident())
    if s is None:
        raise ValueError(
            "`ray_tpu.train` functions may only be called from inside a "
            "train function started by a Trainer")
    return s


def shutdown_session() -> None:
    _sessions.pop(threading.get_ident(), None)


# ------------------------------------------------------------- public API
def report(**kwargs) -> None:
    """Report intermediate metrics; blocks until the driver consumes them."""
    get_session().report(**kwargs)


def save_checkpoint(**kwargs) -> None:
    get_session().checkpoint(**kwargs)


def load_checkpoint() -> Optional[Dict]:
    return get_session().loaded_checkpoint


def world_rank() -> int:
    return get_session().world_rank


def local_rank() -> int:
    return get_session().local_rank


def world_size() -> int:
    return get_session().world_size


def get_dataset_shard(shard_name: Optional[str] = None) -> Any:
    shard = get_session().dataset_shard
    if isinstance(shard, dict):
        if shard_name is None:
            raise ValueError("Multiple datasets were passed; specify "
                             "which shard via get_dataset_shard(name)")
        return shard[shard_name]
    return shard
