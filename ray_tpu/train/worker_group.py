"""Actor fleet for distributed training.

Mirrors the reference's ray.train worker group
(python/ray/train/worker_group.py): BaseWorkerMixin actors that execute
arbitrary closures, created inside an optional placement group.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, TypeVar

import ray_tpu

T = TypeVar("T")


class BaseWorker:
    """Executes arbitrary functions; the session rides on top."""

    def __init__(self):
        self._env: Dict[str, str] = {}

    def _execute(self, fn: Callable[..., T], *args, **kwargs) -> T:
        return fn(*args, **kwargs)

    def node_id(self):
        return ray_tpu.get_runtime_context().get_node_id()


@dataclass
class WorkerMetadata:
    node_id: str


@dataclass
class Worker:
    actor: Any
    metadata: WorkerMetadata


class WorkerGroup:
    def __init__(self, num_workers: int = 1,
                 num_cpus_per_worker: float = 1,
                 num_gpus_per_worker: float = 0,
                 additional_resources_per_worker: Optional[Dict] = None,
                 placement_group: Any = None):
        if num_workers <= 0:
            raise ValueError("num_workers must be positive")
        self.num_workers = num_workers
        self.num_cpus_per_worker = num_cpus_per_worker
        self.num_gpus_per_worker = num_gpus_per_worker
        self.additional_resources_per_worker = additional_resources_per_worker
        self.placement_group = placement_group
        self.workers: List[Worker] = []
        self._remote_cls = None
        self.start()

    def _actor_options(self, bundle_index: int) -> dict:
        opts: dict = dict(num_cpus=self.num_cpus_per_worker,
                          num_gpus=self.num_gpus_per_worker)
        if self.additional_resources_per_worker:
            opts["resources"] = dict(self.additional_resources_per_worker)
        if self.placement_group is not None:
            from ray_tpu.util.scheduling_strategies import (
                PlacementGroupSchedulingStrategy,
            )

            opts["scheduling_strategy"] = PlacementGroupSchedulingStrategy(
                placement_group=self.placement_group,
                placement_group_bundle_index=bundle_index,
            )
        return opts

    def start(self) -> None:
        if self.workers:
            raise RuntimeError("WorkerGroup already started")
        self._remote_cls = ray_tpu.remote(BaseWorker)
        for i in range(self.num_workers):
            actor = self._remote_cls.options(
                **self._actor_options(i)).remote()
            self.workers.append(Worker(actor, None))
        ids = ray_tpu.get(
            [w.actor.node_id.remote() for w in self.workers])
        for w, nid in zip(self.workers, ids):
            w.metadata = WorkerMetadata(node_id=nid)

    def shutdown(self, patience_s: float = 5) -> None:
        for w in self.workers:
            ray_tpu.kill(w.actor)
        self.workers = []

    def __len__(self) -> int:
        return len(self.workers)

    def execute_async(self, fn: Callable[..., T], *args, **kwargs) -> List:
        if not self.workers:
            raise RuntimeError("WorkerGroup is shut down")
        return [w.actor._execute.remote(fn, *args, **kwargs)
                for w in self.workers]

    def execute(self, fn: Callable[..., T], *args, **kwargs) -> List[T]:
        return ray_tpu.get(self.execute_async(fn, *args, **kwargs))

    def execute_single_async(self, worker_index: int,
                             fn: Callable[..., T], *args, **kwargs):
        if worker_index >= len(self.workers):
            raise ValueError(f"worker_index {worker_index} out of range")
        return self.workers[worker_index].actor._execute.remote(
            fn, *args, **kwargs)

    def execute_single(self, worker_index: int, fn: Callable[..., T],
                       *args, **kwargs) -> T:
        return ray_tpu.get(
            self.execute_single_async(worker_index, fn, *args, **kwargs))

    def remove_workers(self, worker_indexes: List[int]) -> None:
        self.workers = [w for i, w in enumerate(self.workers)
                        if i not in set(worker_indexes)]

    def add_workers(self, num_workers: int) -> None:
        new = []
        base = len(self.workers)
        for i in range(num_workers):
            actor = self._remote_cls.options(
                **self._actor_options(base + i)).remote()
            new.append(Worker(actor, None))
        ids = ray_tpu.get([w.actor.node_id.remote() for w in new])
        for w, nid in zip(new, ids):
            w.metadata = WorkerMetadata(node_id=nid)
        self.workers.extend(new)
