"""ray_tpu.train — distributed training orchestration (reference:
python/ray/train/)."""

from ray_tpu.train.backend import (  # noqa: F401
    Backend,
    BackendConfig,
    BackendExecutor,
    JaxConfig,
)
from ray_tpu.train.callbacks import (  # noqa: F401
    JsonLoggerCallback,
    PrintCallback,
    TrainingCallback,
)
from ray_tpu.train.checkpoint import (  # noqa: F401
    CheckpointManager,
    CheckpointStrategy,
)
from ray_tpu.train.session import (  # noqa: F401
    get_dataset_shard,
    load_checkpoint,
    local_rank,
    report,
    save_checkpoint,
    world_rank,
    world_size,
)
from ray_tpu.train.trainer import Trainer, TrainingIterator  # noqa: F401
from ray_tpu.train.worker_group import WorkerGroup  # noqa: F401
