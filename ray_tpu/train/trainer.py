"""Trainer — the user-facing entry of ray_tpu.train.

Mirrors the reference's ray.train Trainer (python/ray/train/trainer.py:94;
run:264, run_iterator:343): wraps a BackendExecutor, drives the result
loop through callbacks, persists checkpoints, and exposes an iterator
form for Tune integration. Backend "jax" is the TPU-native default.
"""

from __future__ import annotations

import logging
import tempfile
import time
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Union

from ray_tpu.train.backend import (  # noqa: F401 — registry entries
    TensorflowConfig,
    TorchConfig,
    Backend,
    BackendConfig,
    BackendExecutor,
    JaxConfig,
    TrainingWorkerError,
)
from ray_tpu.train.callbacks import TrainingCallback
from ray_tpu.train.checkpoint import (
    CheckpointManager,
    CheckpointStrategy,
)
from ray_tpu.train.session import TrainingResultType

logger = logging.getLogger(__name__)

BACKEND_NAME_TO_CONFIG_CLS = {
    "jax": JaxConfig,
    "tpu": JaxConfig,
    # reference-parity backends (train/torch.py, train/tensorflow.py):
    # real process-group / TF_CONFIG bootstrap over process workers
    "torch": TorchConfig,
    "tensorflow": TensorflowConfig,
}


def _construct_backend_config(
        backend: Union[str, BackendConfig]) -> BackendConfig:
    if isinstance(backend, BackendConfig):
        return backend
    if isinstance(backend, str):
        cls = BACKEND_NAME_TO_CONFIG_CLS.get(backend)
        if cls is None:
            raise ValueError(
                f"Invalid backend {backend!r}; registered: "
                f"{sorted(BACKEND_NAME_TO_CONFIG_CLS)}")
        return cls()
    raise TypeError("backend must be a string or BackendConfig")


class Trainer:
    def __init__(self,
                 backend: Union[str, BackendConfig] = "jax",
                 num_workers: int = 1,
                 use_gpu: bool = False,
                 resources_per_worker: Optional[Dict[str, float]] = None,
                 logdir: Optional[str] = None,
                 max_retries: int = 3,
                 elastic_min_workers: Optional[int] = None):
        """elastic_min_workers < num_workers turns on elastic training:
        after a node loss the run continues on any group size down to
        the minimum, and grows back toward num_workers when capacity
        returns (always resuming from the latest checkpoint)."""
        import ray_tpu

        if not ray_tpu.is_initialized():
            ray_tpu.init()
        if num_workers <= 0:
            raise ValueError("num_workers must be positive")
        resources = dict(resources_per_worker or {})
        num_cpus = resources.pop("CPU", 1)
        num_gpus = resources.pop("GPU", int(use_gpu))
        self._backend_config = _construct_backend_config(backend)
        self._executor = BackendExecutor(
            backend_config=self._backend_config,
            num_workers=num_workers,
            num_cpus_per_worker=num_cpus,
            num_gpus_per_worker=num_gpus,
            additional_resources_per_worker=resources or None,
            max_retries=max_retries,
            min_workers=elastic_min_workers)
        self._logdir = Path(logdir) if logdir else Path(
            tempfile.mkdtemp(prefix="ray_tpu_train_"))
        self._logdir.mkdir(parents=True, exist_ok=True)
        self.checkpoint_manager = CheckpointManager(run_dir=self._logdir)
        self._started = False

    # ------------------------------------------------------------ lifecycle
    @property
    def logdir(self) -> Path:
        return self._logdir

    @property
    def latest_checkpoint(self) -> Optional[Dict]:
        return self.checkpoint_manager.latest_checkpoint

    @property
    def latest_checkpoint_path(self) -> Optional[Path]:
        return self.checkpoint_manager.latest_checkpoint_path

    @property
    def best_checkpoint_path(self) -> Optional[Path]:
        return self.checkpoint_manager.best_checkpoint_path

    def start(self, initialization_hook: Optional[Callable] = None) -> None:
        try:
            self._executor.start(initialization_hook)
        except BaseException:
            # a failed backend on_start (e.g. torch backend rejecting
            # thread workers) must not leak the already-created worker
            # group + placement group — their CPUs would stay reserved
            # for the rest of the session
            try:
                self._executor.shutdown()
            except Exception:
                pass
            raise
        self._started = True

    # -------------------------------------------------------------- running
    def run(self,
            train_func: Union[Callable[[], Any], Callable[[Dict], Any]],
            config: Optional[Dict] = None,
            callbacks: Optional[List[TrainingCallback]] = None,
            dataset: Any = None,
            checkpoint: Optional[Union[Dict, str, Path]] = None,
            checkpoint_strategy: Optional[CheckpointStrategy] = None
            ) -> List[Any]:
        if not self._started:
            self.start()
        callbacks = callbacks or []
        train_func = self._wrap_function(train_func, config)
        checkpoint = self._load_checkpoint_arg(checkpoint)
        self._executor.reset_checkpoint()
        self.checkpoint_manager.on_start_training(
            checkpoint_strategy=checkpoint_strategy)
        for cb in callbacks:
            cb.start_training(logdir=str(self._logdir), config=config)
        error = False
        try:
            iterator = TrainingIterator(
                self._executor, train_func, checkpoint,
                self.checkpoint_manager, shard_fn=self._shard_fn(dataset))
            for round_results in iterator:
                for cb in callbacks:
                    cb.handle_result(round_results)
            return iterator.latest_run_results
        except BaseException:
            error = True
            raise
        finally:
            for cb in callbacks:
                cb.finish_training(error=error)

    def run_iterator(self, train_func, config=None, dataset=None,
                     checkpoint=None, checkpoint_strategy=None
                     ) -> "TrainingIterator":
        if not self._started:
            self.start()
        train_func = self._wrap_function(train_func, config)
        checkpoint = self._load_checkpoint_arg(checkpoint)
        self._executor.reset_checkpoint()
        self.checkpoint_manager.on_start_training(
            checkpoint_strategy=checkpoint_strategy)
        return TrainingIterator(
            self._executor, train_func, checkpoint,
            self.checkpoint_manager, shard_fn=self._shard_fn(dataset))

    def _shard_fn(self, dataset) -> Optional[Callable[[int], List]]:
        """world size -> shards, re-invoked on every (elastic) group
        (re)start so shards always match the live worker count."""
        if dataset is None:
            return None
        return lambda n: self._shards_for(dataset, n)

    def _shards_for(self, dataset, n: int) -> Optional[List]:
        if dataset is None:
            return None
        if isinstance(dataset, dict):
            shard_dict = {
                name: self._split_dataset(ds, n)
                for name, ds in dataset.items()}
            return [{name: shards[i] for name, shards in shard_dict.items()}
                    for i in range(n)]
        return self._split_dataset(dataset, n)

    @staticmethod
    def _split_dataset(dataset, n: int) -> List:
        if hasattr(dataset, "split"):
            return dataset.split(n)
        raise TypeError(f"cannot shard dataset of type {type(dataset)}")

    @staticmethod
    def _wrap_function(train_func: Callable, config: Optional[Dict]
                       ) -> Callable[[], Any]:
        import inspect

        sig = inspect.signature(train_func)
        if len(sig.parameters) > 1:
            raise ValueError(
                "train_func must take 0 or 1 argument (the config dict)")
        if len(sig.parameters) == 1:
            cfg = config or {}
            return lambda: train_func(cfg)
        return train_func

    @staticmethod
    def _load_checkpoint_arg(checkpoint) -> Optional[Dict]:
        if checkpoint is None or isinstance(checkpoint, dict):
            return checkpoint
        return CheckpointManager.load_checkpoint_from_path(checkpoint)

    def shutdown(self) -> None:
        if self._started:
            self._executor.shutdown()
            self._started = False

    # ---------------------------------------------------- tune integration
    def to_tune_trainable(self, train_func: Callable,
                          dataset: Any = None) -> type:
        """Wrap into a function trainable for ray_tpu.tune
        (reference trainer.py build_tune_trainable). Each trial builds
        its OWN Trainer — concurrent trials sharing one executor would
        overwrite each other's worker sessions."""
        backend_config = self._backend_config
        num_workers = self._executor._num_workers
        cpus = self._executor._num_cpus_per_worker
        gpus = self._executor._num_gpus_per_worker
        extra = self._executor._additional_resources_per_worker

        def trainable(config):
            from ray_tpu import tune

            resources = dict(extra or {})
            resources["CPU"] = cpus
            if gpus:
                resources["GPU"] = gpus
            trial_trainer = Trainer(
                backend=backend_config, num_workers=num_workers,
                resources_per_worker=resources)
            try:
                iterator = trial_trainer.run_iterator(
                    train_func, config, dataset=dataset)
                for round_results in iterator:
                    if round_results:
                        tune.report(**round_results[0])
            finally:
                trial_trainer.shutdown()
        trainable.__name__ = getattr(train_func, "__name__", "train_func")
        return trainable


class TrainingIterator:
    """Yields one list of per-worker results per lock-step round; restarts
    the worker group on failure (reference trainer.py TrainingIterator).
    Elastic executors also resize here, at round boundaries: shrink is a
    failure-restart with whatever capacity remains; growth triggers when
    capacity returns and a checkpoint exists to resume from."""

    def __init__(self, backend_executor: BackendExecutor, train_func,
                 checkpoint, checkpoint_manager: CheckpointManager,
                 shard_fn=None):
        self._executor = backend_executor
        self._train_func = train_func
        self._checkpoint_manager = checkpoint_manager
        self._shard_fn = shard_fn  # n -> shards, re-split per (re)start
        # a failure before this run's FIRST checkpoint restarts from the
        # run's own starting checkpoint, never a previous run's
        self._initial_checkpoint = checkpoint
        self._run_complete = False
        self.latest_run_results: Optional[List[Any]] = None
        self._start(checkpoint)

    def _start(self, checkpoint) -> None:
        shards = None
        if self._shard_fn is not None:
            shards = self._shard_fn(len(self._executor.worker_group))
        self._executor.start_training(
            self._train_func, checkpoint=checkpoint,
            dataset_shards=shards)

    def _restart_from_checkpoint(self) -> None:
        self._executor.handle_failure(None)
        self._start(self._checkpoint_manager.latest_checkpoint
                    or self._initial_checkpoint)

    def __iter__(self):
        return self

    def __next__(self) -> List[Dict]:
        while True:
            if self._executor.should_scale_up():
                logger.info("elastic scale-up: resizing the worker group")
                try:
                    self._restart_from_checkpoint()
                except Exception:
                    # the capacity that justified the resize vanished
                    # mid-restart; the group is down — come back at
                    # whatever size is feasible, not at all costs larger
                    logger.warning(
                        "scale-up failed; restarting at feasible size")
                    self._executor._resize_floor = 0
                    self._restart_from_checkpoint()
            try:
                results = self._fetch_round()
            except TrainingWorkerError:
                # restart from latest checkpoint after a worker death
                self._restart_from_checkpoint()
                continue
            if results is None:
                self.latest_run_results = self._finish()
                raise StopIteration
            return results

    def _fetch_round(self) -> Optional[List[Dict]]:
        while True:
            results = self._executor.get_next_results()
            if results is None:
                return None
            if results[0].type is TrainingResultType.CHECKPOINT:
                data = next((r.data for r in results if r.data), {})
                self._checkpoint_manager.process_checkpoint(data)
                continue  # checkpoints are consumed, not yielded
            return [r.data for r in results]

    def _finish(self) -> List[Any]:
        while True:
            try:
                return self._executor.finish_training()
            except TrainingWorkerError:
                self._executor.handle_failure(None)
                self._start(self._checkpoint_manager.latest_checkpoint
                            or self._initial_checkpoint)
                # drain the rerun
                while self._fetch_round() is not None:
                    pass
