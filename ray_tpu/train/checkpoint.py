"""Checkpoint bookkeeping for ray_tpu.train.

Mirrors the reference's ray.train CheckpointManager
(python/ray/train/checkpoint.py): tracks the latest + best checkpoints,
persists rank-0 checkpoints to disk, bounds how many are kept
(keep N by score or recency).
"""

from __future__ import annotations

import heapq
import logging
import pickle
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

TUNE_CHECKPOINT_ID = "_current_checkpoint_id"


@dataclass
class CheckpointStrategy:
    """Mirrors ray.train.CheckpointStrategy."""

    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: str = "_training_iteration"
    checkpoint_score_order: str = "max"

    def __post_init__(self):
        if self.num_to_keep is not None and self.num_to_keep < 0:
            raise ValueError("num_to_keep must be non-negative")
        if self.checkpoint_score_order not in ("max", "min"):
            raise ValueError("checkpoint_score_order must be 'max' or 'min'")


@dataclass(order=True)
class _Tracked:
    priority: float
    checkpoint_id: int
    path: Optional[Path] = None


class CheckpointManager:
    def __init__(self, run_dir: Optional[Path] = None,
                 checkpoint_strategy: Optional[CheckpointStrategy] = None):
        self.run_dir = Path(run_dir) if run_dir else None
        self._strategy = checkpoint_strategy or CheckpointStrategy()
        self._checkpoint_id = 0
        self.latest_checkpoint: Optional[Dict] = None
        self.latest_checkpoint_path: Optional[Path] = None
        self.best_checkpoint_path: Optional[Path] = None
        self._top: List[_Tracked] = []  # min-heap of kept checkpoints

    @property
    def latest_checkpoint_id(self) -> int:
        return self._checkpoint_id

    def on_start_training(self, checkpoint_strategy=None, run_dir=None,
                          latest_checkpoint_id=None):
        if checkpoint_strategy is not None:
            self._strategy = checkpoint_strategy
        if run_dir is not None:
            self.run_dir = Path(run_dir)
        if latest_checkpoint_id is not None:
            self._checkpoint_id = latest_checkpoint_id
        # a fresh run must not see the previous run's checkpoint through
        # the failure-restart path OR the path accessors (the persisted
        # files themselves remain on disk under run_dir)
        self.latest_checkpoint = None
        self.latest_checkpoint_path = None
        self.best_checkpoint_path = None
        self._top = []

    def _score(self, checkpoint: Dict) -> float:
        attr = self._strategy.checkpoint_score_attribute
        value = checkpoint.get(attr, self._checkpoint_id)
        try:
            score = float(value)
        except (TypeError, ValueError):
            score = float(self._checkpoint_id)
        return score if self._strategy.checkpoint_score_order == "max" \
            else -score

    def process_checkpoint(self, checkpoint: Dict) -> None:
        self._checkpoint_id += 1
        self.latest_checkpoint = dict(checkpoint)
        self.latest_checkpoint[TUNE_CHECKPOINT_ID] = self._checkpoint_id
        if self.run_dir is None:
            return
        ckpt_dir = self.run_dir / "checkpoints"
        ckpt_dir.mkdir(parents=True, exist_ok=True)
        path = ckpt_dir / f"checkpoint_{self._checkpoint_id:06d}"
        with open(path, "wb") as f:
            pickle.dump(self.latest_checkpoint, f)
        self.latest_checkpoint_path = path
        tracked = _Tracked(self._score(checkpoint), self._checkpoint_id, path)
        keep = self._strategy.num_to_keep
        if keep is None:
            heapq.heappush(self._top, tracked)
        elif keep == 0:
            path.unlink(missing_ok=True)
            return
        elif len(self._top) < keep:
            heapq.heappush(self._top, tracked)
        else:
            worst = heapq.heappushpop(self._top, tracked)
            if worst.path is not None and worst.path != path:
                worst.path.unlink(missing_ok=True)
        if self._top:
            self.best_checkpoint_path = max(self._top).path

    @staticmethod
    def load_checkpoint_from_path(path) -> Dict:
        with open(path, "rb") as f:
            return pickle.load(f)
