"""Wire-protocol state machines for RC13 (raycheck v3).

The reference stack compiles its multi-step conversations out of
protobuf IDL + gRPC service definitions, so protocol drift is a build
error. This repo's wire layer is pickled dict messages over
length-prefixed frames — nothing structural stops a handler from
driving an edge the conversation never declared, or a state from
losing its timeout path in a refactor. RC13 closes that gap by making
each conversation an explicit, importable state machine; phase-1 facts
already know every registered handler and schema, so phase 2 can check
the declarations against the live tree.

Each :class:`Protocol` declares:

* ``states`` / ``initial`` / ``terminal`` — the conversation's shape.
* ``transitions`` — :class:`T` edges, each naming its ``driver``: for
  ``kind="wire"`` the schema op whose handler drives the edge, for
  ``kind="internal"`` the function (sweeper, deadline loop, breaker
  method) that drives it locally. ``escape=True`` marks the
  timeout/abort/expiry edge that guarantees the source state cannot
  wedge — RC13 requires at least one leaving every non-initial,
  non-terminal state (and flags terminal states with outgoing edges,
  unreachable states, and drivers that resolve to nothing).
* ``covers`` — wire ops that BELONG to this conversation: every
  covered op must drive at least one edge, so adding a message to the
  family without placing it in the machine is a finding.

The declarations are plain literals: RC13 re-extracts them from this
file's AST (not by importing it), so a machine built dynamically is
itself a finding ("not statically analyzable").
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Tuple

__all__ = ["T", "Protocol", "PROTOCOLS"]


@dataclass(frozen=True)
class T:
    """One legal transition. ``driver`` is a wire op (kind="wire") or a
    function name defined somewhere in the scanned tree
    (kind="internal"). ``escape`` marks the timeout/abort/expiry edge
    for the source state."""
    src: str
    dst: str
    driver: str
    kind: str = "wire"
    escape: bool = False


@dataclass(frozen=True)
class Protocol:
    name: str
    states: Tuple[str, ...]
    initial: str
    terminal: Tuple[str, ...]
    transitions: Tuple[T, ...]
    covers: Tuple[str, ...] = field(default=())


# --------------------------------------------------------------------------
# Object push: offer → begin → chunk* → end, abort/sweep everywhere.
# Receiver-side state lives in RayletServer._push_in; the RECEIVING
# escape is the stale-inbound sweeper (PR 13/14), which reaps trees
# whose sender died mid-stream.
# --------------------------------------------------------------------------

PUSH = Protocol(
    name="push",
    states=("IDLE", "OFFERED", "RECEIVING", "SEALED", "ABORTED"),
    initial="IDLE",
    terminal=("SEALED", "ABORTED"),
    transitions=(
        T("IDLE", "OFFERED", "push_offer"),
        # small objects arrive whole in one frame: offer, stream, and
        # seal collapse into a single message
        T("IDLE", "SEALED", "push_object"),
        # mid-size objects skip the offer and open the stream directly
        T("IDLE", "RECEIVING", "push_begin"),
        T("OFFERED", "RECEIVING", "push_begin"),
        T("RECEIVING", "RECEIVING", "push_chunk"),
        T("RECEIVING", "RECEIVING", "push_chunk_data"),
        T("RECEIVING", "SEALED", "push_end"),
        T("OFFERED", "ABORTED", "push_abort", escape=True),
        T("RECEIVING", "ABORTED", "push_abort", escape=True),
        # sender died mid-stream: the sweeper reaps the inbound tree
        T("OFFERED", "ABORTED", "_sweep_stale_inbound",
          kind="internal", escape=True),
        T("RECEIVING", "ABORTED", "_sweep_stale_inbound",
          kind="internal", escape=True),
    ),
    covers=("push_offer", "push_object", "push_begin", "push_chunk",
            "push_chunk_data", "push_end", "push_abort"),
)


# --------------------------------------------------------------------------
# Node drain: ALIVE → DRAINING → DEAD (PR 16). Wire entry points are
# drain_node (operator) and preempt_notice (spot eviction); the GCS
# drives migration internally and the deadline fallback guarantees
# DRAINING always terminates.
# --------------------------------------------------------------------------

DRAIN = Protocol(
    name="drain",
    states=("ALIVE", "DRAINING", "DEAD"),
    initial="ALIVE",
    terminal=("DEAD",),
    transitions=(
        T("ALIVE", "DRAINING", "drain_node"),
        T("ALIVE", "DRAINING", "preempt_notice"),
        T("ALIVE", "DRAINING", "_drain_for_preemption", kind="internal"),
        T("DRAINING", "DEAD", "_drain_node_graceful", kind="internal"),
        # deadline fallback: a drain that cannot migrate in time is
        # forced dead rather than wedged
        T("DRAINING", "DEAD", "_mark_node_dead", kind="internal",
          escape=True),
        # an unresponsive node skips DRAINING entirely
        T("ALIVE", "DEAD", "_mark_node_dead", kind="internal",
          escape=True),
    ),
    covers=("drain_node", "preempt_notice"),
)


# --------------------------------------------------------------------------
# Placement-group two-phase commit (PR 1/15): prepare leases resources,
# commit pins them, return releases. PENDING's escape is pg_remove
# (caller gave up before placement); PREPARED's is the lease expiry
# sweep; COMMITTED returns bundles on group removal or node death.
# --------------------------------------------------------------------------

PG_2PC = Protocol(
    name="pg_2pc",
    states=("PENDING", "PREPARED", "COMMITTED", "RETURNED"),
    initial="PENDING",
    terminal=("RETURNED",),
    transitions=(
        T("PENDING", "PREPARED", "prepare_bundle"),
        T("PREPARED", "COMMITTED", "commit_bundle"),
        T("COMMITTED", "RETURNED", "return_bundle", escape=True),
        T("PREPARED", "RETURNED", "return_bundle", escape=True),
        T("PENDING", "RETURNED", "pg_remove", escape=True),
    ),
    covers=("prepare_bundle", "commit_bundle", "return_bundle",
            "pg_remove"),
)


# --------------------------------------------------------------------------
# Circuit breaker (overload plane, PRs 11/14): purely node-local, so
# every driver is internal. No terminal state — the machine cycles for
# the process lifetime; OPEN's escape is the allow() probe timer,
# HALF_OPEN's is record_failure snapping back to OPEN.
# --------------------------------------------------------------------------

BREAKER = Protocol(
    name="breaker",
    states=("closed", "open", "half_open"),
    initial="closed",
    terminal=(),
    transitions=(
        T("closed", "open", "record_failure", kind="internal"),
        T("open", "half_open", "allow", kind="internal", escape=True),
        T("half_open", "closed", "record_success", kind="internal"),
        T("half_open", "open", "record_failure", kind="internal",
          escape=True),
    ),
)


PROTOCOLS = (PUSH, DRAIN, PG_2PC, BREAKER)
