"""Per-function control-flow graphs + the RC12 resource-lifecycle
dataflow (raycheck phase-1.5: flow-sensitive, where RC01–RC11 are
pattern- or join-shaped).

The runtime acquires real kernel and owner-managed resources on its hot
paths — sockets and pipe fds in the RPC substrate, mmap'd shm segments
in the byte store, worker-pool leases, ThreadRegistry handles,
dedupe-window reservations, device buffers behind the scheduler's
``DeviceMatrixMirror``. A resource acquired into a local and dropped on
an early ``return`` — or, the classic shape, leaked when the statement
*between* acquire and release raises — is invisible to per-line
pattern rules: the defect is a *path*, not a statement. So RC12 builds
a statement-level CFG per function (normal edges AND exception edges:
any statement inside a ``try`` may transfer to its handlers/finally,
any statement outside one may exit the function exceptionally) and runs
a forward may-hold dataflow over it (reference posture: this is the
static half of what LSAN/ASAN's leak checking sees at runtime in the
C++ raylet's CI).

Ownership model (deliberately lenient — the goal is real leaks, not a
borrow checker):

* **gen** — a call whose terminal callee name is in the resource table
  (or in a module-local function summary, see below) assigned to plain
  name(s): ``s = socket.create_connection(...)``,
  ``r, w = os.pipe()``.
* **kill** — any of: a release-method call on the resource
  (``s.close()``); passing the resource as an argument to ANY call
  (ownership transfer: ``self._pool._release(w)``,
  ``os.close(fd)``, ``closing(s)``); storing it into an attribute /
  subscript / container (return-to-owner: ``self._sock = s``);
  ``return``/``yield``-ing it (transfer to caller); ``del``;
  rebinding the name; using it as a ``with`` context manager. Kinds
  with ``release_any`` additionally kill on a *bare call by name*
  anywhere on the path (the shm pin / dedupe-window shape, where the
  release call names the object id, not the handle variable).
* acquisitions inside a ``with`` item never gen (the context manager
  owns the release on every edge).

A resource still live at the function's normal or exceptional exit on
SOME path is a finding at its acquire line. Interprocedural summaries
close the module-local wrapper gap: a function that acquires and
*returns* a resource makes its callers (``self.method()`` / bare-name
calls in the same file) acquirers of the same kind, to a fixpoint.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

__all__ = [
    "RESOURCE_KINDS",
    "ResourceKind",
    "FunctionLeaks",
    "Leak",
    "Node",
    "build_cfg",
    "analyze_functions",
]


# --------------------------------------------------------------------------
# the resource table
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class ResourceKind:
    """One acquire/release pairing the dataflow tracks.

    ``acquire`` — terminal callee names whose call result IS the
    resource. ``release_methods`` — method names on the resource that
    end its lifetime. ``release_any`` — function/method names whose
    mere call (any receiver, any args) releases every live resource of
    this kind in the function: the shm-pin / dedupe-window shape where
    release is keyed by object id or token, not by the handle
    variable."""
    name: str
    acquire: Tuple[str, ...]
    release_methods: Tuple[str, ...] = ()
    release_any: Tuple[str, ...] = ()


RESOURCE_KINDS: Tuple[ResourceKind, ...] = (
    # kernel fds: the RPC substrate's sockets, train's rendezvous
    # socket, the worker pipe pair
    ResourceKind("socket", ("create_connection", "socket"),
                 release_methods=("close", "detach", "shutdown")),
    ResourceKind("pipe/file fd", ("pipe", "open", "fdopen", "dup"),
                 release_methods=("close", "detach")),
    ResourceKind("mmap", ("mmap",), release_methods=("close",)),
    # byte-store shm segments: ShmStore() maps a segment + fd + mmap;
    # close() unmaps all three (and unlinks when owner)
    ResourceKind("shm segment", ("ShmStore",),
                 release_methods=("close",)),
    # shm pins: get_buffer/pin_region pin the block until
    # store.release(object_id) — release is keyed by object id, so a
    # bare `.release(...)` call on the path counts
    ResourceKind("shm pin", ("get_buffer", "pin_region"),
                 release_any=("release",)),
    # worker-pool leases: a popped WorkerProcess must flow back through
    # _release/_return (transfer-kill) or be stored on the owner
    ResourceKind("worker-pool lease", ("_lease", "_warm_lease"),
                 release_methods=("kill", "terminate")),
    # ThreadRegistry: the registry handle owns named daemon threads;
    # join_all is the observable teardown
    ResourceKind("thread registry", ("ThreadRegistry",),
                 release_methods=("join_all",)),
    # dedupe-window reservations: rows resolved against the per-row
    # token window must be stored back (or answered from cache) —
    # resolving and dropping the pending rows silently disables the
    # exactly-once replay path
    ResourceKind("dedupe-window reservation",
                 ("_row_tokens_resolve",),
                 release_any=("_row_tokens_store", "_row_token_store")),
    # device buffers held by the scheduler's mirror: close/invalidate
    # returns them to the allocator
    ResourceKind("device-mirror buffer", ("DeviceMatrixMirror",),
                 release_methods=("close", "invalidate", "reset")),
)

_ACQUIRE_TO_KIND: Dict[str, ResourceKind] = {
    name: kind for kind in RESOURCE_KINDS for name in kind.acquire}

# release_any names, joined across kinds, checked per-kind at kill time
_RELEASE_ANY: Dict[str, Tuple[str, ...]] = {
    kind.name: kind.release_any for kind in RESOURCE_KINDS}


def _terminal_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Call):
        return _terminal_name(node.func)
    return None


# --------------------------------------------------------------------------
# the CFG
# --------------------------------------------------------------------------


class Node:
    """One statement-level CFG node. ``succ`` are normal-flow
    successors; ``exc`` are exception successors (the innermost
    enclosing handler/finally entries, or the function's exceptional
    exit). Sentinel nodes (entry/exit/exc_exit/join) carry no stmt.
    ``refine`` — branch-refinement pseudo-nodes carry (var, kill):
    entering this edge proves ``var`` is None (kill=True) or not-None
    (kill=False), from an ``if var is [not] None`` test."""

    __slots__ = ("stmt", "succ", "exc", "label", "refine")

    def __init__(self, stmt: Optional[ast.stmt] = None,
                 label: str = "stmt"):
        self.stmt = stmt
        self.succ: List["Node"] = []
        self.exc: List["Node"] = []
        self.label = label
        self.refine: Optional[Tuple[str, bool]] = None

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        line = getattr(self.stmt, "lineno", "-")
        return f"<Node {self.label}:{line}>"


@dataclass
class Cfg:
    entry: Node
    exit: Node          # normal return / fall-off-the-end
    exc_exit: Node      # uncaught exception propagates to the caller
    nodes: List[Node] = field(default_factory=list)


class _Builder:
    """Builds a statement-level CFG for one function body.

    ``finally`` is modeled without block duplication: exceptional flow
    is routed through the same finally nodes and then to BOTH the
    normal continuation and the propagation target. The extra
    normal-continuation path is a may-analysis over-approximation — it
    only matters if it reaches an exit with a live resource, and a
    correct finally released it."""

    def __init__(self) -> None:
        self.exit = Node(label="exit")
        self.exc_exit = Node(label="exc_exit")
        self.nodes: List[Node] = [self.exit, self.exc_exit]

    def _node(self, stmt: Optional[ast.stmt], label: str = "stmt") -> Node:
        n = Node(stmt, label)
        self.nodes.append(n)
        return n

    def build(self, body: List[ast.stmt]) -> Cfg:
        entry = self._node(None, "entry")
        exits = self._body(body, [entry], [self.exc_exit], None, None)
        for n in exits:
            n.succ.append(self.exit)
        return Cfg(entry, self.exit, self.exc_exit, self.nodes)

    # ``preds`` — nodes whose normal flow enters the construct;
    # returns the nodes whose normal flow leaves it.
    def _body(self, stmts: List[ast.stmt], preds: List[Node],
              exc: List[Node], brk: Optional[Node],
              cont: Optional[Node]) -> List[Node]:
        cur = preds
        for stmt in stmts:
            cur = self._stmt(stmt, cur, exc, brk, cont)
            if not cur:   # unreachable code after return/raise/...
                break
        return cur

    def _link(self, preds: List[Node], node: Node) -> None:
        for p in preds:
            node not in p.succ and p.succ.append(node)

    def _stmt(self, stmt: ast.stmt, preds: List[Node], exc: List[Node],
              brk: Optional[Node], cont: Optional[Node]) -> List[Node]:
        if isinstance(stmt, ast.If):
            test = self._node(stmt, "if")
            self._link(preds, test)
            if _expr_can_raise(stmt.test):
                test.exc = list(exc)
            # None-refinement: `if var is None:` proves the acquire
            # returned nothing on the true branch (the get_buffer /
            # attach-miss guard shape), and vice versa for `is not`
            t_pred, f_pred = [test], [test]
            ref = _none_test(stmt.test)
            if ref is not None:
                var, is_none = ref
                t_node = self._node(None, "assume")
                t_node.refine = (var, is_none)
                f_node = self._node(None, "assume")
                f_node.refine = (var, not is_none)
                self._link([test], t_node)
                self._link([test], f_node)
                t_pred, f_pred = [t_node], [f_node]
            t = self._body(stmt.body, t_pred, exc, brk, cont)
            f = (self._body(stmt.orelse, f_pred, exc, brk, cont)
                 if stmt.orelse else f_pred)
            return t + f
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            head = self._node(stmt, "loop")
            self._link(preds, head)
            if isinstance(stmt, ast.While) \
                    and not _expr_can_raise(stmt.test):
                pass   # `while True:` / `while flag:` heads don't raise
            else:
                head.exc = list(exc)
            after: List[Node] = [head]   # loop may run zero times
            body_exits = self._body(stmt.body, [head], exc,
                                    brk=head, cont=head)
            for n in body_exits:
                n.succ.append(head)      # back edge
            if stmt.orelse:
                after = self._body(stmt.orelse, after, exc, brk, cont)
            return after
        if isinstance(stmt, (ast.Try, getattr(ast, "TryStar", ast.Try))):
            return self._try(stmt, preds, exc, brk, cont)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            head = self._node(stmt, "with")
            self._link(preds, head)
            head.exc = list(exc)
            # the with body's exceptions unwind through __exit__ then
            # propagate to the enclosing target
            return self._body(stmt.body, [head], exc, brk, cont)
        if isinstance(stmt, (ast.Return, ast.Raise)):
            node = self._node(stmt, "return" if isinstance(
                stmt, ast.Return) else "raise")
            self._link(preds, node)
            node.exc = list(exc)
            if isinstance(stmt, ast.Return):
                node.succ.append(self.exit)
            else:
                for t in exc:
                    node.succ.append(t)
            return []
        if isinstance(stmt, ast.Break):
            node = self._node(stmt, "break")
            self._link(preds, node)
            # break target's *after* set is resolved by the loop head
            # approximation: flow back to the loop head, whose normal
            # successors include everything after the loop
            if brk is not None:
                node.succ.append(brk)
            return []
        if isinstance(stmt, ast.Continue):
            node = self._node(stmt, "continue")
            self._link(preds, node)
            if cont is not None:
                node.succ.append(cont)
            return []
        # plain statement (expr, assign, del, assert, import, ...)
        node = self._node(stmt)
        self._link(preds, node)
        if _can_raise(stmt):
            node.exc = list(exc)
        return [node]

    def _try(self, stmt: ast.Try, preds: List[Node], exc: List[Node],
             brk: Optional[Node], cont: Optional[Node]) -> List[Node]:
        # entries the try body's exceptions transfer to: every handler,
        # plus the finally (when present), plus — for re-raise after
        # unmatched handlers — the outer target
        handler_entries: List[Node] = []
        handler_nodes: List[Tuple[Node, ast.ExceptHandler]] = []
        for h in stmt.handlers:
            hn = self._node(h, "except")
            handler_entries.append(hn)
            handler_nodes.append((hn, h))

        fin_entry: Optional[Node] = None
        if stmt.finalbody:
            fin_entry = self._node(None, "finally")

        # an exception from the body enters a handler, or — when no
        # handler matches (or none exist) — unwinds through the finally
        # when present, else propagates to the outer target. It never
        # bypasses an existing finally.
        body_exc = handler_entries + (
            [fin_entry] if fin_entry else list(exc))
        body_exits = self._body(stmt.body, preds, body_exc, brk, cont)
        if stmt.orelse:
            body_exits = self._body(stmt.orelse, body_exits, body_exc,
                                    brk, cont)

        all_exits: List[Node] = list(body_exits)
        for hn, h in handler_nodes:
            h_exc = ([fin_entry] if fin_entry else []) + list(exc)
            hn.exc = h_exc
            all_exits += self._body(h.body, [hn], h_exc, brk, cont)

        if fin_entry is None:
            return all_exits
        self._link(all_exits, fin_entry)
        fin_exits = self._body(stmt.finalbody, [fin_entry], exc, brk,
                               cont)
        # finally completes: normal continuation AND (for the
        # exceptional entry) propagation outward
        for n in fin_exits:
            for t in exc:
                t not in n.succ and n.succ.append(t)
        return fin_exits


def _none_test(test: ast.AST) -> Optional[Tuple[str, bool]]:
    """``var is None`` → (var, True); ``var is not None`` →
    (var, False); anything else → None."""
    if isinstance(test, ast.Compare) \
            and isinstance(test.left, ast.Name) \
            and len(test.ops) == 1 \
            and isinstance(test.ops[0], (ast.Is, ast.IsNot)) \
            and isinstance(test.comparators[0], ast.Constant) \
            and test.comparators[0].value is None:
        return test.left.id, isinstance(test.ops[0], ast.Is)
    return None


# expression kinds that cannot realistically raise: names, attribute
# loads on bound objects, constants, tuples, additive arithmetic,
# comparisons. Calls, subscripts, division, and await/yield can.
_SAFE_EXPRS = (ast.Name, ast.Attribute, ast.Constant, ast.Tuple,
               ast.List, ast.UnaryOp, ast.BoolOp, ast.Compare,
               ast.Load, ast.Store, ast.Del, ast.And, ast.Or,
               ast.Not, ast.USub, ast.UAdd, ast.Eq, ast.NotEq,
               ast.Lt, ast.LtE, ast.Gt, ast.GtE, ast.Is, ast.IsNot,
               ast.In, ast.NotIn, ast.Add, ast.Sub, ast.Mult,
               ast.expr_context, ast.boolop, ast.operator,
               ast.unaryop, ast.cmpop)


def _expr_can_raise(expr: ast.AST) -> bool:
    """True unless every subexpression is a safe load/arith node —
    names, attribute loads, constants, comparisons, additive
    arithmetic. Calls, subscripts, division, and f-strings can
    raise."""
    for node in ast.walk(expr):
        if isinstance(node, ast.BinOp):
            if not isinstance(node.op, (ast.Add, ast.Sub, ast.Mult)):
                return True
            continue
        if not isinstance(node, _SAFE_EXPRS):
            return True
    return False


def _can_raise(stmt: ast.stmt) -> bool:
    """False only for trivially non-raising statements (``x = y``,
    ``self.total += n``, ``flag = a and not b``): every subexpression
    is a safe load/arith node. Anything containing a call, subscript,
    division, or f-string keeps its exception edge."""
    if not isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign,
                             ast.Expr, ast.Pass)):
        return True
    for node in ast.walk(stmt):
        if node is stmt:
            continue
        if isinstance(node, ast.BinOp):
            if not isinstance(node.op, (ast.Add, ast.Sub, ast.Mult)):
                return True
            continue
        if not isinstance(node, _SAFE_EXPRS):
            return True
    return False


def build_cfg(fndef: ast.AST) -> Cfg:
    """Statement-level CFG (with exception edges) for one function."""
    return _Builder().build(list(fndef.body))


# --------------------------------------------------------------------------
# the may-hold dataflow
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class Leak:
    var: str
    kind: str
    line: int           # acquire line
    exceptional: bool   # leak path reaches the exceptional exit only


@dataclass
class FunctionLeaks:
    path: str
    name: str
    leaks: List[Leak]


_FN_BOUNDARY = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                ast.ClassDef)


def _own_walk(node: ast.AST) -> Iterable[ast.AST]:
    """ast.walk pruned at nested function/class boundaries (their
    bodies run later, under their own CFG)."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for c in ast.iter_child_nodes(n):
            if not isinstance(c, _FN_BOUNDARY):
                stack.append(c)


class _Dataflow:
    """Forward may-hold analysis over one CFG. State: frozenset of
    (var, rid) aliases; ``rid`` identifies one acquire site. A rid
    live at an exit node on any path is a leak at its acquire line."""

    def __init__(self, path: str, fndef: ast.AST,
                 acquire_to_kind: Dict[str, ResourceKind]):
        self.path = path
        self.fndef = fndef
        self.acquires = acquire_to_kind
        self.rid_info: Dict[int, Tuple[str, int]] = {}  # rid->(kind,line)
        self._next_rid = 0

    # -- expression helpers ------------------------------------------------
    def _acquire_kind(self, value: ast.AST) -> Optional[ResourceKind]:
        if not isinstance(value, ast.Call):
            return None
        name = _terminal_name(value.func)
        return self.acquires.get(name) if name else None

    def _vars_passed_to_calls(self, stmt: ast.stmt) -> Set[str]:
        out: Set[str] = set()
        for node in _own_walk(stmt):
            if isinstance(node, ast.Call):
                for a in list(node.args) + [k.value for k in
                                            node.keywords]:
                    for sub in ast.walk(a):
                        if isinstance(sub, ast.Name):
                            out.add(sub.id)
        return out

    def _release_method_receivers(self, stmt: ast.stmt) -> Set[Tuple[str, str]]:
        """(var, method) pairs for ``var.method(...)`` calls."""
        out: Set[Tuple[str, str]] = set()
        for node in _own_walk(stmt):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and isinstance(node.func.value, ast.Name):
                out.add((node.func.value.id, node.func.attr))
        return out

    def _called_names(self, stmt: ast.stmt) -> Set[str]:
        out: Set[str] = set()
        for node in _own_walk(stmt):
            if isinstance(node, ast.Call):
                name = _terminal_name(node.func)
                if name:
                    out.add(name)
        return out

    # -- transfer ----------------------------------------------------------
    def transfer(self, node: Node, state: frozenset,
                 gen: bool = True) -> frozenset:
        """Post-state of ``node``. With ``gen=False``, apply kills only
        — the exception-edge semantics: a statement that raises may
        still have completed its release/ownership transfer (a close()
        that raises still closed; a callee that raises still received
        the resource), but an acquire whose statement raised never
        bound the name."""
        stmt = node.stmt
        if stmt is None:
            if node.refine is not None and node.refine[1]:
                # the `is None` branch: the acquire returned nothing
                var = node.refine[0]
                return frozenset(p for p in state if p[0] != var)
            return state
        aliases = set(state)

        def kill_rid(rid: int) -> None:
            for pair in [p for p in aliases if p[1] == rid]:
                aliases.discard(pair)

        def kill_var(var: str) -> None:
            for pair in [p for p in aliases if p[0] == var]:
                aliases.discard(pair)

        def rids_of(var: str) -> List[int]:
            return [rid for v, rid in aliases if v == var]

        # 1. releases: var.release_method() / release_any-by-kind /
        #    passing the var to any call (ownership transfer)
        for var, meth in self._release_method_receivers(stmt):
            for rid in rids_of(var):
                kind, _ = self.rid_info[rid]
                spec = next(k for k in RESOURCE_KINDS if k.name == kind)
                if meth in spec.release_methods:
                    kill_rid(rid)
        called = self._called_names(stmt)
        for v, rid in list(aliases):
            kind, _ = self.rid_info[rid]
            if any(name in called for name in _RELEASE_ANY.get(kind, ())):
                kill_rid(rid)
        for var in self._vars_passed_to_calls(stmt):
            for rid in rids_of(var):
                kill_rid(rid)

        # 2. transfer to caller / owner: return, yield, attribute or
        #    subscript store, container literal in an assignment value,
        #    with-context use, del
        if isinstance(stmt, ast.Return) and stmt.value is not None:
            for n in ast.walk(stmt.value):
                if isinstance(n, ast.Name):
                    for rid in rids_of(n.id):
                        kill_rid(rid)
        for n in _own_walk(stmt):
            if isinstance(n, (ast.Yield, ast.YieldFrom)) \
                    and n.value is not None:
                for sub in ast.walk(n.value):
                    if isinstance(sub, ast.Name):
                        for rid in rids_of(sub.id):
                            kill_rid(rid)
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            stores_to_owner = any(
                isinstance(t, (ast.Attribute, ast.Subscript))
                or any(isinstance(s, (ast.Attribute, ast.Subscript))
                       for s in ast.walk(t))
                for t in targets)
            value = getattr(stmt, "value", None)
            if value is not None:
                for sub in ast.walk(value):
                    if isinstance(sub, ast.Name) and (
                            stores_to_owner
                            or isinstance(value, (ast.List, ast.Tuple,
                                                  ast.Dict, ast.Set))):
                        for rid in rids_of(sub.id):
                            kill_rid(rid)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                for sub in ast.walk(item.context_expr):
                    if isinstance(sub, ast.Name):
                        for rid in rids_of(sub.id):
                            kill_rid(rid)
        if isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    for rid in rids_of(t.id):
                        kill_rid(rid)

        # 3. gen: acquire call assigned to plain name(s). Aliasing
        #    (`y = x`) maps the new name onto the same rid.
        if gen and isinstance(stmt, ast.Assign) \
                and len(stmt.targets) == 1:
            target = stmt.targets[0]
            value = stmt.value
            if isinstance(target, ast.Name):
                if isinstance(value, ast.Name):
                    src_rids = rids_of(value.id)
                    kill_var(target.id)
                    for rid in src_rids:
                        aliases.add((target.id, rid))
                else:
                    kind = self._acquire_kind(value)
                    kill_var(target.id)
                    if kind is not None:
                        rid = self._rid(kind.name, stmt.lineno)
                        aliases.add((target.id, rid))
            elif isinstance(target, (ast.Tuple, ast.List)):
                kind = self._acquire_kind(value)
                for elt in target.elts:
                    if isinstance(elt, ast.Name):
                        kill_var(elt.id)
                        if kind is not None:
                            rid = self._rid(kind.name, stmt.lineno)
                            aliases.add((elt.id, rid))
        return frozenset(aliases)

    def _rid(self, kind: str, line: int) -> int:
        # one rid per (kind, line): re-executions of the same acquire
        # statement (loops) merge into one tracked resource
        for rid, info in self.rid_info.items():
            if info == (kind, line):
                return rid
        rid = self._next_rid
        self._next_rid += 1
        self.rid_info[rid] = (kind, line)
        return rid

    # -- fixpoint ----------------------------------------------------------
    def run(self) -> List[Leak]:
        cfg = build_cfg(self.fndef)
        in_state: Dict[int, Set[frozenset]] = {id(n): set()
                                               for n in cfg.nodes}
        in_state[id(cfg.entry)] = {frozenset()}
        work = [cfg.entry]
        # per-node union of reachable states, propagated to fixpoint;
        # states are small (few live resources), functions are small —
        # convergence is fast in practice
        guard = 0
        while work and guard < 20000:
            guard += 1
            node = work.pop()
            for st in list(in_state[id(node)]):
                out = self.transfer(node, st)
                exc_out = self.transfer(node, st, gen=False)
                for succ in node.succ:
                    if out not in in_state[id(succ)]:
                        in_state[id(succ)].add(out)
                        work.append(succ)
                for succ in node.exc:
                    if exc_out not in in_state[id(succ)]:
                        in_state[id(succ)].add(exc_out)
                        work.append(succ)
        leaks: Dict[int, bool] = {}   # rid -> leaked-on-normal-exit?
        for exit_node, exceptional in ((cfg.exit, False),
                                       (cfg.exc_exit, True)):
            for st in in_state[id(exit_node)]:
                for var, rid in st:
                    if not exceptional:
                        leaks[rid] = True
                    else:
                        leaks.setdefault(rid, False)
        out: List[Leak] = []
        seen_lines: Set[Tuple[int, str]] = set()
        for rid, on_normal in sorted(leaks.items()):
            kind, line = self.rid_info[rid]
            var = self._var_for(rid, in_state)
            key = (line, kind)
            if key in seen_lines:
                continue
            seen_lines.add(key)
            out.append(Leak(var, kind, line, exceptional=not on_normal))
        return out

    def _var_for(self, rid: int,
                 in_state: Dict[int, Set[frozenset]]) -> str:
        for states in in_state.values():
            for st in states:
                for var, r in st:
                    if r == rid:
                        return var
        return "?"


# --------------------------------------------------------------------------
# interprocedural summaries + the per-file entry point
# --------------------------------------------------------------------------


def _returns_acquired(fndef: ast.AST,
                      acquires: Dict[str, ResourceKind]) -> Optional[ResourceKind]:
    """Does ``fndef`` acquire a resource and return it (possibly via a
    local)? Then calling it IS an acquire of that kind.

    Statements are walked in source order, and a var stored into an
    attribute/subscript target BEFORE the return is dropped from the
    acquired set: a function that parks the handle in a module cache or
    on ``self`` and then returns it is lending a reference the owner
    still tracks, not transferring fresh ownership (the ``attach_shm``
    shape)."""
    acquired_vars: Dict[str, ResourceKind] = {}
    for node in _ordered_stmts(fndef.body):
        if isinstance(node, ast.Assign):
            # store-to-owner: `self._x = seg` / `_cache[k] = seg`
            if any(isinstance(t, (ast.Attribute, ast.Subscript))
                   for t in node.targets):
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Name):
                        acquired_vars.pop(sub.id, None)
            elif len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call):
                name = _terminal_name(node.value.func)
                kind = acquires.get(name) if name else None
                if kind is not None:
                    acquired_vars[node.targets[0].id] = kind
                else:
                    acquired_vars.pop(node.targets[0].id, None)
        if isinstance(node, ast.Return) and node.value is not None:
            if isinstance(node.value, ast.Call):
                name = _terminal_name(node.value.func)
                kind = acquires.get(name) if name else None
                if kind is not None:
                    return kind
            if isinstance(node.value, ast.Name) \
                    and node.value.id in acquired_vars:
                return acquired_vars[node.value.id]
    return None


def _ordered_stmts(body: List[ast.stmt]) -> Iterable[ast.stmt]:
    """Statements in source order, recursing into compound-statement
    bodies but not nested function/class definitions (the ordering
    _own_walk's LIFO stack does not give)."""
    for stmt in body:
        yield stmt
        if isinstance(stmt, _FN_BOUNDARY):
            continue
        for attr in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, attr, None)
            if sub:
                yield from _ordered_stmts(sub)
        for handler in getattr(stmt, "handlers", ()) or ():
            yield from _ordered_stmts(handler.body)


def analyze_functions(path: str,
                      functions: Dict[str, Tuple[Optional[str], ast.AST]],
                      ) -> List[FunctionLeaks]:
    """RC12 over one file's functions (``functions`` as extracted by
    facts._FileFacts: fid -> (class, fndef)). Module-local summaries:
    wrappers that acquire-and-return become acquirers for their
    callers, to a fixpoint."""
    acquires = dict(_ACQUIRE_TO_KIND)
    # fixpoint over module-local acquire summaries (a wrapper of a
    # wrapper still counts)
    for _ in range(4):
        grew = False
        for fid, (_cls, fndef) in functions.items():
            kind = _returns_acquired(fndef, acquires)
            fname = fid.rsplit(".", 1)[-1].split("::")[-1]
            if kind is not None and fname not in acquires:
                acquires[fname] = kind
                grew = True
        if not grew:
            break
    out: List[FunctionLeaks] = []
    for fid, (_cls, fndef) in sorted(functions.items()):
        # a function that acquires-and-returns hands ownership to its
        # caller by design; its own exit-with-live-resource is the
        # return statement, already killed by the transfer rule
        flow = _Dataflow(path, fndef, acquires)
        leaks = flow.run()
        if leaks:
            out.append(FunctionLeaks(path, fid, leaks))
    return out
