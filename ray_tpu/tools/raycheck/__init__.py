"""raycheck — repo-specific static analysis for the runtime's
concurrency & determinism invariants.

The fault-injection and recovery work (PR 1) made several properties
load-bearing: every probabilistic fault-plane decision comes from a
seeded per-stream RNG (single-seed replay), deadlines survive wall-clock
steps because they are monotonic, GCS mutations dedupe retries through
request tokens, and nothing blocks while holding a state lock. Nothing
checked those mechanically — the next refactor could silently break
replayability or reintroduce the fixed-sleep/lock-held-blocking patterns
that were just removed. raycheck is the mechanical check (reference: Ray
gates merges on exactly this kind of tooling — the ASAN/TSAN suites and
custom lint under ``ci/``).

Rules (see :mod:`ray_tpu.tools.raycheck.rules`):

=====  ==================================================================
RC01   lock-held-blocking — no ``time.sleep``, socket send/recv, RPC
       ``call()``/``call_stream()``, or ``open()`` inside a
       ``with <lock>:`` body (cluster/, core/). Locks that serialize the
       I/O itself (``send_lock``-style names) are exempt.
RC02   wall-clock-deadline — no ``time.time()`` in runtime code;
       deadline/backoff/lease arithmetic must use ``time.monotonic()``.
       Genuinely wall-clock sites (filesystem mtimes, user-facing
       timestamps) carry a justified suppression.
RC03   unseeded-randomness — no module-level ``random.*`` /
       ``np.random.*`` draws in cluster/ or scheduler/; an explicit
       ``random.Random`` stream must be threaded in (see
       ``fault_plane.derive_rng``), preserving single-seed replay.
RC04   mutation-token — every GCS mutation RPC handler registered in
       ``gcs_server.py`` must be wrapped by the ``@token_deduped``
       request-token dedupe decorator.
RC05   swallowed-exception — no log-less ``except ...: pass`` in
       cluster/ or core/; swallows get a ``logger.debug`` with enough
       context to attribute them during fault-injection runs.
RC06   wire-method-resolution (whole-program) — every
       ``client.call("name", ...)`` site resolves to a handler
       registered with the RPC server (and with the right unary/stream
       kind); registered handlers and @message schemas nothing calls
       are dead wire surface and flagged too.
RC07   wire-schema-conformance (whole-program) — every registered
       handler has a ``@message`` schema, schema fields match the
       handler's signature, and every literal call site satisfies the
       schema (required fields present, no silently-dropped unknown
       fields, literal types the validator accepts).
RC08   lock-order-cycle (whole-program) — cycle detection on the
       inter-procedural lock-acquisition graph over cluster/ + core/;
       opposite-order lock pairs are potential deadlocks, reported
       with both stacks.
RC09   unmanaged-thread — ``threading.Thread(...)`` in cluster/ or
       core/ outside cluster/threads.py must go through a
       ``ThreadRegistry`` (teardown joins threads by name instead of
       leaking them).
RC10   unbounded-queue — no ``deque()`` / ``queue.Queue()`` /
       ``SimpleQueue()`` without an explicit bound (``maxlen=`` /
       ``maxsize=``) in cluster/ or core/; queues bounded by an
       admission check (shed with RetryLaterError on submit) carry a
       suppression naming the check. Unbounded queues are the raw
       material of metastable overload collapse.
RC11   batch-handler-dedupe — every public ``*_batch`` wire handler in
       the server modules must resolve rows through the per-row
       idempotence-token path before applying them (retried/replayed
       frames re-answer cached rows instead of re-applying them).
RC12   resource-lifecycle (whole-program, flow-sensitive) — per-function
       CFGs with exception edges + a may-hold dataflow over acquired
       resources (shm segments/pins, worker-pool leases, ThreadRegistry
       handles, dedupe-window reservations, pipe/socket fds, device
       buffers); a path where the resource escapes without release or
       return-to-owner is a leak (see :mod:`.cfg`).
RC13   protocol-state-machine (whole-program) — multi-step wire
       conversations (push offer/begin/chunk/end/abort, drain
       ALIVE→DRAINING→DEAD, PG 2PC, breaker closed/open/half-open) are
       declared as explicit state machines in :mod:`.protocols`; phase 2
       checks every declared driver resolves to a live handler or
       function, every non-terminal state has a timeout/abort escape
       edge, no terminal state has outgoing edges, and no state is
       unreachable.
RC14   knob-hygiene (whole-program) — every ``Config`` knob must be
       read somewhere outside its defining config.py, documented in the
       README knob tables, and exercised by at least one test at a
       non-default value.
RC15   counter-hygiene (whole-program) — every ``.inc()`` site must
       target a metric registered in observability/metrics.py; every
       registered metric must be used outside the registry; every
       dict-valued heartbeat stats field must be rendered by
       ``cli.py status``.
RC16   guarded-by-data-race (whole-program) — RacerD-style inference:
       thread roots (ThreadRegistry spawns, raw Thread targets, RPC
       handlers) + per-root reachability + lockset-annotated field
       accesses; a field written from ≥2 roots whose candidate guard
       (majority lock over write sites) some conflicting access does
       not hold is a race. Escapes: init-before-spawn writes,
       immutable-after-publish, Queue/Event/Condition handoffs,
       single-rooted fields (see :mod:`.races`).
RC17   unbounded-blocking (whole-program) — ``Condition.wait()`` /
       ``Event.wait()`` / ``Queue.get()`` / zero-arg ``.join()`` /
       raw socket ``recv`` outside the rpc framing layer, reachable
       from a thread root, without a timeout: a hung peer must cost a
       bounded wait plus a retry decision, never a wedged daemon.
=====  ==================================================================

RC06–RC09 and RC12–RC17 are *whole-program*: phase 1 (:mod:`.facts`)
extracts call sites, handler registrations, schemas, lock edges, thread
spawns and roots, lockset-annotated field accesses, wait sites,
knob/metric/protocol declarations, and per-file use sets from
every file's AST (parsed once, shared by all rules); phase 2 joins them
across the tree — so they only make sense on a whole-tree scan, which
is what the CLI and the tier-1 gate run.

Run ``python -m ray_tpu.tools.raycheck`` (exit 0 = clean; ``--json``
prints a machine-readable finding list; ``--sarif`` writes a SARIF
2.1.0 report for CI archival). Suppress a single finding
inline with ``# raycheck: disable=RC0N`` on the flagged line or the
line above — always with a reason. ``baseline.txt`` can grandfather
known findings by key (regenerate with ``--update-baseline``); it
ships empty and should stay empty.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Set

__all__ = [
    "Finding",
    "SourceFile",
    "check_file",
    "check_tree",
    "default_baseline_path",
    "load_baseline",
    "load_tree",
    "save_baseline",
]


@dataclass(frozen=True)
class Finding:
    code: str      # rule code, e.g. "RC01"
    path: str      # posix path relative to the scan root
    line: int      # 1-indexed
    message: str   # defect + fix-it

    @property
    def key(self) -> str:
        """Stable identity for baseline matching (line numbers drift, so
        the baseline keys on path+code+line — a grandfathered finding
        that moves must be re-reviewed, which is the point)."""
        return f"{self.path}:{self.line}:{self.code}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"

    def to_dict(self) -> Dict[str, object]:
        """Machine-readable form for the CLI's ``--json`` report."""
        return {"code": self.code, "path": self.path,
                "line": self.line, "message": self.message,
                "key": self.key}


# ``# raycheck: disable=RC01`` or ``disable=RC01,RC05`` — trailing prose
# (the required justification) is ignored by the parser, not by review.
_SUPPRESS_RE = re.compile(
    r"#\s*raycheck:\s*disable=([A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)")


class SourceFile:
    """One parsed file: AST + per-line suppression map."""

    def __init__(self, relpath: str, text: str):
        self.relpath = relpath.replace(os.sep, "/")
        self.text = text
        self.tree = ast.parse(text)
        self._suppressed: Dict[int, Set[str]] = {}
        for lineno, line in enumerate(text.splitlines(), start=1):
            m = _SUPPRESS_RE.search(line)
            if m:
                self._suppressed[lineno] = {
                    c.strip().upper() for c in m.group(1).split(",")}

    def is_suppressed(self, line: int, code: str) -> bool:
        """A suppression comment applies to its own physical line and
        the line directly below it (so long statements can carry the
        comment above)."""
        for ln in (line, line - 1):
            codes = self._suppressed.get(ln)
            if codes and (code in codes or "ALL" in codes):
                return True
        return False


def _resolve_rules(rules=None):
    from ray_tpu.tools.raycheck import rules as _rules

    table = _rules.all_rules()
    if rules is None:
        return table
    wanted = set()
    for r in rules:
        wanted.add(r if isinstance(r, str) else r.code)
    return [r for r in table if r.code in wanted]


def _load_source(path: str, relpath: str):
    """(SourceFile, None) or (None, RC00 Finding) for one file."""
    relpath = relpath.replace(os.sep, "/")
    with open(path, "r", encoding="utf-8") as f:
        text = f.read()
    try:
        return SourceFile(relpath, text), None
    except SyntaxError as e:
        return None, Finding("RC00", relpath, e.lineno or 1,
                             f"file does not parse: {e.msg}")


def check_file(path: str, relpath: Optional[str] = None,
               rules=None) -> List[Finding]:
    """Run the (selected) per-file rules over one file. Unsuppressed
    findings only; a file that does not parse yields a single RC00
    finding. Program rules (RC06+) need the whole tree — use
    :func:`check_tree`."""
    sf, err = _load_source(path, relpath or path)
    if err is not None:
        return [err]
    findings: List[Finding] = []
    for rule in _resolve_rules(rules):
        if rule.program or not rule.applies(sf.relpath):
            continue
        for finding in rule.check(sf):
            if not sf.is_suppressed(finding.line, finding.code):
                findings.append(finding)
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    return findings


_SKIP_DIRS = {"__pycache__", ".git", "build", "node_modules"}


def iter_py_files(root: str) -> Iterable[str]:
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if d not in _SKIP_DIRS
                             and not d.startswith("."))
        for name in sorted(filenames):
            if name.endswith(".py"):
                yield os.path.join(dirpath, name)


def load_tree(root: str) -> List[SourceFile]:
    """Parse every ``.py`` under ``root`` into :class:`SourceFile`\\ s
    (unparseable files are skipped — :func:`check_tree` reports them as
    RC00). Useful for building a :class:`~.facts.Program` directly,
    e.g. to pin the extracted wire map in a regression test."""
    root = os.path.abspath(root)
    sources: List[SourceFile] = []
    for path in iter_py_files(root):
        sf, _ = _load_source(path, os.path.relpath(path, root))
        if sf is not None:
            sources.append(sf)
    return sources


def check_tree(root: str, rules=None, timings=None) -> List[Finding]:
    """Scan every ``.py`` under ``root``; finding paths are relative to
    ``root`` (rule scoping matches on those relative path parts).

    Two phases over ONE shared parse (the AST cache): per-file rules
    run against each :class:`SourceFile`; then the program rules
    (RC06–RC09, RC12–RC17) run against the :class:`~.facts.Program`
    joined from every file's extracted facts. Inline suppressions
    apply to both.

    Pass a dict as ``timings`` to receive the wall-time breakdown in
    place: ``{"facts_s": <fact-extraction seconds>, "<code>": <rule
    seconds>, ...}`` — what ``--json`` reports and ``check.sh`` prints
    when the scan overruns its budget."""
    import time as _time

    root = os.path.abspath(root)
    resolved = _resolve_rules(rules)
    sources: List[SourceFile] = []
    findings: List[Finding] = []
    if os.path.isfile(root):
        paths = [(root, os.path.basename(root))]
    else:
        paths = [(p, os.path.relpath(p, root))
                 for p in iter_py_files(root)]
    for path, relpath in paths:
        sf, err = _load_source(path, relpath)
        if err is not None:
            findings.append(err)
        else:
            sources.append(sf)
    per_file = [r for r in resolved if not r.program]
    program_rules = [r for r in resolved if r.program]
    rule_s = {r.code: 0.0 for r in resolved}
    for sf in sources:
        for rule in per_file:
            if not rule.applies(sf.relpath):
                continue
            t0 = _time.monotonic()
            for finding in rule.check(sf):
                if not sf.is_suppressed(finding.line, finding.code):
                    findings.append(finding)
            rule_s[rule.code] += _time.monotonic() - t0
    if program_rules:
        from ray_tpu.tools.raycheck import facts as _facts

        t0 = _time.monotonic()
        program = _facts.Program(sources, root=root)
        facts_s = _time.monotonic() - t0
        by_path = {sf.relpath: sf for sf in sources}
        for rule in program_rules:
            t0 = _time.monotonic()
            for finding in rule.check_program(program):
                sf = by_path.get(finding.path)
                if sf is None or not sf.is_suppressed(finding.line,
                                                      finding.code):
                    findings.append(finding)
            rule_s[rule.code] += _time.monotonic() - t0
    else:
        facts_s = 0.0
    if timings is not None:
        timings["facts_s"] = round(facts_s, 4)
        for code, secs in rule_s.items():
            timings[code] = round(secs, 4)
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    return findings


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(__file__), "baseline.txt")


def load_baseline(path: Optional[str] = None) -> Set[str]:
    """Finding keys (``path:line:code``) grandfathered by the baseline
    file; blank lines and ``#`` comments are ignored. The shipped
    baseline is empty — the tree is raycheck-clean — and new entries
    should be treated as debt, not as a suppression mechanism."""
    path = path or default_baseline_path()
    keys: Set[str] = set()
    if not os.path.exists(path):
        return keys
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line and not line.startswith("#"):
                keys.add(line)
    return keys


def save_baseline(keys: Iterable[str],
                  path: Optional[str] = None) -> str:
    """Write a baseline file from finding keys (the CLI's
    ``--update-baseline``). The header restates the contract: entries
    are debt to pay down, and the shipped baseline is pinned empty by
    test — this exists so CI can regenerate the file mechanically
    instead of hand-editing keys."""
    path = path or default_baseline_path()
    with open(path, "w", encoding="utf-8") as f:
        f.write("# raycheck baseline — grandfathered finding keys, "
                "one per line as\n# `path:line:code`. Ships EMPTY: "
                "the tree is raycheck-clean, and new\n# entries are "
                "debt to pay down, not an alternative to fixing or "
                "to an\n# inline justified suppression.\n")
        for key in sorted(set(keys)):
            f.write(key + "\n")
    return path
