"""raycheck — repo-specific static analysis for the runtime's
concurrency & determinism invariants.

The fault-injection and recovery work (PR 1) made several properties
load-bearing: every probabilistic fault-plane decision comes from a
seeded per-stream RNG (single-seed replay), deadlines survive wall-clock
steps because they are monotonic, GCS mutations dedupe retries through
request tokens, and nothing blocks while holding a state lock. Nothing
checked those mechanically — the next refactor could silently break
replayability or reintroduce the fixed-sleep/lock-held-blocking patterns
that were just removed. raycheck is the mechanical check (reference: Ray
gates merges on exactly this kind of tooling — the ASAN/TSAN suites and
custom lint under ``ci/``).

Rules (see :mod:`ray_tpu.tools.raycheck.rules`):

=====  ==================================================================
RC01   lock-held-blocking — no ``time.sleep``, socket send/recv, RPC
       ``call()``/``call_stream()``, or ``open()`` inside a
       ``with <lock>:`` body (cluster/, core/). Locks that serialize the
       I/O itself (``send_lock``-style names) are exempt.
RC02   wall-clock-deadline — no ``time.time()`` in runtime code;
       deadline/backoff/lease arithmetic must use ``time.monotonic()``.
       Genuinely wall-clock sites (filesystem mtimes, user-facing
       timestamps) carry a justified suppression.
RC03   unseeded-randomness — no module-level ``random.*`` /
       ``np.random.*`` draws in cluster/ or scheduler/; an explicit
       ``random.Random`` stream must be threaded in (see
       ``fault_plane.derive_rng``), preserving single-seed replay.
RC04   mutation-token — every GCS mutation RPC handler registered in
       ``gcs_server.py`` must be wrapped by the ``@token_deduped``
       request-token dedupe decorator.
RC05   swallowed-exception — no log-less ``except ...: pass`` in
       cluster/ or core/; swallows get a ``logger.debug`` with enough
       context to attribute them during fault-injection runs.
=====  ==================================================================

Run ``python -m ray_tpu.tools.raycheck`` (exit 0 = clean). Suppress a
single finding inline with ``# raycheck: disable=RC0N`` on the flagged
line or the line above — always with a reason. ``baseline.txt`` can
grandfather known findings by key; it ships empty and should stay empty.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Set

__all__ = [
    "Finding",
    "SourceFile",
    "check_file",
    "check_tree",
    "default_baseline_path",
    "load_baseline",
]


@dataclass(frozen=True)
class Finding:
    code: str      # rule code, e.g. "RC01"
    path: str      # posix path relative to the scan root
    line: int      # 1-indexed
    message: str   # defect + fix-it

    @property
    def key(self) -> str:
        """Stable identity for baseline matching (line numbers drift, so
        the baseline keys on path+code+line — a grandfathered finding
        that moves must be re-reviewed, which is the point)."""
        return f"{self.path}:{self.line}:{self.code}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"


# ``# raycheck: disable=RC01`` or ``disable=RC01,RC05`` — trailing prose
# (the required justification) is ignored by the parser, not by review.
_SUPPRESS_RE = re.compile(
    r"#\s*raycheck:\s*disable=([A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)")


class SourceFile:
    """One parsed file: AST + per-line suppression map."""

    def __init__(self, relpath: str, text: str):
        self.relpath = relpath.replace(os.sep, "/")
        self.text = text
        self.tree = ast.parse(text)
        self._suppressed: Dict[int, Set[str]] = {}
        for lineno, line in enumerate(text.splitlines(), start=1):
            m = _SUPPRESS_RE.search(line)
            if m:
                self._suppressed[lineno] = {
                    c.strip().upper() for c in m.group(1).split(",")}

    def is_suppressed(self, line: int, code: str) -> bool:
        """A suppression comment applies to its own physical line and
        the line directly below it (so long statements can carry the
        comment above)."""
        for ln in (line, line - 1):
            codes = self._suppressed.get(ln)
            if codes and (code in codes or "ALL" in codes):
                return True
        return False


def _resolve_rules(rules=None):
    from ray_tpu.tools.raycheck import rules as _rules

    table = _rules.all_rules()
    if rules is None:
        return table
    wanted = set()
    for r in rules:
        wanted.add(r if isinstance(r, str) else r.code)
    return [r for r in table if r.code in wanted]


def check_file(path: str, relpath: Optional[str] = None,
               rules=None) -> List[Finding]:
    """Run the (selected) rules over one file. Unsuppressed findings
    only; a file that does not parse yields a single RC00 finding."""
    relpath = (relpath or path).replace(os.sep, "/")
    with open(path, "r", encoding="utf-8") as f:
        text = f.read()
    try:
        sf = SourceFile(relpath, text)
    except SyntaxError as e:
        return [Finding("RC00", relpath, e.lineno or 1,
                        f"file does not parse: {e.msg}")]
    findings: List[Finding] = []
    for rule in _resolve_rules(rules):
        if not rule.applies(relpath):
            continue
        for finding in rule.check(sf):
            if not sf.is_suppressed(finding.line, finding.code):
                findings.append(finding)
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    return findings


_SKIP_DIRS = {"__pycache__", ".git", "build", "node_modules"}


def iter_py_files(root: str) -> Iterable[str]:
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if d not in _SKIP_DIRS
                             and not d.startswith("."))
        for name in sorted(filenames):
            if name.endswith(".py"):
                yield os.path.join(dirpath, name)


def check_tree(root: str, rules=None) -> List[Finding]:
    """Scan every ``.py`` under ``root``; finding paths are relative to
    ``root`` (rule scoping matches on those relative path parts)."""
    root = os.path.abspath(root)
    findings: List[Finding] = []
    if os.path.isfile(root):
        return check_file(root, os.path.basename(root), rules)
    for path in iter_py_files(root):
        findings.extend(
            check_file(path, os.path.relpath(path, root), rules))
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    return findings


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(__file__), "baseline.txt")


def load_baseline(path: Optional[str] = None) -> Set[str]:
    """Finding keys (``path:line:code``) grandfathered by the baseline
    file; blank lines and ``#`` comments are ignored. The shipped
    baseline is empty — the tree is raycheck-clean — and new entries
    should be treated as debt, not as a suppression mechanism."""
    path = path or default_baseline_path()
    keys: Set[str] = set()
    if not os.path.exists(path):
        return keys
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line and not line.startswith("#"):
                keys.add(line)
    return keys
