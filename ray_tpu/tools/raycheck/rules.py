"""raycheck rule implementations.

Each rule is a :class:`Rule` with a code, a short title, a path scope
(which part of the tree the invariant governs), and a ``check(sf)``
generator yielding :class:`~ray_tpu.tools.raycheck.Finding`. Rules are
purely syntactic/AST-level by design: they over-approximate (a
legitimate exception gets an inline ``# raycheck: disable=RC0N`` with a
reason) rather than under-approximate (a silent miss is a replay or
liveness bug waiting for a fault-injection run to find it the hard
way)."""

from __future__ import annotations

import ast
import os
import re
from typing import Callable, Iterator, List, Optional

from ray_tpu.tools.raycheck import Finding, SourceFile
from ray_tpu.tools.raycheck import races as _races


class Rule:
    """A per-file rule checks one :class:`SourceFile`; a *program* rule
    (``program=True``) checks the whole-scan :class:`~.facts.Program`
    — its facts span files, so it runs once per tree, after phase 1
    extracted every file's facts."""

    def __init__(self, code: str, title: str,
                 scope: Callable[[List[str]], bool],
                 check: Callable, program: bool = False):
        self.code = code
        self.title = title
        self.program = program
        self._scope = scope
        self._check = check

    def applies(self, relpath: str) -> bool:
        return self._scope(relpath.split("/"))

    def check(self, sf: SourceFile) -> Iterator[Finding]:
        return self._check(sf)

    def check_program(self, program) -> Iterator[Finding]:
        return self._check(program)


def _in_dirs(*dirs: str) -> Callable[[List[str]], bool]:
    """Scope predicate: any of ``dirs`` appears as a directory segment
    of the relative path (works whether the scan root is the repo, the
    package, or a corpus fixture tree)."""
    wanted = set(dirs)
    return lambda parts: bool(wanted.intersection(parts[:-1]))


def _terminal_name(node: ast.AST) -> Optional[str]:
    """``self._avail_lock`` -> ``_avail_lock``; ``send_lock`` ->
    ``send_lock``; calls/subscripts -> None (not a named lock)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


# --------------------------------------------------------------------------
# RC01 — lock-held-blocking
# --------------------------------------------------------------------------

# a with-item naming one of these is treated as a state lock
_LOCK_NAME_RE = re.compile(r"(?:^|_)(?:lock|cv|cond|mutex)$")
# ...unless the name says the lock serializes the I/O itself (the
# send-lock pattern in rpc.py: frames from concurrent handlers must not
# interleave mid-frame, so holding it across sendall is the point)
_IO_LOCK_RE = re.compile(r"send|write|reply")

# socket methods blocking enough to flag unconditionally
_SOCKET_ATTRS = {"sendall", "sendto", "recv_into", "recvfrom"}
# ambiguous names ('send' is also a pipe/generator method): only flagged
# when the receiver's name looks like a socket/connection
_SOCKETISH_ATTRS = {"send", "recv", "connect", "accept"}
_SOCKETISH_RECV_RE = re.compile(r"sock|conn")
# the RPC client surface
_RPC_ATTRS = {"call", "call_stream"}


def _blocking_desc(node: ast.Call) -> Optional[str]:
    fn = node.func
    if isinstance(fn, ast.Name) and fn.id == "open":
        return "file I/O (open())"
    if not isinstance(fn, ast.Attribute):
        return None
    attr = fn.attr
    if attr == "sleep" and isinstance(fn.value, ast.Name) \
            and fn.value.id == "time":
        return "time.sleep()"
    if attr in _SOCKET_ATTRS:
        return f"socket .{attr}()"
    if attr in _SOCKETISH_ATTRS:
        recv = _terminal_name(fn.value)
        if recv and _SOCKETISH_RECV_RE.search(recv.lower()):
            return f"socket .{attr}()"
        return None
    if attr in _RPC_ATTRS:
        return f"blocking RPC .{attr}()"
    return None


def _prune_walk(stmt: ast.stmt) -> Iterator[ast.AST]:
    """ast.walk with pruning at deferred-execution boundaries: nested
    function bodies, lambdas, and class bodies run after the lock is
    released, so calls inside them are not lock-held."""
    stack: List[ast.AST] = [stmt]
    skip = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
            ast.ClassDef)
    while stack:
        node = stack.pop()
        if isinstance(node, skip):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def check_rc01(sf: SourceFile) -> Iterator[Finding]:
    for node in ast.walk(sf.tree):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        lock_name = None
        for item in node.items:
            name = _terminal_name(item.context_expr)
            if name is None:
                continue
            low = name.lower()
            if _LOCK_NAME_RE.search(low) and not _IO_LOCK_RE.search(low):
                lock_name = name
                break
        if lock_name is None:
            continue
        for stmt in node.body:
            for child in _prune_walk(stmt):
                if not isinstance(child, ast.Call):
                    continue
                desc = _blocking_desc(child)
                if desc is not None:
                    yield Finding(
                        "RC01", sf.relpath, child.lineno,
                        f"{desc} while holding `{lock_name}` — move the "
                        f"blocking work outside the critical section "
                        f"(copy state under the lock, act after "
                        f"release); if this lock exists to serialize "
                        f"the I/O itself, name it *send_lock*-style or "
                        f"suppress with a reason")


# --------------------------------------------------------------------------
# RC02 — wall-clock-deadline
# --------------------------------------------------------------------------


def _time_time_calls(sf: SourceFile) -> Iterator[ast.Call]:
    bare_time_imported = any(
        isinstance(n, ast.ImportFrom) and n.module == "time"
        and any(a.name == "time" for a in n.names)
        for n in ast.walk(sf.tree))
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr == "time" \
                and isinstance(fn.value, ast.Name) \
                and fn.value.id == "time":
            yield node
        elif bare_time_imported and isinstance(fn, ast.Name) \
                and fn.id == "time":
            yield node


def check_rc02(sf: SourceFile) -> Iterator[Finding]:
    for call in _time_time_calls(sf):
        yield Finding(
            "RC02", sf.relpath, call.lineno,
            "time.time() in runtime code — deadline/backoff/lease "
            "arithmetic must use time.monotonic() (wall-clock steps "
            "under NTP and breaks expiry math); if wall-clock is "
            "genuinely required (filesystem mtimes, user-facing "
            "timestamps), suppress with the reason")


# --------------------------------------------------------------------------
# RC03 — unseeded-randomness
# --------------------------------------------------------------------------

# constructors of explicit streams are the fix, not the violation
_RANDOM_ALLOWED = {"Random", "SystemRandom"}
_NP_RANDOM_ALLOWED = {"default_rng", "Generator", "RandomState"}


def _module_aliases(sf: SourceFile, module: str) -> set:
    out = set()
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == module:
                    out.add(alias.asname or module)
    return out


def check_rc03(sf: SourceFile) -> Iterator[Finding]:
    rand_aliases = _module_aliases(sf, "random")
    np_aliases = _module_aliases(sf, "numpy")
    fix = ("thread an explicit seeded random.Random stream in "
           "(fault_plane.derive_rng derives one from the active fault "
           "plan's seed) so schedules replay from a single integer seed")
    # `from random import shuffle` defeats the stream discipline outright
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.ImportFrom) and node.module == "random":
            bad = [a.name for a in node.names
                   if a.name not in _RANDOM_ALLOWED]
            if bad:
                yield Finding(
                    "RC03", sf.relpath, node.lineno,
                    f"module-level randomness imported from `random` "
                    f"({', '.join(bad)}) — {fix}")
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call) \
                or not isinstance(node.func, ast.Attribute):
            continue
        fn = node.func
        if isinstance(fn.value, ast.Name) and fn.value.id in rand_aliases \
                and fn.attr not in _RANDOM_ALLOWED:
            yield Finding(
                "RC03", sf.relpath, node.lineno,
                f"random.{fn.attr}() draws from the process-global RNG "
                f"— {fix}")
        elif isinstance(fn.value, ast.Attribute) \
                and fn.value.attr == "random" \
                and isinstance(fn.value.value, ast.Name) \
                and fn.value.value.id in np_aliases \
                and fn.attr not in _NP_RANDOM_ALLOWED:
            yield Finding(
                "RC03", sf.relpath, node.lineno,
                f"np.random.{fn.attr}() draws from numpy's global RNG "
                f"— {fix}")


# --------------------------------------------------------------------------
# RC04 — mutation-token (gcs_server.py cross-checks registration vs defs)
# --------------------------------------------------------------------------

# the GCS mutation surface: retried/duplicated frames must replay the
# cached reply instead of double-applying (double-counted restarts,
# twice-killed actors, double-placed PGs)
MUTATION_HANDLERS = frozenset({
    "actor_create", "actor_kill", "report_actor_failure",
    "pg_create", "pg_remove", "drain_node",
})
_DECORATOR_NAME = "token_deduped"


def _registered_names(tree: ast.AST) -> set:
    """Handler names registered with the RPC server: literal
    ``srv.register("name", ...)`` calls plus ``for name in (...):``
    loops whose body registers the loop variable."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("register", "register_stream") \
                and node.args \
                and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            out.add(node.args[0].value)
        if isinstance(node, ast.For) \
                and isinstance(node.iter, (ast.Tuple, ast.List, ast.Set)):
            registers = any(
                isinstance(c, ast.Call)
                and isinstance(c.func, ast.Attribute)
                and c.func.attr == "register"
                for b in node.body for c in ast.walk(b))
            if registers:
                out.update(
                    e.value for e in node.iter.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str))
    return out


def _has_token_decorator(fn: ast.FunctionDef) -> bool:
    for dec in fn.decorator_list:
        name = _terminal_name(dec)
        if name is None and isinstance(dec, ast.Call):
            name = _terminal_name(dec.func)
        if name == _DECORATOR_NAME:
            return True
    return False


def check_rc04(sf: SourceFile) -> Iterator[Finding]:
    registered = _registered_names(sf.tree)
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for fn in node.body:
            if not isinstance(fn, ast.FunctionDef):
                continue
            is_mutation = fn.name in MUTATION_HANDLERS
            takes_token = any(
                a.arg == "token"
                for a in fn.args.args + fn.args.kwonlyargs)
            if not (is_mutation or (takes_token and fn.name in registered)):
                continue
            if is_mutation and fn.name not in registered:
                yield Finding(
                    "RC04", sf.relpath, fn.lineno,
                    f"mutation handler {fn.name}() is not registered "
                    f"with the RPC server — clients retry it by name; "
                    f"add it to the serve() registration list")
            if not _has_token_decorator(fn):
                why = ("declares a request `token` parameter"
                       if takes_token and not is_mutation
                       else "mutates GCS state")
                yield Finding(
                    "RC04", sf.relpath, fn.lineno,
                    f"handler {fn.name}() {why} but is not wrapped by "
                    f"@{_DECORATOR_NAME} — a client retry after a lost "
                    f"ack (or a fault-plane frame duplication) would "
                    f"double-apply the mutation; decorate it and drop "
                    f"any hand-rolled token plumbing")


# --------------------------------------------------------------------------
# RC05 — swallowed-exception
# --------------------------------------------------------------------------


def check_rc05(sf: SourceFile) -> Iterator[Finding]:
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.ExceptHandler) \
                and len(node.body) == 1 \
                and isinstance(node.body[0], ast.Pass):
            what = (ast.unparse(node.type)
                    if node.type is not None else "BaseException")
            yield Finding(
                "RC05", sf.relpath, node.lineno,
                f"`except {what}: pass` swallows the exception without "
                f"a trace — fault-injection failures become "
                f"unattributable; add a logger.debug(...) carrying "
                f"enough context (what was being attempted, on what "
                f"object/peer) or suppress with the reason the swallow "
                f"is safe")


# --------------------------------------------------------------------------
# RC06 — wire-method-resolution (whole-program)
# --------------------------------------------------------------------------


def check_rc06(prog) -> Iterator[Finding]:
    """Joins every wire call site against every registered handler:
    a typo'd method name fails here instead of at runtime (reference:
    proto-compiled stubs make an unknown RPC a compile error), dead
    handlers/schemas are surfaced so the wire surface cannot silently
    rot, and unary/stream kind mismatches are rejected."""
    handlers = prog.handler_map()
    called = prog.called_methods()
    for cs in prog.wire_call_sites():
        hs = handlers.get(cs.method)
        if not hs:
            yield Finding(
                "RC06", cs.path, cs.line,
                f".{cs.kind}({cs.method!r}) resolves to no registered "
                f"handler — no srv.register()/register_stream() in the "
                f"scanned tree declares it; a typo'd or renamed method "
                f"only fails at runtime (AttributeError in dispatch), "
                f"and only on the code path a test happens to exercise")
            continue
        is_stream = any(h.is_stream for h in hs)
        is_unary = any(not h.is_stream for h in hs)
        if cs.kind == "call_stream" and not is_stream:
            yield Finding(
                "RC06", cs.path, cs.line,
                f".call_stream({cs.method!r}) targets a unary handler "
                f"— the reply is a single ok frame, not a chunk "
                f"stream; use .call() or register the handler with "
                f"register_stream()")
        elif cs.kind in ("call", "call_async") and not is_unary:
            yield Finding(
                "RC06", cs.path, cs.line,
                f".{cs.kind}({cs.method!r}) targets a stream handler "
                f"— chunks would be dropped by the unary completion "
                f"path; use .call_stream()")
    for method in sorted(handlers):
        if method in called:
            continue
        for h in handlers[method]:
            yield Finding(
                "RC06", h.path, h.line,
                f"handler {method!r} ({h.server}) is registered but no "
                f".call()/.call_async()/.call_stream() site in the "
                f"scanned tree invokes it — dead wire surface drifts "
                f"unchecked; delete the registration or wire up the "
                f"caller that should exist")
    for sd in prog.schemas:
        if sd.method not in handlers:
            yield Finding(
                "RC06", sd.path, sd.line,
                f"@message({sd.method!r}) schema has no registered "
                f"handler — it validates nothing; delete it or "
                f"register the handler it was written for")


# --------------------------------------------------------------------------
# RC07 — wire-schema-conformance (whole-program)
# --------------------------------------------------------------------------


def check_rc07(prog) -> Iterator[Finding]:
    """Three joins around ``cluster/schema.py``'s @message registry:
    every registered handler must have a schema (the IDL-coverage bar
    — an unschema'd method skips validation entirely), the schema's
    field set must match the handler's signature (validate() drops
    unknown kwargs BEFORE dispatch, so drift surfaces as a missing-arg
    TypeError or a silently lost field), and every literal call site
    must satisfy the schema (required fields present, no fields the
    receiver would drop, literal types the validator accepts)."""
    from ray_tpu.tools.raycheck import facts as _facts

    handlers = prog.handler_map()
    schemas = prog.schema_map()
    for method in sorted(handlers):
        sd = schemas.get(method)
        for h in handlers[method]:
            if sd is None:
                yield Finding(
                    "RC07", h.path, h.line,
                    f"registered handler {method!r} ({h.server}) has "
                    f"no @message schema — its kwargs cross the wire "
                    f"unvalidated (reference: every Ray RPC has a "
                    f".proto message); declare one in "
                    f"cluster/schema.py")
                continue
            if not h.resolved:
                continue
            params = set(h.required) | set(h.optional)
            fields = sd.field_map()
            if not h.var_kw:
                for f in sd.fields:
                    if f.name not in params:
                        yield Finding(
                            "RC07", sd.path, f.line,
                            f"schema field {f.name!r} of "
                            f"@message({method!r}) is not a parameter "
                            f"of the registered handler ({h.server}) "
                            f"— validate() passes it through and "
                            f"dispatch dies with TypeError; remove "
                            f"the field or add the parameter")
            for p in h.required:
                if p not in fields:
                    yield Finding(
                        "RC07", h.path, h.line,
                        f"handler {method!r} requires parameter "
                        f"{p!r} but @message({method!r}) does not "
                        f"declare it — validate() drops or omits the "
                        f"field before dispatch, so every call dies "
                        f"with a missing-argument TypeError; add the "
                        f"field to the schema")
    for cs in prog.wire_call_sites():
        sd = schemas.get(cs.method)
        if sd is None:
            continue
        fields = sd.field_map()
        keys = set(cs.keys) - _facts.CLIENT_KWARGS
        for k in sorted(keys - set(fields)):
            yield Finding(
                "RC07", cs.path, cs.line,
                f"field {k!r} of this {cs.method!r} call is not in "
                f"its @message schema — the receiver SILENTLY DROPS "
                f"unknown fields (proto3 posture), so the argument "
                f"never arrives; fix the kwarg name or extend the "
                f"schema")
        if not cs.splat:
            for f in sd.fields:
                if f.required and f.name not in keys:
                    yield Finding(
                        "RC07", cs.path, cs.line,
                        f"required field {f.name!r} of "
                        f"@message({cs.method!r}) is missing at this "
                        f"call site — validate() raises SchemaError "
                        f"at dispatch; pass it (or give the field a "
                        f"default in cluster/schema.py)")
        for key, typename in cs.consts:
            f = fields.get(key)
            if f is not None and not _facts.type_compatible(f.type,
                                                            typename):
                yield Finding(
                    "RC07", cs.path, cs.line,
                    f"literal {typename} for field {key!r} of "
                    f"@message({cs.method!r}) — the schema declares "
                    f"{f.type} and validate() raises SchemaError; "
                    f"fix the literal or the declared type")


# --------------------------------------------------------------------------
# RC08 — lock-order-cycle (whole-program)
# --------------------------------------------------------------------------


def check_rc08(prog) -> Iterator[Finding]:
    """Cycle detection on the inter-procedural lock-acquisition graph
    over cluster/ + core/ (the static half of what TSAN's deadlock
    detector sees at runtime): two code paths taking the same pair of
    locks in opposite orders can deadlock under concurrency — each
    cycle is reported once with every participating edge's stack."""
    for cycle in prog.lock_cycles:
        first = cycle[0]
        lines = []
        for e in cycle:
            via = f" via {e.via.split('::')[-1]}()" if e.via else ""
            lines.append(f"holding `{_short(e.src)}` acquires "
                         f"`{_short(e.dst)}` at {e.path}:{e.line} "
                         f"(in {e.holder.split('::')[-1]}{via})")
        yield Finding(
            "RC08", first.path, first.line,
            "lock-order cycle (potential deadlock): "
            + "; ".join(lines)
            + " — pick one order and restructure the other path "
            "(copy state under the first lock, act after release), "
            "or suppress with the reason the paths cannot run "
            "concurrently")


def _short(lock_id: str) -> str:
    return lock_id.split("::")[-1]


# --------------------------------------------------------------------------
# RC09 — unmanaged-thread (whole-program facts, per-site findings)
# --------------------------------------------------------------------------


def check_rc09(prog) -> Iterator[Finding]:
    """Every ``threading.Thread(...)`` in the server/daemon modules
    (cluster/, core/) must spawn through a
    :class:`~ray_tpu.cluster.threads.ThreadRegistry` — unregistered
    daemons outlive teardown silently and mutate half-torn-down state;
    the registry joins them BY NAME (threads.py itself is the one
    legitimate spawn site)."""
    for ts in prog.thread_spawns:
        if ts.path.endswith("cluster/threads.py") \
                or ts.path == "threads.py":
            continue
        yield Finding(
            "RC09", ts.path, ts.line,
            "threading.Thread() outside cluster/threads.py — "
            "server/daemon threads must spawn through a "
            "ThreadRegistry so teardown joins them by name instead "
            "of leaking them into the next test; if this thread's "
            "lifetime is genuinely bound to another resource (a "
            "connection, a child process), suppress with that reason")


# --------------------------------------------------------------------------
# RC10 — unbounded-queue
# --------------------------------------------------------------------------

# queue constructors whose bound is the FIRST positional / a keyword
_QUEUE_CLASSES = {
    "Queue": "maxsize",
    "LifoQueue": "maxsize",
    "PriorityQueue": "maxsize",
}


def _queue_ctor_name(node: ast.Call) -> Optional[str]:
    """'deque' / 'Queue' / 'SimpleQueue' / ... for a constructor call,
    whether imported bare (``deque(...)``) or qualified
    (``collections.deque(...)``, ``queue.Queue(...)``)."""
    fn = node.func
    name = None
    if isinstance(fn, ast.Name):
        name = fn.id
    elif isinstance(fn, ast.Attribute):
        name = fn.attr
    if name in _QUEUE_CLASSES or name in ("deque", "SimpleQueue"):
        return name
    return None


def check_rc10(sf: SourceFile) -> Iterator[Finding]:
    """Unbounded producer/consumer queues in the runtime's server and
    daemon modules are the raw material of metastable overload: under a
    stalled consumer they grow without limit, converting a transient
    slowdown into memory exhaustion and unbounded queueing delay
    (Bronson et al., HotOS '21). Every ``deque()`` / ``queue.Queue()``
    must carry an explicit bound (``maxlen=`` / ``maxsize=``);
    ``SimpleQueue`` cannot carry one and is always flagged. A queue
    bounded by an admission check elsewhere (shed-on-submit) carries a
    suppression naming that check."""
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _queue_ctor_name(node)
        if name is None:
            continue
        kwargs = {kw.arg for kw in node.keywords if kw.arg}
        if name == "deque":
            # deque(iterable, maxlen) — bounded via kwarg or 2nd arg
            if "maxlen" in kwargs or len(node.args) >= 2:
                continue
            fix = "give it maxlen=..."
        elif name == "SimpleQueue":
            fix = ("SimpleQueue has no bound at all — use "
                   "queue.Queue(maxsize=...)")
        else:
            # Queue/LifoQueue/PriorityQueue(maxsize=...) — a literal 0
            # (or omitted) means infinite
            bound = None
            if node.args:
                bound = node.args[0]
            for kw in node.keywords:
                if kw.arg == "maxsize":
                    bound = kw.value
            if bound is not None and not (
                    isinstance(bound, ast.Constant)
                    and bound.value in (0, None)):
                continue
            fix = "pass maxsize=..."
        yield Finding(
            "RC10", sf.relpath, node.lineno,
            f"unbounded {name}() in runtime code — under a stalled "
            f"consumer it grows without limit (queueing delay and "
            f"memory are the overload amplifiers); {fix}, or gate "
            f"every enqueue behind an admission check that sheds with "
            f"RetryLaterError and suppress with the check's name")


# --------------------------------------------------------------------------
# RC11 — batch-handler-dedupe
# --------------------------------------------------------------------------

_ROW_TOKEN_RE = re.compile(r"_row_token")


def check_rc11(sf: SourceFile) -> Iterator[Finding]:
    """Every public ``*_batch`` wire handler in the server modules
    applies a frame of rows that mutate cluster state. A frame retried
    after a dropped reply — or replayed by a GCS recovering its journal
    — re-delivers every row, so the handler must resolve rows against
    the per-row idempotence-token path (``_row_tokens_resolve`` on the
    GCS, ``_row_token_seen``/``_row_token_store`` on the raylet) before
    applying them; cached rows are re-answered, not re-applied. A
    handler whose rows are genuinely idempotent (kills: killing a dead
    actor is a no-op) carries a suppression saying exactly that."""
    for node in ast.walk(sf.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name.startswith("_") or not node.name.endswith("_batch"):
            continue
        has_token_path = False
        for inner in ast.walk(node):
            if not isinstance(inner, ast.Call):
                continue
            name = _terminal_name(inner.func)
            if name and _ROW_TOKEN_RE.search(name):
                has_token_path = True
                break
        if has_token_path:
            continue
        yield Finding(
            "RC11", sf.relpath, node.lineno,
            f"batch wire handler {node.name}() applies rows without a "
            f"per-row idempotence-token path — a frame retried after a "
            f"lost reply (or replayed by a restarted GCS) re-applies "
            f"every row, double-placing tasks or double-creating "
            f"actors; resolve the frame through _row_tokens_resolve() "
            f"/ _row_token_seen() and store accepted rows, or suppress "
            f"with the reason the rows are idempotent")


# --------------------------------------------------------------------------
# RC12 — resource-lifecycle (whole-program, flow-sensitive)
# --------------------------------------------------------------------------

# the runtime dirs whose acquire sites RC12 governs; corpus fixtures
# mirror the layout, so the same predicate scopes both
_RC12_DIRS = _in_dirs("cluster", "core", "serve", "observability",
                      "autoscaler", "scheduler")


def check_rc12(program) -> Iterator[Finding]:
    """Flow-sensitive leak detection: for every function in the runtime
    dirs, build a CFG (normal + exception edges) and run a may-hold
    dataflow over acquired resources (see :mod:`.cfg` for the
    acquire/release table and the ownership-transfer kills). A resource
    still live at a normal or exceptional exit on some path escaped
    without release or return-to-owner."""
    from ray_tpu.tools.raycheck import cfg as _cfg

    for path in sorted(program.file_functions):
        if not _RC12_DIRS(path.split("/")):
            continue
        for fl in _cfg.analyze_functions(
                path, program.file_functions[path]):
            fn = fl.name.rsplit("::", 1)[-1]
            for leak in fl.leaks:
                how = ("on exception paths (a statement between "
                       "acquire and release can raise)"
                       if leak.exceptional else "on some path")
                yield Finding(
                    "RC12", path, leak.line,
                    f"{leak.kind} acquired into `{leak.var}` in "
                    f"{fn}() escapes {how} without release or "
                    f"return-to-owner — release it in a "
                    f"finally/with, hand it back to its pool, or "
                    f"store it on the owning object; if ownership "
                    f"genuinely transfers in a way the checker "
                    f"cannot see, suppress with the reason")


# --------------------------------------------------------------------------
# RC13 — protocol-state-machine (whole-program)
# --------------------------------------------------------------------------


def check_rc13(program) -> Iterator[Finding]:
    """Check the state machines declared in ``protocols.py`` (see
    :mod:`.protocols`) against themselves and against the phase-1 wire
    map: states must be declared/reachable, terminal states must be
    final, every non-initial non-terminal state needs a timeout/abort
    escape edge, wire drivers must resolve to a registered handler or
    ``@message`` schema, internal drivers to a function defined in the
    tree, and every op the conversation covers (explicitly or by
    ``<name>_`` prefix) must drive at least one edge."""
    decls = list(program.protocol_decls)
    if not decls:
        return
    wire_known = set(program.handler_map()) | set(program.schema_map())
    fn_names = program.function_names()
    for p in sorted(decls, key=lambda d: (d.path, d.line)):
        if p.malformed:
            yield Finding(
                "RC13", p.path, p.line,
                f"protocol {p.name or '<unnamed>'} is not statically "
                f"analyzable ({p.malformed}) — declare states, "
                f"transitions, and covers as plain literals so the "
                f"machine can be checked against the wire map")
            continue
        states = set(p.states)
        terminal = set(p.terminal)
        label = f"protocol {p.name}"
        for s in list(terminal) + ([p.initial] if p.initial else []):
            if s not in states:
                yield Finding(
                    "RC13", p.path, p.line,
                    f"{label}: state '{s}' (initial/terminal) is not "
                    f"in the declared state set")
        adj: dict = {s: set() for s in states}
        escapes_from: set = set()
        for t in p.transitions:
            for s in (t.src, t.dst):
                if s not in states:
                    yield Finding(
                        "RC13", p.path, t.line,
                        f"{label}: transition {t.src}→{t.dst} "
                        f"references undeclared state '{s}'")
            if t.src in terminal:
                yield Finding(
                    "RC13", p.path, t.line,
                    f"{label}: illegal transition out of terminal "
                    f"state '{t.src}' ({t.src}→{t.dst} via "
                    f"{t.driver}) — terminal means the conversation "
                    f"is over; add an explicit restart state if "
                    f"re-entry is real")
            if t.src in adj:
                adj[t.src].add(t.dst)
            if t.escape:
                escapes_from.add(t.src)
            if t.kind == "wire":
                if wire_known and t.driver not in wire_known:
                    yield Finding(
                        "RC13", p.path, t.line,
                        f"{label}: wire driver '{t.driver}' for "
                        f"{t.src}→{t.dst} resolves to no registered "
                        f"handler or @message schema — the declared "
                        f"conversation and the wire surface drifted")
            elif fn_names and t.driver not in fn_names:
                yield Finding(
                    "RC13", p.path, t.line,
                    f"{label}: internal driver '{t.driver}' for "
                    f"{t.src}→{t.dst} is not a function defined "
                    f"anywhere in the tree — the sweeper/deadline "
                    f"path this edge depends on does not exist")
        # reachability from the initial state
        if p.initial in states:
            seen = {p.initial}
            frontier = [p.initial]
            while frontier:
                cur = frontier.pop()
                for nxt in adj.get(cur, ()):
                    if nxt in states and nxt not in seen:
                        seen.add(nxt)
                        frontier.append(nxt)
            for s in sorted(states - seen):
                yield Finding(
                    "RC13", p.path, p.line,
                    f"{label}: state '{s}' is unreachable from "
                    f"initial state '{p.initial}' — dead protocol "
                    f"surface, or a missing transition")
        # every non-initial, non-terminal state must have an escape
        # edge: without one, a dead peer wedges the conversation there
        for s in sorted(states - terminal - {p.initial}):
            if s not in escapes_from:
                yield Finding(
                    "RC13", p.path, p.line,
                    f"{label}: state '{s}' has no timeout/abort "
                    f"escape edge — a peer dying mid-conversation "
                    f"wedges it there forever; add the "
                    f"sweep/deadline/abort transition and mark it "
                    f"escape=True")
        # coverage: declared covers + the op-name family must all
        # drive at least one edge
        drivers = {t.driver for t in p.transitions if t.kind == "wire"}
        for op in p.covers:
            if wire_known and op not in wire_known:
                yield Finding(
                    "RC13", p.path, p.line,
                    f"{label}: covered op '{op}' is not a registered "
                    f"handler or schema")
            if op not in drivers:
                yield Finding(
                    "RC13", p.path, p.line,
                    f"{label}: covered op '{op}' drives no declared "
                    f"transition — a message in the conversation the "
                    f"machine does not model")
        prefix = p.name + "_"
        for op in sorted(wire_known):
            if op.startswith(prefix) and op not in p.covers:
                yield Finding(
                    "RC13", p.path, p.line,
                    f"{label}: wire op '{op}' matches the "
                    f"conversation's name family but is not in "
                    f"covers — new messages must be placed in the "
                    f"state machine (or covered and given edges)")


# --------------------------------------------------------------------------
# RC14 — knob-hygiene (whole-program)
# --------------------------------------------------------------------------


def _read_text(path: str) -> Optional[str]:
    try:
        with open(path, "r", encoding="utf-8") as f:
            return f.read()
    except OSError:
        return None


def _find_aux(root: Optional[str], name: str) -> Optional[str]:
    """Locate ``name`` (a file or dir) at the scan root or one level
    up — the CLI scans the package dir, check.sh the repo root, and a
    corpus fixture is its own root."""
    if root is None:
        return None
    for base in (root, os.path.dirname(root)):
        cand = os.path.join(base, name)
        if os.path.exists(cand):
            return cand
    return None


def check_rc14(program) -> Iterator[Finding]:
    """Every ``Config`` knob must be (1) read somewhere outside its
    defining config.py — an unread knob is dead tuning surface that
    silently does nothing; (2) documented in the README knob tables;
    (3) exercised by at least one test that sets a non-default value.
    Checks (2)/(3) skip when the scan root has no README/tests beside
    it (single-file and bare-corpus scans)."""
    if not program.knobs:
        return
    # "read": the name appears outside the DEFINING file (serve's own
    # config.py is a legitimate reader of the global knobs), as an
    # identifier or as a string constant (the getattr-by-knob-name
    # idiom in the overload lane map)
    defining = {k.path for k in program.knobs}
    used_outside: dict = {p: set() for p in defining}
    for path in program.used_names_by_path:
        for def_path in defining:
            if path != def_path:
                used_outside[def_path] |= \
                    program.used_names_by_path[path]
                used_outside[def_path] |= \
                    program.used_strings_by_path.get(path, set())
    readme_path = _find_aux(program.root, "README.md")
    readme = _read_text(readme_path) if readme_path else None
    readme_words = set(re.findall(r"\w+", readme)) if readme else None
    tests_dir = _find_aux(program.root, "tests")
    tests_text = None
    if tests_dir and os.path.isdir(tests_dir):
        chunks = []
        for dirpath, dirnames, filenames in os.walk(tests_dir):
            dirnames[:] = [d for d in dirnames
                           if d != "__pycache__"
                           and not d.startswith(".")]
            for fname in filenames:
                if fname.endswith(".py"):
                    text = _read_text(os.path.join(dirpath, fname))
                    if text:
                        chunks.append(text)
        tests_text = "\n".join(chunks)
    tests_words = (set(re.findall(r"\w+", tests_text))
                   if tests_text else None)
    for knob in sorted(program.knobs, key=lambda k: (k.path, k.line)):
        if knob.name not in used_outside[knob.path]:
            yield Finding(
                "RC14", knob.path, knob.line,
                f"Config knob '{knob.name}' is never read outside "
                f"{knob.path} — dead tuning surface: wire it into the "
                f"code path it is meant to govern, or delete it")
        if readme_words is not None and knob.name not in readme_words:
            yield Finding(
                "RC14", knob.path, knob.line,
                f"Config knob '{knob.name}' is missing from the "
                f"README knob tables — document the default, the "
                f"unit, and what it governs")
        if tests_words is not None and knob.name not in tests_words:
            yield Finding(
                "RC14", knob.path, knob.line,
                f"Config knob '{knob.name}' is not exercised by any "
                f"test — add coverage that sets a non-default value "
                f"and observes the governed behavior")


# --------------------------------------------------------------------------
# RC15 — counter-hygiene (whole-program)
# --------------------------------------------------------------------------


def check_rc15(program) -> Iterator[Finding]:
    """Counters must round-trip: every ``.inc()`` site targets a metric
    registered in observability/metrics.py (a typo'd receiver silently
    counts into nothing via a registry miss or an AttributeError on a
    cold path); every registered metric is used outside the registry
    (dead metrics are dashboard noise); every dict-valued heartbeat
    stats field is rendered by ``cli.py status`` (stats shipped on
    every heartbeat but never shown are dead wire weight)."""
    metric_names = {m.name for m in program.metrics}
    if metric_names:
        for site in sorted(program.inc_sites,
                           key=lambda s: (s.path, s.line)):
            if not _RC12_DIRS(site.path.split("/")):
                continue
            if site.receiver not in metric_names:
                yield Finding(
                    "RC15", site.path, site.line,
                    f".inc() on '{site.receiver}' which is not a "
                    f"metric registered in the metrics module — "
                    f"register it (Counter/Gauge/Histogram) or fix "
                    f"the receiver name")
        used = program.names_used_outside("metrics")
        for m in sorted(program.metrics,
                        key=lambda m: (m.path, m.line)):
            if m.name not in used:
                yield Finding(
                    "RC15", m.path, m.line,
                    f"{m.kind} '{m.name}' is registered but never "
                    f"used outside {m.path} — dead metric: "
                    f"instrument the code path or delete the "
                    f"registration")
    hb = program.schema_map().get("heartbeat")
    cli_strings: set = set()
    has_cli = False
    for path, strings in program.used_strings_by_path.items():
        if path.rsplit("/", 1)[-1] == "cli.py":
            has_cli = True
            cli_strings |= strings
    if hb is not None and has_cli:
        for field in hb.fields:
            base = field.type.lower()
            if "dict" not in base:
                continue
            if field.name not in cli_strings:
                yield Finding(
                    "RC15", hb.path, field.line,
                    f"heartbeat stats field '{field.name}' is "
                    f"shipped on every heartbeat but never rendered "
                    f"by `cli.py status` — render it (or stop "
                    f"shipping it)")


# --------------------------------------------------------------------------
# registry
# --------------------------------------------------------------------------

_ANY = lambda parts: True  # noqa: E731 — program rules scope via facts

# serve/ joined the runtime scope with the serve resilience plane: its
# controller probe/drain loops, router, and replica shed path carry the
# same liveness/determinism obligations as cluster/ and core/
_RULES = [
    Rule("RC01", "lock-held-blocking",
         _in_dirs("cluster", "core", "serve"), check_rc01),
    Rule("RC02", "wall-clock-deadline",
         _in_dirs("cluster", "core", "scheduler", "serve"), check_rc02),
    Rule("RC03", "unseeded-randomness",
         _in_dirs("cluster", "scheduler"), check_rc03),
    Rule("RC04", "mutation-token",
         lambda parts: parts[-1] == "gcs_server.py", check_rc04),
    Rule("RC05", "swallowed-exception",
         _in_dirs("cluster", "core", "serve"), check_rc05),
    Rule("RC06", "wire-method-resolution", _ANY, check_rc06,
         program=True),
    Rule("RC07", "wire-schema-conformance", _ANY, check_rc07,
         program=True),
    Rule("RC08", "lock-order-cycle", _ANY, check_rc08, program=True),
    Rule("RC09", "unmanaged-thread", _ANY, check_rc09, program=True),
    Rule("RC10", "unbounded-queue",
         _in_dirs("cluster", "core", "serve"), check_rc10),
    Rule("RC11", "batch-handler-dedupe",
         lambda parts: parts[-1] in ("gcs_server.py",
                                     "raylet_server.py"), check_rc11),
    Rule("RC12", "resource-lifecycle", _ANY, check_rc12, program=True),
    Rule("RC13", "protocol-state-machine", _ANY, check_rc13,
         program=True),
    Rule("RC14", "knob-hygiene", _ANY, check_rc14, program=True),
    Rule("RC15", "counter-hygiene", _ANY, check_rc15, program=True),
    Rule("RC16", "guarded-by-data-race", _ANY, _races.check_rc16,
         program=True),
    Rule("RC17", "unbounded-blocking", _ANY, _races.check_rc17,
         program=True),
]


def all_rules() -> List[Rule]:
    return list(_RULES)
