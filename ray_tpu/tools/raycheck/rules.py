"""raycheck rule implementations.

Each rule is a :class:`Rule` with a code, a short title, a path scope
(which part of the tree the invariant governs), and a ``check(sf)``
generator yielding :class:`~ray_tpu.tools.raycheck.Finding`. Rules are
purely syntactic/AST-level by design: they over-approximate (a
legitimate exception gets an inline ``# raycheck: disable=RC0N`` with a
reason) rather than under-approximate (a silent miss is a replay or
liveness bug waiting for a fault-injection run to find it the hard
way)."""

from __future__ import annotations

import ast
import re
from typing import Callable, Iterator, List, Optional

from ray_tpu.tools.raycheck import Finding, SourceFile


class Rule:
    def __init__(self, code: str, title: str,
                 scope: Callable[[List[str]], bool],
                 check: Callable[[SourceFile], Iterator[Finding]]):
        self.code = code
        self.title = title
        self._scope = scope
        self._check = check

    def applies(self, relpath: str) -> bool:
        return self._scope(relpath.split("/"))

    def check(self, sf: SourceFile) -> Iterator[Finding]:
        return self._check(sf)


def _in_dirs(*dirs: str) -> Callable[[List[str]], bool]:
    """Scope predicate: any of ``dirs`` appears as a directory segment
    of the relative path (works whether the scan root is the repo, the
    package, or a corpus fixture tree)."""
    wanted = set(dirs)
    return lambda parts: bool(wanted.intersection(parts[:-1]))


def _terminal_name(node: ast.AST) -> Optional[str]:
    """``self._avail_lock`` -> ``_avail_lock``; ``send_lock`` ->
    ``send_lock``; calls/subscripts -> None (not a named lock)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


# --------------------------------------------------------------------------
# RC01 — lock-held-blocking
# --------------------------------------------------------------------------

# a with-item naming one of these is treated as a state lock
_LOCK_NAME_RE = re.compile(r"(?:^|_)(?:lock|cv|cond|mutex)$")
# ...unless the name says the lock serializes the I/O itself (the
# send-lock pattern in rpc.py: frames from concurrent handlers must not
# interleave mid-frame, so holding it across sendall is the point)
_IO_LOCK_RE = re.compile(r"send|write|reply")

# socket methods blocking enough to flag unconditionally
_SOCKET_ATTRS = {"sendall", "sendto", "recv_into", "recvfrom"}
# ambiguous names ('send' is also a pipe/generator method): only flagged
# when the receiver's name looks like a socket/connection
_SOCKETISH_ATTRS = {"send", "recv", "connect", "accept"}
_SOCKETISH_RECV_RE = re.compile(r"sock|conn")
# the RPC client surface
_RPC_ATTRS = {"call", "call_stream"}


def _blocking_desc(node: ast.Call) -> Optional[str]:
    fn = node.func
    if isinstance(fn, ast.Name) and fn.id == "open":
        return "file I/O (open())"
    if not isinstance(fn, ast.Attribute):
        return None
    attr = fn.attr
    if attr == "sleep" and isinstance(fn.value, ast.Name) \
            and fn.value.id == "time":
        return "time.sleep()"
    if attr in _SOCKET_ATTRS:
        return f"socket .{attr}()"
    if attr in _SOCKETISH_ATTRS:
        recv = _terminal_name(fn.value)
        if recv and _SOCKETISH_RECV_RE.search(recv.lower()):
            return f"socket .{attr}()"
        return None
    if attr in _RPC_ATTRS:
        return f"blocking RPC .{attr}()"
    return None


def _prune_walk(stmt: ast.stmt) -> Iterator[ast.AST]:
    """ast.walk with pruning at deferred-execution boundaries: nested
    function bodies, lambdas, and class bodies run after the lock is
    released, so calls inside them are not lock-held."""
    stack: List[ast.AST] = [stmt]
    skip = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
            ast.ClassDef)
    while stack:
        node = stack.pop()
        if isinstance(node, skip):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def check_rc01(sf: SourceFile) -> Iterator[Finding]:
    for node in ast.walk(sf.tree):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        lock_name = None
        for item in node.items:
            name = _terminal_name(item.context_expr)
            if name is None:
                continue
            low = name.lower()
            if _LOCK_NAME_RE.search(low) and not _IO_LOCK_RE.search(low):
                lock_name = name
                break
        if lock_name is None:
            continue
        for stmt in node.body:
            for child in _prune_walk(stmt):
                if not isinstance(child, ast.Call):
                    continue
                desc = _blocking_desc(child)
                if desc is not None:
                    yield Finding(
                        "RC01", sf.relpath, child.lineno,
                        f"{desc} while holding `{lock_name}` — move the "
                        f"blocking work outside the critical section "
                        f"(copy state under the lock, act after "
                        f"release); if this lock exists to serialize "
                        f"the I/O itself, name it *send_lock*-style or "
                        f"suppress with a reason")


# --------------------------------------------------------------------------
# RC02 — wall-clock-deadline
# --------------------------------------------------------------------------


def _time_time_calls(sf: SourceFile) -> Iterator[ast.Call]:
    bare_time_imported = any(
        isinstance(n, ast.ImportFrom) and n.module == "time"
        and any(a.name == "time" for a in n.names)
        for n in ast.walk(sf.tree))
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr == "time" \
                and isinstance(fn.value, ast.Name) \
                and fn.value.id == "time":
            yield node
        elif bare_time_imported and isinstance(fn, ast.Name) \
                and fn.id == "time":
            yield node


def check_rc02(sf: SourceFile) -> Iterator[Finding]:
    for call in _time_time_calls(sf):
        yield Finding(
            "RC02", sf.relpath, call.lineno,
            "time.time() in runtime code — deadline/backoff/lease "
            "arithmetic must use time.monotonic() (wall-clock steps "
            "under NTP and breaks expiry math); if wall-clock is "
            "genuinely required (filesystem mtimes, user-facing "
            "timestamps), suppress with the reason")


# --------------------------------------------------------------------------
# RC03 — unseeded-randomness
# --------------------------------------------------------------------------

# constructors of explicit streams are the fix, not the violation
_RANDOM_ALLOWED = {"Random", "SystemRandom"}
_NP_RANDOM_ALLOWED = {"default_rng", "Generator", "RandomState"}


def _module_aliases(sf: SourceFile, module: str) -> set:
    out = set()
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == module:
                    out.add(alias.asname or module)
    return out


def check_rc03(sf: SourceFile) -> Iterator[Finding]:
    rand_aliases = _module_aliases(sf, "random")
    np_aliases = _module_aliases(sf, "numpy")
    fix = ("thread an explicit seeded random.Random stream in "
           "(fault_plane.derive_rng derives one from the active fault "
           "plan's seed) so schedules replay from a single integer seed")
    # `from random import shuffle` defeats the stream discipline outright
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.ImportFrom) and node.module == "random":
            bad = [a.name for a in node.names
                   if a.name not in _RANDOM_ALLOWED]
            if bad:
                yield Finding(
                    "RC03", sf.relpath, node.lineno,
                    f"module-level randomness imported from `random` "
                    f"({', '.join(bad)}) — {fix}")
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call) \
                or not isinstance(node.func, ast.Attribute):
            continue
        fn = node.func
        if isinstance(fn.value, ast.Name) and fn.value.id in rand_aliases \
                and fn.attr not in _RANDOM_ALLOWED:
            yield Finding(
                "RC03", sf.relpath, node.lineno,
                f"random.{fn.attr}() draws from the process-global RNG "
                f"— {fix}")
        elif isinstance(fn.value, ast.Attribute) \
                and fn.value.attr == "random" \
                and isinstance(fn.value.value, ast.Name) \
                and fn.value.value.id in np_aliases \
                and fn.attr not in _NP_RANDOM_ALLOWED:
            yield Finding(
                "RC03", sf.relpath, node.lineno,
                f"np.random.{fn.attr}() draws from numpy's global RNG "
                f"— {fix}")


# --------------------------------------------------------------------------
# RC04 — mutation-token (gcs_server.py cross-checks registration vs defs)
# --------------------------------------------------------------------------

# the GCS mutation surface: retried/duplicated frames must replay the
# cached reply instead of double-applying (double-counted restarts,
# twice-killed actors, double-placed PGs)
MUTATION_HANDLERS = frozenset({
    "actor_create", "actor_kill", "report_actor_failure",
    "pg_create", "pg_remove",
})
_DECORATOR_NAME = "token_deduped"


def _registered_names(tree: ast.AST) -> set:
    """Handler names registered with the RPC server: literal
    ``srv.register("name", ...)`` calls plus ``for name in (...):``
    loops whose body registers the loop variable."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("register", "register_stream") \
                and node.args \
                and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            out.add(node.args[0].value)
        if isinstance(node, ast.For) \
                and isinstance(node.iter, (ast.Tuple, ast.List, ast.Set)):
            registers = any(
                isinstance(c, ast.Call)
                and isinstance(c.func, ast.Attribute)
                and c.func.attr == "register"
                for b in node.body for c in ast.walk(b))
            if registers:
                out.update(
                    e.value for e in node.iter.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str))
    return out


def _has_token_decorator(fn: ast.FunctionDef) -> bool:
    for dec in fn.decorator_list:
        name = _terminal_name(dec)
        if name is None and isinstance(dec, ast.Call):
            name = _terminal_name(dec.func)
        if name == _DECORATOR_NAME:
            return True
    return False


def check_rc04(sf: SourceFile) -> Iterator[Finding]:
    registered = _registered_names(sf.tree)
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for fn in node.body:
            if not isinstance(fn, ast.FunctionDef):
                continue
            is_mutation = fn.name in MUTATION_HANDLERS
            takes_token = any(
                a.arg == "token"
                for a in fn.args.args + fn.args.kwonlyargs)
            if not (is_mutation or (takes_token and fn.name in registered)):
                continue
            if is_mutation and fn.name not in registered:
                yield Finding(
                    "RC04", sf.relpath, fn.lineno,
                    f"mutation handler {fn.name}() is not registered "
                    f"with the RPC server — clients retry it by name; "
                    f"add it to the serve() registration list")
            if not _has_token_decorator(fn):
                why = ("declares a request `token` parameter"
                       if takes_token and not is_mutation
                       else "mutates GCS state")
                yield Finding(
                    "RC04", sf.relpath, fn.lineno,
                    f"handler {fn.name}() {why} but is not wrapped by "
                    f"@{_DECORATOR_NAME} — a client retry after a lost "
                    f"ack (or a fault-plane frame duplication) would "
                    f"double-apply the mutation; decorate it and drop "
                    f"any hand-rolled token plumbing")


# --------------------------------------------------------------------------
# RC05 — swallowed-exception
# --------------------------------------------------------------------------


def check_rc05(sf: SourceFile) -> Iterator[Finding]:
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.ExceptHandler) \
                and len(node.body) == 1 \
                and isinstance(node.body[0], ast.Pass):
            what = (ast.unparse(node.type)
                    if node.type is not None else "BaseException")
            yield Finding(
                "RC05", sf.relpath, node.lineno,
                f"`except {what}: pass` swallows the exception without "
                f"a trace — fault-injection failures become "
                f"unattributable; add a logger.debug(...) carrying "
                f"enough context (what was being attempted, on what "
                f"object/peer) or suppress with the reason the swallow "
                f"is safe")


# --------------------------------------------------------------------------
# registry
# --------------------------------------------------------------------------

_RULES = [
    Rule("RC01", "lock-held-blocking",
         _in_dirs("cluster", "core"), check_rc01),
    Rule("RC02", "wall-clock-deadline",
         _in_dirs("cluster", "core", "scheduler"), check_rc02),
    Rule("RC03", "unseeded-randomness",
         _in_dirs("cluster", "scheduler"), check_rc03),
    Rule("RC04", "mutation-token",
         lambda parts: parts[-1] == "gcs_server.py", check_rc04),
    Rule("RC05", "swallowed-exception",
         _in_dirs("cluster", "core"), check_rc05),
]


def all_rules() -> List[Rule]:
    return list(_RULES)
