"""Whole-program fact extraction — phase 1 of raycheck's RC06–RC09.

The per-file rules (RC01–RC05) check invariants a single AST can
witness. The wire-protocol and lock-order invariants cannot be seen
from one file: a ``client.call("actor_create", ...)`` site in
``process_cluster.py`` is only correct relative to the handler
registered in ``gcs_server.serve()`` and the ``@message`` schema in
``cluster/schema.py``, and a lock-order deadlock needs the acquisition
edges of *both* participating code paths. So the analysis is split:

* **Phase 1 (this module)** walks every parsed file once and extracts
  facts — :class:`CallSite`, :class:`Handler`, :class:`SchemaDef`,
  the inter-procedural lock-acquisition graph (:class:`LockEdge`), and
  :class:`ThreadSpawn` sites — into a :class:`Program`.
* **Phase 2** (the RC06–RC09 rules in :mod:`.rules`) joins facts across
  files and reports violations.

Analysis boundaries (deliberate, documented over-approximations):

* A ``.call("name", ...)`` site participates in the wire analysis only
  when it is *wire-shaped* (a literal method name and keyword-only
  arguments — the :class:`~ray_tpu.cluster.rpc.RpcClient` signature)
  AND the receiver's name looks like an RPC client (``gcs``,
  ``client``, ``peer``, ``hb``, ...). This keeps the serve
  ``ControllerRef.call(method, *args)`` actor surface and the
  process-pool pipe protocol (``worker.call("task", {...})``) out of
  the join.
* Lock identities are qualified per file and class
  (``cluster/gcs_server.py::GcsService._lock``); a
  ``threading.Condition(self._lock)`` aliases to its underlying lock.
  Call edges resolve module-locally (``self.method()`` and bare
  module functions); cross-module attribute calls are not followed —
  a cycle spanning that boundary needs a runtime detector, not this
  checker. Self-edges (re-acquiring the lock you hold) are ignored:
  the runtime's state locks are reentrant by convention (RLock /
  Condition), and reentrancy is not an ordering violation.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Set, Tuple

__all__ = [
    "CallSite",
    "FieldAccess",
    "Handler",
    "IncSite",
    "KnobDef",
    "LockEdge",
    "MetricDef",
    "Program",
    "ProtocolDecl",
    "SchemaDef",
    "SchemaField",
    "ThreadRoot",
    "ThreadSpawn",
    "TransitionDecl",
    "WaitSite",
    "type_compatible",
]


# --------------------------------------------------------------------------
# shared AST helpers (kept local: rules.py imports facts, not vice versa)
# --------------------------------------------------------------------------


def _terminal_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Call):
        return _terminal_name(node.func)
    return None


_FN_BOUNDARY = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                ast.ClassDef)


class _NotLiteral(Exception):
    """A protocols.py declaration field that is not a plain literal —
    the machine cannot be checked statically, which RC13 reports."""


# --------------------------------------------------------------------------
# wire facts: call sites, handlers, schemas
# --------------------------------------------------------------------------

WIRE_CALL_ATTRS = {"call", "call_async", "call_stream"}

# Receiver-name heuristic separating RPC-substrate clients from the
# other ``.call`` surfaces in the tree (serve's ControllerRef takes
# positional args; the process-pool pipe protocol passes a payload
# dict). Matched against the receiver expression's terminal name.
_WIRE_RECEIVER_RE = re.compile(
    r"gcs|client|peer|raylet|rpc|reap|^hb$|^c$|^srv$")

# kwargs consumed client-side before the frame is built (RpcClient.call
# signature); never part of the wire schema
CLIENT_KWARGS = frozenset({"timeout"})


@dataclass(frozen=True)
class CallSite:
    path: str
    line: int
    method: str
    kind: str              # "call" | "call_async" | "call_stream"
    keys: Tuple[str, ...]  # literal kwarg names (client kwargs included)
    splat: bool            # a **kwargs splat defeats field checks
    consts: Tuple[Tuple[str, str], ...]  # (kwarg, literal type name)
    receiver: str
    wire: bool             # wire-shaped AND wire-named receiver


@dataclass(frozen=True)
class Handler:
    path: str
    line: int
    method: str
    server: str            # "gcs_server.GcsService"-style owner label
    is_stream: bool
    resolved: bool         # signature was resolved to a function def
    required: Tuple[str, ...] = ()
    optional: Tuple[str, ...] = ()
    var_kw: bool = False


@dataclass(frozen=True)
class SchemaField:
    name: str
    line: int
    type: str
    required: bool


@dataclass(frozen=True)
class SchemaDef:
    path: str
    line: int
    method: str
    fields: Tuple[SchemaField, ...]

    def field_map(self) -> Dict[str, SchemaField]:
        return {f.name: f for f in self.fields}


@dataclass(frozen=True)
class ThreadSpawn:
    path: str
    line: int


# ---- raycheck v4 fact kinds (RC16–RC17) ----------------------------------


@dataclass(frozen=True, order=True)
class ThreadRoot:
    """One entry point from which a distinct thread of control starts:
    a ThreadRegistry ``spawn`` target, a ``threading.Thread(target=)``,
    or a registered RPC handler (dispatch-pool / reader-thread entry).
    ``label`` is the human root name — ``<stem>.<qualname>`` — shared
    with :meth:`~ray_tpu.cluster.threads.ThreadRegistry.roots` so RC16
    reports and the flight recorder name threads identically."""
    path: str
    line: int
    kind: str    # "registry-spawn" | "thread" | "handler"
    fid: str     # function id the root enters
    label: str


@dataclass(frozen=True)
class FieldAccess:
    """One read/write of ``self.<attr>`` (``cls`` set) or of a module
    global declared via ``global`` (``cls == ""``), annotated with the
    lockset held at the site: locks acquired locally plus the entry
    lockset flowed through the module-local call closure. Container
    mutations (``self.x[k] = v``, ``self.x.append(...)``) count as
    writes — rebind-only tracking misses most real races."""
    path: str
    cls: str
    attr: str
    line: int
    fid: str
    write: bool
    locks: frozenset


@dataclass(frozen=True, order=True)
class WaitSite:
    """One potentially-unbounded cross-thread wait: ``Condition.wait``
    / ``wait_for``, ``Event.wait``, ``Queue.get``, a zero-arg
    ``.join()``, or a raw socket ``recv`` outside the rpc framing
    layer. ``bounded`` records whether a timeout argument is present
    at the call site."""
    path: str
    line: int
    fid: str
    desc: str
    bounded: bool
    receiver: str


# ---- raycheck v3 fact kinds (RC12–RC15) ----------------------------------


@dataclass(frozen=True)
class KnobDef:
    """One annotated field of a ``Config`` dataclass in a file named
    ``config.py`` (underscore-prefixed internals excluded)."""
    path: str
    line: int
    name: str


@dataclass(frozen=True)
class MetricDef:
    """One module-level ``name = Counter|Gauge|Histogram(...)`` in a
    file named ``metrics.py``."""
    path: str
    line: int
    name: str
    kind: str          # "Counter" | "Gauge" | "Histogram"


@dataclass(frozen=True)
class IncSite:
    """One ``<receiver>.inc(...)`` call; ``receiver`` is the terminal
    name of the receiver expression (``metrics.tasks_shed`` →
    ``tasks_shed``)."""
    path: str
    line: int
    receiver: str


@dataclass(frozen=True)
class TransitionDecl:
    src: str
    dst: str
    driver: str
    kind: str
    escape: bool
    line: int


@dataclass(frozen=True)
class ProtocolDecl:
    """One literal ``Protocol(...)`` declaration re-extracted from a
    ``protocols.py`` AST. ``malformed`` carries a reason when the
    declaration is not statically analyzable (non-literal fields)."""
    path: str
    line: int
    name: str
    states: Tuple[str, ...] = ()
    initial: str = ""
    terminal: Tuple[str, ...] = ()
    transitions: Tuple[TransitionDecl, ...] = ()
    covers: Tuple[str, ...] = ()
    malformed: str = ""


@dataclass(frozen=True, order=True)
class LockEdge:
    """While holding ``src``, ``dst`` is (possibly transitively)
    acquired at ``path:line`` inside ``holder``; ``via`` names the
    callee chain entry point for inter-procedural edges ("" for a
    directly nested ``with``)."""
    src: str
    dst: str
    path: str
    line: int
    holder: str
    via: str


# literal-constant type vs schema annotation compatibility, mirroring
# schema._runtime_type's isinstance targets (bool is an int subclass;
# any buffer type is wire-equivalent to bytes)
_TYPE_OK = {
    "bytes": {"bytes", "bytearray", "memoryview"},
    "str": {"str"},
    "bool": {"bool"},
    "int": {"int", "bool"},
    "float": {"int", "float", "bool"},
    "dict": {"dict"}, "Dict": {"dict"},
    "list": {"list"}, "List": {"list"},
    "tuple": {"tuple"},
}


def type_compatible(annotation: str, literal_type: str) -> bool:
    """Would ``schema.validate`` accept a literal of ``literal_type``
    for a field annotated ``annotation``? Unknown annotations are
    unchecked at runtime, so they are compatible here too."""
    if literal_type == "NoneType":
        return True  # validate() skips None values
    ann = annotation.strip().strip("\"'")
    base = ann.split("[")[0].strip()
    if base == "Optional":
        inner = ann[ann.index("[") + 1:-1] if "[" in ann else ""
        return type_compatible(inner, literal_type)
    allowed = _TYPE_OK.get(base)
    return True if allowed is None else literal_type in allowed


# --------------------------------------------------------------------------
# per-file extraction
# --------------------------------------------------------------------------


def _signature(fn: ast.FunctionDef) -> Tuple[Tuple[str, ...],
                                             Tuple[str, ...], bool]:
    """(required, optional, has **kwargs) of a handler def, self
    stripped; a @token_deduped wrapper adds the reserved optional
    ``token`` kwarg it owns."""
    a = fn.args
    pos = list(getattr(a, "posonlyargs", [])) + list(a.args)
    if pos and pos[0].arg in ("self", "cls"):
        pos = pos[1:]
    n_opt = len(a.defaults)
    required = [p.arg for p in pos[:len(pos) - n_opt]]
    optional = [p.arg for p in pos[len(pos) - n_opt:]]
    for kw, default in zip(a.kwonlyargs, a.kw_defaults):
        (required if default is None else optional).append(kw.arg)
    if any(_terminal_name(d) == "token_deduped" for d in fn.decorator_list):
        optional.append("token")
    return tuple(required), tuple(optional), a.kwarg is not None


class _FileFacts(ast.NodeVisitor):
    """One pass over one file's AST collecting every fact kind."""

    def __init__(self, relpath: str, tree: ast.AST):
        self.relpath = relpath
        self.call_sites: List[CallSite] = []
        self.handlers: List[Handler] = []
        self.schemas: List[SchemaDef] = []
        self.thread_spawns: List[ThreadSpawn] = []
        # raycheck v3 facts
        self.knobs: List[KnobDef] = []
        self.metrics: List[MetricDef] = []
        self.inc_sites: List[IncSite] = []
        self.protocol_decls: List[ProtocolDecl] = []
        self.used_names: Set[str] = set()
        self.used_strings: Set[str] = set()
        # lock facts, resolved later by _LockAnalysis
        self._cls_stack: List[ast.ClassDef] = []
        self._methods: Dict[str, Dict[str, ast.FunctionDef]] = {}
        self.functions: Dict[str, Tuple[Optional[str], ast.FunctionDef]] = {}
        self.cond_aliases: Dict[Tuple[str, str], str] = {}
        # raycheck v4 raw facts, resolved later by _LockAnalysis:
        # (kind, owner_cls, target_kind, target_name, line)
        self.root_sites: List[
            Tuple[str, Optional[str], str, str, int]] = []
        # (cls, attr) -> ctor name for `self.X = Ctor(...)` assignments
        self.field_types: Dict[Tuple[str, str], str] = {}
        self.global_names: Set[str] = set()
        self._stem = relpath.rsplit("/", 1)[-1][:-3]
        for node in ast.iter_child_nodes(tree):
            if isinstance(node, ast.ClassDef):
                self._methods[node.name] = {
                    n.name: n for n in node.body
                    if isinstance(n, ast.FunctionDef)}
        self.visit(tree)
        if self._stem == "config":
            self._extract_knobs(tree)
        if self._stem == "metrics":
            self._extract_metrics(tree)
        if self._stem == "protocols":
            self._extract_protocols(tree)

    # -- structure tracking ----------------------------------------------
    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._cls_stack.append(node)
        self.generic_visit(node)
        self._cls_stack.pop()

    def _cur_cls(self) -> Optional[str]:
        return self._cls_stack[-1].name if self._cls_stack else None

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        cls = self._cur_cls()
        fid = (f"{self.relpath}::{cls}.{node.name}" if cls
               else f"{self.relpath}::{node.name}")
        # first def wins (nested defs under a method keep the method id)
        self.functions.setdefault(fid, (cls, node))
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    # -- fact collection ---------------------------------------------------
    def visit_Assign(self, node: ast.Assign) -> None:
        cls = self._cur_cls()
        if cls and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Attribute) \
                and isinstance(node.targets[0].value, ast.Name) \
                and node.targets[0].value.id == "self" \
                and isinstance(node.value, ast.Call):
            attr = node.targets[0].attr
            ctor = _terminal_name(node.value.func)
            # self.X = threading.Condition(self.Y): X aliases lock Y
            if ctor == "Condition" and node.value.args:
                underlying = node.value.args[0]
                if isinstance(underlying, ast.Attribute) \
                        and isinstance(underlying.value, ast.Name) \
                        and underlying.value.id == "self":
                    self.cond_aliases[(cls, attr)] = underlying.attr
            # self.X = Queue(...)/Event()/...: field type for the
            # race-escape and wait-receiver resolution (first ctor
            # assignment wins — __init__ is visited first)
            if ctor is not None:
                self.field_types.setdefault((cls, attr), ctor)
        self.generic_visit(node)

    def visit_Global(self, node: ast.Global) -> None:
        self.global_names.update(node.names)

    def visit_Call(self, node: ast.Call) -> None:
        self._maybe_call_site(node)
        self._maybe_register(node)
        self._maybe_thread(node)
        self._maybe_spawn(node)
        self._maybe_inc(node)
        self.generic_visit(node)

    # -- use sets (RC14/RC15 joins) ----------------------------------------
    def visit_Name(self, node: ast.Name) -> None:
        self.used_names.add(node.id)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        self.used_names.add(node.attr)
        self.generic_visit(node)

    def visit_Constant(self, node: ast.Constant) -> None:
        if isinstance(node.value, str):
            self.used_strings.add(node.value)

    def visit_For(self, node: ast.For) -> None:
        # the loop-registration idiom:
        #   for name in ("a", "b", ...):
        #       srv.register(name, getattr(self, name), ...)
        if isinstance(node.iter, (ast.Tuple, ast.List, ast.Set)) \
                and isinstance(node.target, ast.Name):
            registers = any(
                isinstance(c, ast.Call)
                and isinstance(c.func, ast.Attribute)
                and c.func.attr in ("register", "register_stream")
                and c.args and isinstance(c.args[0], ast.Name)
                and c.args[0].id == node.target.id
                for b in node.body for c in ast.walk(b))
            if registers:
                for elt in node.iter.elts:
                    if isinstance(elt, ast.Constant) \
                            and isinstance(elt.value, str):
                        self._add_handler(elt.value, elt.lineno,
                                          elt.value, is_stream=False)
        self.generic_visit(node)

    def _maybe_call_site(self, node: ast.Call) -> None:
        fn = node.func
        if not isinstance(fn, ast.Attribute) \
                or fn.attr not in WIRE_CALL_ATTRS:
            return
        if not (node.args and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            return
        receiver = _terminal_name(fn.value) or ""
        # RpcClient's surface is kwargs-only past the method name (plus
        # call_stream's on_chunk); positional extras mean a different
        # protocol that merely shares the attribute name. Non-wire
        # sites are still recorded (liberal input to the dead-handler
        # check) but excluded from the strict RC06/RC07 joins.
        allowed_pos = 2 if fn.attr == "call_stream" else 1
        wire = (len(node.args) <= allowed_pos
                and bool(_WIRE_RECEIVER_RE.search(receiver.lower())))
        keys, consts = [], []
        splat = False
        for kw in node.keywords:
            if kw.arg is None:
                splat = True
                continue
            keys.append(kw.arg)
            if isinstance(kw.value, ast.Constant):
                consts.append((kw.arg, type(kw.value.value).__name__))
        self.call_sites.append(CallSite(
            self.relpath, node.lineno, node.args[0].value, fn.attr,
            tuple(keys), splat, tuple(consts), receiver, wire))

    def _maybe_register(self, node: ast.Call) -> None:
        fn = node.func
        if not isinstance(fn, ast.Attribute) \
                or fn.attr not in ("register", "register_stream"):
            return
        if not (node.args and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            return
        target = None
        if len(node.args) > 1:
            expr = node.args[1]
            if isinstance(expr, ast.Attribute) \
                    and isinstance(expr.value, ast.Name) \
                    and expr.value.id == "self":
                target = expr.attr
            elif isinstance(expr, ast.Call) \
                    and _terminal_name(expr.func) == "getattr" \
                    and len(expr.args) == 2 \
                    and isinstance(expr.args[1], ast.Constant):
                target = expr.args[1].value
        self._add_handler(node.args[0].value, node.lineno, target,
                          is_stream=fn.attr == "register_stream")

    def _add_handler(self, method: str, line: int,
                     target: Optional[str], is_stream: bool) -> None:
        cls = self._cur_cls()
        server = f"{self._stem}.{cls}" if cls else self._stem
        # every registered handler is a thread root: the dispatch pool
        # (or a connection's reader thread, for inline handlers) runs it
        # concurrently with every other root
        if cls and (target or method):
            self.root_sites.append(
                ("handler", cls, "self", target or method, line))
        fndef = (self._methods.get(cls, {}).get(target)
                 if cls and target else None)
        if fndef is None:
            self.handlers.append(Handler(
                self.relpath, line, method, server, is_stream,
                resolved=False))
            return
        required, optional, var_kw = _signature(fndef)
        self.handlers.append(Handler(
            self.relpath, line, method, server, is_stream,
            resolved=True, required=required, optional=optional,
            var_kw=var_kw))

    def _target_desc(self, expr: Optional[ast.AST]) \
            -> Optional[Tuple[str, str]]:
        """A thread-entry expression as ("self", attr) / ("name", id);
        anything else (lambdas, partials, cross-object methods) is not
        module-locally resolvable and yields no root."""
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self":
            return ("self", expr.attr)
        if isinstance(expr, ast.Name):
            return ("name", expr.id)
        return None

    def _maybe_thread(self, node: ast.Call) -> None:
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr == "Thread" \
                and isinstance(fn.value, ast.Name) \
                and fn.value.id == "threading":
            self.thread_spawns.append(
                ThreadSpawn(self.relpath, node.lineno))
            target = next((kw.value for kw in node.keywords
                           if kw.arg == "target"), None)
            desc = self._target_desc(target)
            if desc is not None:
                self.root_sites.append(
                    ("thread", self._cur_cls(), desc[0], desc[1],
                     node.lineno))

    def _maybe_spawn(self, node: ast.Call) -> None:
        # <registry>.spawn(self._loop, "name", ...) — the ThreadRegistry
        # surface (cluster/threads.py); matched by attribute shape so
        # corpus fixtures don't need the real class
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr == "spawn" \
                and node.args:
            desc = self._target_desc(node.args[0])
            if desc is not None:
                self.root_sites.append(
                    ("registry-spawn", self._cur_cls(), desc[0],
                     desc[1], node.lineno))

    def _maybe_inc(self, node: ast.Call) -> None:
        # <metric>.inc(...) — receiver's terminal name joins against the
        # metrics registry in RC15
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr == "inc":
            receiver = _terminal_name(fn.value)
            if receiver is not None:
                self.inc_sites.append(
                    IncSite(self.relpath, node.lineno, receiver))

    # -- raycheck v3 declaration extraction --------------------------------
    def _extract_knobs(self, tree: ast.AST) -> None:
        for node in ast.iter_child_nodes(tree):
            if not (isinstance(node, ast.ClassDef)
                    and node.name == "Config"):
                continue
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) \
                        and isinstance(stmt.target, ast.Name) \
                        and not stmt.target.id.startswith("_"):
                    self.knobs.append(KnobDef(
                        self.relpath, stmt.lineno, stmt.target.id))

    _METRIC_CTORS = frozenset({"Counter", "Gauge", "Histogram"})

    def _extract_metrics(self, tree: ast.AST) -> None:
        for node in ast.iter_child_nodes(tree):
            if isinstance(node, ast.Assign) \
                    and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call):
                ctor = _terminal_name(node.value.func)
                if ctor in self._METRIC_CTORS:
                    self.metrics.append(MetricDef(
                        self.relpath, node.lineno,
                        node.targets[0].id, ctor))

    def _extract_protocols(self, tree: ast.AST) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) \
                    and _terminal_name(node.func) == "Protocol":
                self.protocol_decls.append(
                    self._parse_protocol(node))

    def _parse_protocol(self, call: ast.Call) -> ProtocolDecl:
        order = ("name", "states", "initial", "terminal",
                 "transitions", "covers")
        kw: Dict[str, ast.AST] = dict(zip(order, call.args))
        for k in call.keywords:
            if k.arg is not None:
                kw[k.arg] = k.value

        def _str(node: ast.AST) -> str:
            if isinstance(node, ast.Constant) \
                    and isinstance(node.value, str):
                return node.value
            raise _NotLiteral(node)

        def _strs(node: Optional[ast.AST]) -> Tuple[str, ...]:
            if node is None:
                return ()
            if not isinstance(node, (ast.Tuple, ast.List)):
                raise _NotLiteral(node)
            return tuple(_str(e) for e in node.elts)

        def _transition(node: ast.AST) -> TransitionDecl:
            if not (isinstance(node, ast.Call)
                    and _terminal_name(node.func) == "T"):
                raise _NotLiteral(node)
            t_order = ("src", "dst", "driver", "kind", "escape")
            t_kw: Dict[str, ast.AST] = dict(zip(t_order, node.args))
            for k in node.keywords:
                if k.arg is not None:
                    t_kw[k.arg] = k.value
            escape = False
            if "escape" in t_kw:
                e = t_kw["escape"]
                if not (isinstance(e, ast.Constant)
                        and isinstance(e.value, bool)):
                    raise _NotLiteral(e)
                escape = e.value
            kind = _str(t_kw["kind"]) if "kind" in t_kw else "wire"
            return TransitionDecl(
                _str(t_kw["src"]), _str(t_kw["dst"]),
                _str(t_kw["driver"]), kind, escape, node.lineno)

        try:
            trans_node = kw.get("transitions")
            if trans_node is not None \
                    and not isinstance(trans_node, (ast.Tuple, ast.List)):
                raise _NotLiteral(trans_node)
            return ProtocolDecl(
                self.relpath, call.lineno, _str(kw["name"]),
                states=_strs(kw.get("states")),
                initial=_str(kw["initial"]) if "initial" in kw else "",
                terminal=_strs(kw.get("terminal")),
                transitions=tuple(
                    _transition(e) for e in trans_node.elts)
                if trans_node is not None else (),
                covers=_strs(kw.get("covers")))
        except (_NotLiteral, KeyError) as e:
            name = ""
            node = kw.get("name")
            if isinstance(node, ast.Constant) \
                    and isinstance(node.value, str):
                name = node.value
            reason = ("missing required field"
                      if isinstance(e, KeyError)
                      else "non-literal field")
            return ProtocolDecl(self.relpath, call.lineno, name,
                                malformed=reason)

    def extract_schemas(self, tree: ast.AST) -> None:
        for node in ast.walk(tree):
            if not isinstance(node, ast.ClassDef):
                continue
            method = None
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call) \
                        and _terminal_name(dec.func) == "message" \
                        and dec.args \
                        and isinstance(dec.args[0], ast.Constant) \
                        and isinstance(dec.args[0].value, str):
                    method = dec.args[0].value
            if method is None:
                continue
            fields = []
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) \
                        and isinstance(stmt.target, ast.Name):
                    ann = ast.unparse(stmt.annotation).strip()
                    fields.append(SchemaField(
                        stmt.target.id, stmt.lineno, ann,
                        required=stmt.value is None))
            self.schemas.append(SchemaDef(
                self.relpath, node.lineno, method, tuple(fields)))


# --------------------------------------------------------------------------
# lock-order analysis
# --------------------------------------------------------------------------

# a with-item naming one of these is a lock acquisition; I/O-serializing
# locks (send_lock) participate too — they still order against state
# locks in a deadlock
_LOCK_NAME_RE = re.compile(r"(?:^|_)(?:lock|cv|cond|mutex)$")

# ---- raycheck v4 classification tables -----------------------------------

# synchronization-object constructors: fields holding one are a
# thread-safe handoff, not raceable shared state (RC16 escape), and
# the receiver types RC17 resolves wait methods against
_QUEUE_CTORS = frozenset({"Queue", "LifoQueue", "PriorityQueue",
                          "SimpleQueue"})
_WAITABLE_CTORS = frozenset({"Event", "Condition"})
SYNC_CTORS = frozenset({"Lock", "RLock", "Semaphore",
                        "BoundedSemaphore", "Barrier",
                        "ThreadRegistry"}) \
    | _QUEUE_CTORS | _WAITABLE_CTORS

# receiver names that read as a waitable even when the ctor assignment
# is out of reach (locals, parameters)
_WAITABLE_NAME_RE = re.compile(r"(?:^|_)(?:cv|cond|ev|event)$")

# method calls that mutate the container a field holds — counted as
# writes: rebind-only tracking misses the dict/deque races that matter
_MUTATOR_ATTRS = frozenset({
    "append", "appendleft", "extend", "extendleft", "insert",
    "pop", "popleft", "popitem", "remove", "discard", "clear",
    "update", "setdefault", "add"})

_SOCKET_RECV_ATTRS = frozenset({"recv", "recv_into", "recvfrom"})
_SOCKETISH_NAME_RE = re.compile(r"sock|conn")


def _root_label(fid: str) -> str:
    """``cluster/raylet_server.py::RayletServer._heartbeat_loop`` →
    ``raylet_server.RayletServer._heartbeat_loop`` — module stem plus
    qualname, the SAME derivation
    :func:`ray_tpu.cluster.threads.root_label` applies to a live
    callable, so static reports and runtime thread registries name
    roots identically."""
    path, qual = fid.rsplit("::", 1)
    stem = path.rsplit("/", 1)[-1][:-3]
    return f"{stem}.{qual}"


class _LockAnalysis:
    """Builds the inter-procedural acquisition graph for one scan,
    plus the raycheck-v4 concurrency facts layered on the same call
    resolution: thread roots with per-root reachability, field
    accesses annotated with flowed locksets, and wait sites."""

    def __init__(self, file_facts: List[_FileFacts]):
        self.edges: List[LockEdge] = []
        self._direct: Dict[str, Set[str]] = {}
        self._calls: Dict[str, Set[str]] = {}
        self._may: Dict[str, Set[str]] = {}
        # v4: per-callee [(caller, locks held at the call site)], raw
        # accesses/waits with their locally-held locksets, roots
        self._call_locks: Dict[str, List[Tuple[str, frozenset]]] = {}
        self._raw_accesses: List[
            Tuple[str, str, str, int, str, bool, frozenset]] = []
        self.wait_sites: List[WaitSite] = []
        self.roots: List[ThreadRoot] = []
        self.reach: Dict[str, Set[str]] = {}
        self.accesses: List[FieldAccess] = []
        self.field_types: Dict[Tuple[str, str, str], str] = {}
        for ff in file_facts:
            for (cls, attr), ctor in ff.field_types.items():
                self.field_types[(ff.relpath, cls, attr)] = ctor
            for fid, (cls, fndef) in ff.functions.items():
                self._direct[fid] = set()
                self._calls[fid] = set()
                self._scan_function(ff, fid, cls, fndef)
        for ff in file_facts:
            for fid, (cls, fndef) in ff.functions.items():
                self._scan_accesses(ff, fid, cls, fndef)
        self._fixpoint()
        for ff in file_facts:
            for fid, (cls, fndef) in ff.functions.items():
                self._emit_edges(ff, fid, cls, fndef)
        self._resolve_roots(file_facts)
        self._compute_reach()
        self._finalize_accesses()

    # -- helpers -----------------------------------------------------------
    def _lock_id(self, ff: _FileFacts, cls: Optional[str],
                 expr: ast.AST) -> Optional[str]:
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self" and cls is not None:
            name = ff.cond_aliases.get((cls, expr.attr), expr.attr)
            if _LOCK_NAME_RE.search(name.lower()):
                return f"{ff.relpath}::{cls}.{name}"
            return None
        name = _terminal_name(expr)
        if name is not None and not isinstance(expr, ast.Call) \
                and _LOCK_NAME_RE.search(name.lower()):
            return f"{ff.relpath}::{name}"
        return None

    def _callee(self, ff: _FileFacts, cls: Optional[str],
                node: ast.Call) -> Optional[str]:
        fn = node.func
        if isinstance(fn, ast.Attribute) \
                and isinstance(fn.value, ast.Name) \
                and fn.value.id == "self" and cls is not None:
            fid = f"{ff.relpath}::{cls}.{fn.attr}"
            return fid if fid in self._direct else None
        if isinstance(fn, ast.Name):
            fid = f"{ff.relpath}::{fn.id}"
            return fid if fid in self._direct else None
        return None

    # -- passes ------------------------------------------------------------
    def _scan_function(self, ff: _FileFacts, fid: str,
                       cls: Optional[str], fndef: ast.AST) -> None:
        for node in ast.walk(fndef):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    lock = self._lock_id(ff, cls, item.context_expr)
                    if lock is not None:
                        self._direct[fid].add(lock)
            elif isinstance(node, ast.Call):
                callee = self._callee(ff, cls, node)
                if callee is not None:
                    self._calls[fid].add(callee)

    def _fixpoint(self) -> None:
        self._may = {fid: set(locks)
                     for fid, locks in self._direct.items()}
        changed = True
        while changed:
            changed = False
            for fid, callees in self._calls.items():
                acc = self._may[fid]
                before = len(acc)
                for callee in callees:
                    acc |= self._may.get(callee, set())
                if len(acc) != before:
                    changed = True

    def _emit_edges(self, ff: _FileFacts, fid: str,
                    cls: Optional[str], fndef: ast.AST) -> None:
        for node in ast.walk(fndef):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            held = None
            for item in node.items:
                held = held or self._lock_id(ff, cls, item.context_expr)
            if held is None:
                continue
            for stmt in node.body:
                for child in _iter_with_body(stmt):
                    if isinstance(child, (ast.With, ast.AsyncWith)):
                        for item in child.items:
                            inner = self._lock_id(ff, cls,
                                                  item.context_expr)
                            if inner is not None and inner != held:
                                self.edges.append(LockEdge(
                                    held, inner, ff.relpath,
                                    child.lineno, fid, ""))
                    elif isinstance(child, ast.Call):
                        callee = self._callee(ff, cls, child)
                        if callee is None:
                            continue
                        for inner in sorted(self._may.get(callee, ())):
                            if inner != held:
                                self.edges.append(LockEdge(
                                    held, inner, ff.relpath,
                                    child.lineno, fid, callee))

    # -- raycheck v4 passes ------------------------------------------------
    def _scan_accesses(self, ff: _FileFacts, fid: str,
                       cls: Optional[str], fndef: ast.AST) -> None:
        """One pruned walk per function tracking the locally-held
        lockset: field/global accesses, wait sites, and call sites
        (with held locks, for the entry-lockset fixpoint)."""
        local_types: Dict[str, str] = {}

        def walk(node: ast.AST, held: frozenset) -> None:
            if isinstance(node, _FN_BOUNDARY):
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                inner = held
                for item in node.items:
                    walk(item.context_expr, held)
                    lock = self._lock_id(ff, cls, item.context_expr)
                    if lock is not None:
                        inner = inner | {lock}
                for b in node.body:
                    walk(b, inner)
                return
            self._record_events(ff, fid, cls, node, held, local_types)
            for child in ast.iter_child_nodes(node):
                walk(child, held)

        body = getattr(fndef, "body", [])
        for stmt in body:
            walk(stmt, frozenset())

    def _record_events(self, ff: _FileFacts, fid: str,
                       cls: Optional[str], node: ast.AST,
                       held: frozenset,
                       local_types: Dict[str, str]) -> None:
        if isinstance(node, ast.Assign) \
                and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call):
            ctor = _terminal_name(node.value.func)
            if ctor is not None:
                local_types[node.targets[0].id] = ctor
            return
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self" and cls is not None:
            write = isinstance(node.ctx, (ast.Store, ast.Del))
            self._raw_accesses.append(
                (ff.relpath, cls, node.attr, node.lineno, fid,
                 write, held))
            return
        if isinstance(node, ast.Name) and node.id in ff.global_names:
            write = isinstance(node.ctx, (ast.Store, ast.Del))
            self._raw_accesses.append(
                (ff.relpath, "", node.id, node.lineno, fid,
                 write, held))
            return
        if isinstance(node, ast.Subscript) \
                and isinstance(node.ctx, (ast.Store, ast.Del)):
            # self.x[k] = v / del self.x[k]: a container write
            tgt = node.value
            if isinstance(tgt, ast.Attribute) \
                    and isinstance(tgt.value, ast.Name) \
                    and tgt.value.id == "self" and cls is not None:
                self._raw_accesses.append(
                    (ff.relpath, cls, tgt.attr, node.lineno, fid,
                     True, held))
            return
        if isinstance(node, ast.Call):
            callee = self._callee(ff, cls, node)
            if callee is not None:
                self._call_locks.setdefault(callee, []).append(
                    (fid, held))
            self._maybe_mutator(ff, fid, cls, node, held)
            self._maybe_wait(ff, fid, cls, node, local_types)

    def _maybe_mutator(self, ff: _FileFacts, fid: str,
                       cls: Optional[str], node: ast.Call,
                       held: frozenset) -> None:
        fn = node.func
        if isinstance(fn, ast.Attribute) \
                and fn.attr in _MUTATOR_ATTRS \
                and isinstance(fn.value, ast.Attribute) \
                and isinstance(fn.value.value, ast.Name) \
                and fn.value.value.id == "self" and cls is not None:
            self._raw_accesses.append(
                (ff.relpath, cls, fn.value.attr, node.lineno, fid,
                 True, held))

    def _receiver_type(self, ff: _FileFacts, cls: Optional[str],
                       expr: ast.AST,
                       local_types: Dict[str, str]) -> Optional[str]:
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self" and cls is not None:
            if (cls, expr.attr) in ff.cond_aliases:
                return "Condition"
            return ff.field_types.get((cls, expr.attr))
        if isinstance(expr, ast.Name):
            return local_types.get(expr.id)
        return None

    def _maybe_wait(self, ff: _FileFacts, fid: str,
                    cls: Optional[str], node: ast.Call,
                    local_types: Dict[str, str]) -> None:
        fn = node.func
        if not isinstance(fn, ast.Attribute):
            return
        attr = fn.attr
        recv = _terminal_name(fn.value) or ""
        rtype = self._receiver_type(ff, cls, fn.value, local_types)
        timeout_kw = any(kw.arg in ("timeout", "timeout_s")
                         for kw in node.keywords)
        npos = len(node.args)
        desc = bounded = None
        if attr in ("wait", "wait_for"):
            waitable = (rtype in _WAITABLE_CTORS
                        or (rtype is None
                            and _WAITABLE_NAME_RE.search(recv.lower())))
            if not waitable:
                return
            desc = f"{rtype or 'Condition'}.{attr}"
            need_pos = 2 if attr == "wait_for" else 1
            bounded = timeout_kw or npos >= need_pos
        elif attr == "get":
            if rtype not in _QUEUE_CTORS:
                return
            desc = f"{rtype}.get"
            block_false = any(
                kw.arg == "block" and isinstance(kw.value, ast.Constant)
                and kw.value.value is False for kw in node.keywords)
            first_false = (npos >= 1
                           and isinstance(node.args[0], ast.Constant)
                           and node.args[0].value is False)
            bounded = (timeout_kw or block_false or first_false
                       or npos >= 2)
        elif attr == "join":
            if npos or node.keywords:
                return  # join(timeout) / str-join / path-join
            desc = ".join()"
            bounded = False
        elif attr in _SOCKET_RECV_ATTRS:
            # the rpc framing layer owns its socket deadlines
            # (Deadline-driven settimeout); raw recv anywhere else
            # must bound itself
            if ff.relpath.endswith("rpc.py") \
                    or not _SOCKETISH_NAME_RE.search(recv.lower()):
                return
            desc = f"socket .{attr}()"
            bounded = False
        if desc is not None:
            self.wait_sites.append(WaitSite(
                ff.relpath, node.lineno, fid, desc, bool(bounded),
                recv))

    def _resolve_roots(self, file_facts: List[_FileFacts]) -> None:
        seen: Set[Tuple[str, str, int]] = set()
        for ff in file_facts:
            for kind, cls0, tkind, name, line in ff.root_sites:
                if tkind == "self":
                    if not cls0:
                        continue
                    fid = f"{ff.relpath}::{cls0}.{name}"
                else:
                    fid = f"{ff.relpath}::{name}"
                if fid not in self._direct:
                    continue  # target not module-locally resolvable
                key = (fid, kind, line)
                if key in seen:
                    continue
                seen.add(key)
                self.roots.append(ThreadRoot(
                    ff.relpath, line, kind, fid, _root_label(fid)))
        self.roots.sort()

    def _compute_reach(self) -> None:
        for root in self.roots:
            stack = [root.fid]
            visited: Set[str] = set()
            while stack:
                f = stack.pop()
                if f in visited:
                    continue
                visited.add(f)
                self.reach.setdefault(f, set()).add(root.label)
                stack.extend(self._calls.get(f, ()))

    def _finalize_accesses(self) -> None:
        """Entry-lockset fixpoint (meet = intersection over call sites,
        roots enter with nothing held), then effective lockset =
        entry ∪ locally-held per access."""
        entry: Dict[str, Optional[frozenset]] = {
            fid: None for fid in self._direct}  # None = not-yet-known
        root_fids = {r.fid for r in self.roots}
        for f in root_fids:
            entry[f] = frozenset()
        changed = True
        while changed:
            changed = False
            for callee, sites in self._call_locks.items():
                contribs = [frozenset()] if callee in root_fids else []
                for caller, held in sites:
                    e = entry.get(caller)
                    if e is not None:
                        contribs.append(e | held)
                if not contribs:
                    continue
                new = frozenset.intersection(*contribs)
                if entry.get(callee) != new:
                    entry[callee] = new
                    changed = True
        self.entry_locks = entry
        for path, cls0, attr, line, fid, write, held in \
                self._raw_accesses:
            e = entry.get(fid)
            locks = held if e is None else (held | e)
            self.accesses.append(FieldAccess(
                path, cls0, attr, line, fid, write, locks))
        self.accesses.sort(
            key=lambda a: (a.path, a.cls, a.attr, a.line, a.fid,
                           a.write))
        self.wait_sites.sort()


def _iter_with_body(stmt: ast.stmt) -> Iterable[ast.AST]:
    """Walk a with-body including nested ``with`` blocks but pruned at
    deferred-execution boundaries."""
    stack: List[ast.AST] = [stmt]
    while stack:
        node = stack.pop()
        if isinstance(node, _FN_BOUNDARY):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _lock_cycles(edges: List[LockEdge]) -> List[List[LockEdge]]:
    """Strongly connected components of the acquisition graph with ≥ 2
    locks; each SCC is reported once, as the sorted list of its
    internal edges (deterministic output)."""
    graph: Dict[str, Set[str]] = {}
    for e in edges:
        graph.setdefault(e.src, set()).add(e.dst)
        graph.setdefault(e.dst, set())
    # Tarjan, iterative
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[Set[str]] = []
    counter = [0]

    def strongconnect(root: str) -> None:
        work = [(root, iter(sorted(graph[root])))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph[w]))))
                    advanced = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[v])
            if low[v] == index[v]:
                scc = set()
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.add(w)
                    if w == v:
                        break
                if len(scc) > 1:
                    sccs.append(scc)

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    cycles: List[List[LockEdge]] = []
    for scc in sccs:
        members = sorted(
            e for e in edges if e.src in scc and e.dst in scc)
        # dedupe identical (src,dst,holder) edges from repeated sites
        seen: Set[Tuple[str, str, str]] = set()
        unique: List[LockEdge] = []
        for e in sorted(members, key=lambda e: (e.src, e.dst, e.path,
                                                e.line)):
            key = (e.src, e.dst, e.holder)
            if key not in seen:
                seen.add(key)
                unique.append(e)
        cycles.append(unique)
    cycles.sort(key=lambda es: (es[0].src, es[0].dst))
    return cycles


# --------------------------------------------------------------------------
# the joined program
# --------------------------------------------------------------------------


class Program:
    """All facts of one scan, extracted once and shared by every
    program rule (the AST cache: each file is parsed and walked a
    single time regardless of how many rules consume the facts)."""

    def __init__(self, files, root: Optional[str] = None) -> None:
        # files: List[SourceFile]; root: scan root on disk, used by the
        # hygiene rules (RC14) for README/tests lookups next to the tree
        self.root = root
        self.call_sites: List[CallSite] = []
        self.handlers: List[Handler] = []
        self.schemas: List[SchemaDef] = []
        self.thread_spawns: List[ThreadSpawn] = []
        self.knobs: List[KnobDef] = []
        self.metrics: List[MetricDef] = []
        self.inc_sites: List[IncSite] = []
        self.protocol_decls: List[ProtocolDecl] = []
        self.used_names_by_path: Dict[str, Set[str]] = {}
        self.used_strings_by_path: Dict[str, Set[str]] = {}
        self.file_functions: Dict[
            str, Dict[str, Tuple[Optional[str], ast.AST]]] = {}
        lock_facts: List[_FileFacts] = []
        for sf in files:
            ff = _FileFacts(sf.relpath, sf.tree)
            ff.extract_schemas(sf.tree)
            self.call_sites.extend(ff.call_sites)
            self.handlers.extend(ff.handlers)
            self.schemas.extend(ff.schemas)
            self.knobs.extend(ff.knobs)
            self.metrics.extend(ff.metrics)
            self.inc_sites.extend(ff.inc_sites)
            self.protocol_decls.extend(ff.protocol_decls)
            self.used_names_by_path[sf.relpath] = ff.used_names
            self.used_strings_by_path[sf.relpath] = ff.used_strings
            self.file_functions[sf.relpath] = dict(ff.functions)
            parts = sf.relpath.split("/")
            if {"cluster", "core"}.intersection(parts[:-1]):
                self.thread_spawns.extend(ff.thread_spawns)
                lock_facts.append(ff)
        analysis = _LockAnalysis(lock_facts)
        self.lock_edges: List[LockEdge] = analysis.edges
        self.lock_cycles: List[List[LockEdge]] = _lock_cycles(
            self.lock_edges)
        # raycheck v4 concurrency facts (same cluster/+core/ scope as
        # the lock graph they extend)
        self.thread_roots: List[ThreadRoot] = analysis.roots
        self.field_accesses: List[FieldAccess] = analysis.accesses
        self.wait_sites: List[WaitSite] = analysis.wait_sites
        self.root_reach: Dict[str, Set[str]] = analysis.reach
        self.field_types: Dict[Tuple[str, str, str], str] = \
            analysis.field_types

    # -- joined views ------------------------------------------------------
    def handler_map(self) -> Dict[str, List[Handler]]:
        out: Dict[str, List[Handler]] = {}
        for h in self.handlers:
            out.setdefault(h.method, []).append(h)
        return out

    def schema_map(self) -> Dict[str, SchemaDef]:
        return {s.method: s for s in self.schemas}

    def called_methods(self) -> Set[str]:
        """Every literal method name at any ``.call``-family site —
        liberal on purpose: the dead-handler check must not flag a
        handler reached through an unusually named client."""
        return {cs.method for cs in self.call_sites}

    def wire_call_sites(self) -> List[CallSite]:
        return [cs for cs in self.call_sites if cs.wire]

    def function_names(self) -> Set[str]:
        """Simple names of every function/method defined anywhere in the
        scan (``cluster/gcs_server.py::GcsService._mark_node_dead`` →
        ``_mark_node_dead``) — the resolution target for RC13's
        internal-driver edges."""
        out: Set[str] = set()
        for fns in self.file_functions.values():
            for fid in fns:
                out.add(fid.rsplit("::", 1)[-1].rsplit(".", 1)[-1])
        return out

    def names_used_outside(self, *exclude_stems: str) -> Set[str]:
        """Union of identifier uses over every file whose basename stem
        is NOT in ``exclude_stems`` (RC14: knob read outside config.py;
        RC15: metric used outside metrics.py)."""
        out: Set[str] = set()
        for path, names in self.used_names_by_path.items():
            stem = path.rsplit("/", 1)[-1][:-3]
            if stem not in exclude_stems:
                out |= names
        return out
