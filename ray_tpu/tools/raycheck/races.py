"""RC16/RC17 — whole-program data-race and unbounded-blocking rules.

RacerD-style guarded-by inference over the phase-1 concurrency facts
(:mod:`.facts`): thread roots (ThreadRegistry spawns, raw
``threading.Thread`` targets, registered RPC handlers), the functions
each root transitively reaches through the module-local call graph,
and every instance-field / declared-global access annotated with the
lockset held at the site (locally acquired locks plus the entry
lockset flowed through the intra-class call closure).

**RC16** infers each field's candidate guard — the most common lock
over its write sites — and fires when the field is accessed from ≥ 2
distinct thread roots, at least one access is a write, and some
conflicting pair of accesses shares no lock. Precision escapes, each
a deliberate under-approximation:

* init-before-spawn: ``__init__`` writes (and any access in code no
  thread root reaches — main-thread setup) don't participate;
* immutable-after-publish: fields never written outside ``__init__``
  can't race;
* handoff objects: fields holding a Queue/Event/Condition/Lock are
  internally synchronized, and lock-named fields are the guards
  themselves;
* single-rooted fields: all accesses reached by one root are
  serialized by construction (same-root self-races are out of scope —
  the report names a root *pair*).

**RC17** fires on any potentially-forever wait reachable from a thread
root — ``Condition.wait()``/``wait_for()``, ``Event.wait()``,
``Queue.get()``, a zero-arg ``.join()``, raw socket ``recv`` outside
the rpc framing layer — that passes no timeout argument. A hung peer
must cost a bounded wait plus a retry decision, never a wedged daemon
thread (the reference's timeout-everywhere RPC discipline).
"""

from __future__ import annotations

from collections import Counter
from typing import Dict, Iterator, List, Tuple

from ray_tpu.tools.raycheck import Finding
from ray_tpu.tools.raycheck.facts import (FieldAccess, SYNC_CTORS,
                                          _LOCK_NAME_RE)

__all__ = ["check_rc16", "check_rc17"]


def _fmt_roots(labels) -> str:
    labels = sorted(labels)
    if len(labels) > 3:
        labels = labels[:3] + [f"+{len(labels) - 3} more"]
    return ", ".join(labels)


def check_rc16(program) -> Iterator[Finding]:
    reach = program.root_reach
    by_field: Dict[Tuple[str, str, str], List[FieldAccess]] = {}
    for a in program.field_accesses:
        by_field.setdefault((a.path, a.cls, a.attr), []).append(a)
    for key in sorted(by_field):
        path, cls, attr = key
        # the guard itself, or a synchronized handoff object, is not
        # raceable shared state
        if _LOCK_NAME_RE.search(attr.lower()):
            continue
        if program.field_types.get(key) in SYNC_CTORS:
            continue
        accs = by_field[key]
        post = [a for a in accs if not a.fid.endswith(".__init__")]
        if not any(a.write for a in post):
            continue  # immutable after publish
        # only accesses some thread root actually reaches participate;
        # main-thread setup (serve() before its spawns) drops out here
        rooted = [(a, frozenset(reach.get(a.fid, ()))) for a in post
                  if reach.get(a.fid)]
        all_roots = frozenset().union(*(r for _, r in rooted)) \
            if rooted else frozenset()
        if len(all_roots) < 2:
            continue  # single-rooted: serialized by construction
        writes = [(a, r) for a, r in rooted if a.write]
        if not writes:
            continue
        # candidate guard: majority lock over rooted write sites
        tally: Counter = Counter()
        for a, _ in writes:
            tally.update(a.locks)
        candidate = min((lock for lock, n in tally.items()
                         if n == max(tally.values())), default=None) \
            if tally else None
        # conflict: a write and another access, from provably-distinct
        # roots, with disjoint locksets
        conflict = None
        for a, ra in sorted(writes, key=lambda p: (p[0].line,
                                                   p[0].fid)):
            for b, rb in rooted:
                if a is b:
                    continue
                if ra == rb and len(ra) == 1:
                    continue  # same single root: serialized
                if a.locks & b.locks:
                    continue  # a common lock orders the pair
                conflict = (a, ra, b, rb)
                break
            if conflict:
                break
        if conflict is None:
            continue
        a, ra, b, rb = conflict
        # report at the access MISSING the candidate guard: when the
        # write is correctly locked the defect is the bare access on
        # the other side, and the finding should point there
        if (candidate is not None and candidate in a.locks
                and candidate not in b.locks):
            a, ra, b, rb = b, rb, a, ra
        field = f"{cls}.{attr}" if cls else f"global {attr}"
        other = (f"{b.path}:{b.line}"
                 if b.path != a.path else f"line {b.line}")
        guard_hint = (
            f"hold '{candidate}' at every access"
            if candidate is not None else
            "no write site holds any lock — introduce one")
        verb_a = "written" if a.write else "read"
        verb_b = "written" if b.write else "accessed"
        yield Finding(
            "RC16", a.path, a.line,
            f"data race on '{field}': {verb_a} here from thread "
            f"root(s) [{_fmt_roots(ra)}] and {verb_b} at {other} "
            f"from [{_fmt_roots(rb - ra or rb)}] with no common "
            f"lock (candidate guard: {guard_hint}), or move the "
            f"write before the first spawn, or hand the value off "
            f"through a Queue/Event")


def check_rc17(program) -> Iterator[Finding]:
    reach = program.root_reach
    for w in program.wait_sites:
        if w.bounded:
            continue
        roots = reach.get(w.fid)
        if not roots:
            continue  # not reachable from any server/loop root
        yield Finding(
            "RC17", w.path, w.line,
            f"unbounded blocking: {w.desc} on '{w.receiver}' can "
            f"wait forever on thread root(s) "
            f"[{_fmt_roots(roots)}] — pass a timeout= (a Config "
            f"knob, not a magic number) and handle expiry, or use "
            f"the _nowait/poll form")
