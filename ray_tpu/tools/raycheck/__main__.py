"""CLI: ``python -m ray_tpu.tools.raycheck [paths...]``.

With no paths, scans the installed ``ray_tpu`` package. Exit status 0
means no unsuppressed, non-baselined findings; 1 means findings were
printed; 2 means usage error. ``--json`` emits a machine-readable
report (one object: findings + counts) for CI; ``--sarif PATH``
additionally writes a SARIF 2.1.0 log (the CI-archival interchange
format code-scanning UIs ingest); ``--update-baseline`` rewrites the
baseline file from the current findings so the grandfathering workflow
is mechanical instead of hand-edited."""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from ray_tpu.tools import raycheck
from ray_tpu.tools.raycheck import rules as _rules


def to_sarif(findings) -> dict:
    """One SARIF 2.1.0 run: the rule table as reportingDescriptors,
    each finding as a result with a physical location. Paths are kept
    scan-root-relative (uriBaseId REPOROOT) so the log is stable across
    checkouts — the property the round-trip test pins."""
    by_code = {}
    for rule in _rules.all_rules():
        by_code[rule.code] = {
            "id": rule.code,
            "name": rule.title,
            "shortDescription": {"text": rule.title},
            "properties": {
                "scope": "program" if rule.program else "per-file"},
        }
    # RC00 (file does not parse) is synthesized by the loader, not the
    # rule table
    by_code.setdefault("RC00", {
        "id": "RC00", "name": "parse-error",
        "shortDescription": {"text": "file does not parse"},
        "properties": {"scope": "per-file"}})
    results = []
    for f in findings:
        results.append({
            "ruleId": f.code,
            "level": "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": f.path,
                        "uriBaseId": "REPOROOT"},
                    "region": {"startLine": f.line},
                },
            }],
            "partialFingerprints": {"raycheckKey": f.key},
        })
    return {
        "$schema": ("https://json.schemastore.org/sarif-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "raycheck",
                "informationUri":
                    "https://example.invalid/ray_tpu/tools/raycheck",
                "rules": [by_code[c] for c in sorted(by_code)],
            }},
            "results": results,
            "columnKind": "utf16CodeUnits",
        }],
    }


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.tools.raycheck",
        description="repo-specific static analysis: concurrency, "
                    "determinism, wire-protocol, lifecycle, hygiene "
                    "& data-race invariants (RC01..RC17; RC06-RC09 "
                    "and RC12-RC17 are whole-program)")
    parser.add_argument(
        "paths", nargs="*",
        help="files or directories to scan (default: the ray_tpu "
             "package)")
    parser.add_argument(
        "--baseline", default=None,
        help="baseline file of grandfathered finding keys "
             "(default: the shipped — empty — baseline.txt)")
    parser.add_argument(
        "--rules", default=None,
        help="comma-separated rule codes to run (default: all)")
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="print a machine-readable report (findings + counts) "
             "instead of human-oriented lines")
    parser.add_argument(
        "--sarif", default=None, metavar="PATH",
        help="also write a SARIF 2.1.0 report to PATH (\"-\" for "
             "stdout) — the machine format CI archives and "
             "code-scanning UIs ingest")
    parser.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the baseline file with the current unsuppressed "
             "finding keys (then exit 0); entries are debt, the "
             "shipped baseline is pinned empty by test")
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule table and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in _rules.all_rules():
            kind = "program " if rule.program else "per-file"
            print(f"{rule.code}  {kind}  {rule.title}")
        return 0

    selected = (args.rules.upper().split(",")
                if args.rules else None)
    paths = args.paths
    if not paths:
        import ray_tpu

        paths = [os.path.dirname(os.path.abspath(ray_tpu.__file__))]

    findings = []
    # timing breakdown accumulated across scan roots: fact-extraction
    # seconds plus per-rule wall time, surfaced by --json (and by
    # check.sh when the scan overruns its budget)
    timings: dict = {}
    for path in paths:
        if not os.path.exists(path):
            print(f"raycheck: no such path: {path}", file=sys.stderr)
            return 2
        t: dict = {}
        findings.extend(raycheck.check_tree(path, rules=selected,
                                            timings=t))
        for k, v in t.items():
            timings[k] = round(timings.get(k, 0.0) + v, 4)

    if args.update_baseline:
        out = raycheck.save_baseline(
            (f.key for f in findings), args.baseline)
        print(f"raycheck: baseline updated with {len(findings)} "
              f"key(s): {out}")
        return 0

    baseline = raycheck.load_baseline(args.baseline)
    fresh = [f for f in findings if f.key not in baseline]
    baselined = len(findings) - len(fresh)
    if args.sarif:
        doc = json.dumps(to_sarif(fresh), indent=2)
        if args.sarif == "-":
            print(doc)
        else:
            with open(args.sarif, "w", encoding="utf-8") as f:
                f.write(doc + "\n")
    if args.as_json:
        print(json.dumps({
            "findings": [f.to_dict() for f in fresh],
            "count": len(fresh),
            "baselined": baselined,
            "clean": not fresh,
            "timings_s": timings,
        }, indent=2))
        return 1 if fresh else 0
    for finding in fresh:
        print(finding.render())
    tail = f" ({baselined} baselined)" if baselined else ""
    if fresh:
        print(f"raycheck: {len(fresh)} finding(s){tail}")
        return 1
    print(f"raycheck: clean{tail}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
