"""CLI: ``python -m ray_tpu.tools.raycheck [paths...]``.

With no paths, scans the installed ``ray_tpu`` package. Exit status 0
means no unsuppressed, non-baselined findings; 1 means findings were
printed; 2 means usage error. ``--json`` emits a machine-readable
report (one object: findings + counts) for CI; ``--update-baseline``
rewrites the baseline file from the current findings so the
grandfathering workflow is mechanical instead of hand-edited."""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from ray_tpu.tools import raycheck
from ray_tpu.tools.raycheck import rules as _rules


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.tools.raycheck",
        description="repo-specific static analysis: concurrency, "
                    "determinism & wire-protocol invariants "
                    "(RC01..RC10; RC06-RC09 are whole-program)")
    parser.add_argument(
        "paths", nargs="*",
        help="files or directories to scan (default: the ray_tpu "
             "package)")
    parser.add_argument(
        "--baseline", default=None,
        help="baseline file of grandfathered finding keys "
             "(default: the shipped — empty — baseline.txt)")
    parser.add_argument(
        "--rules", default=None,
        help="comma-separated rule codes to run (default: all)")
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="print a machine-readable report (findings + counts) "
             "instead of human-oriented lines")
    parser.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the baseline file with the current unsuppressed "
             "finding keys (then exit 0); entries are debt, the "
             "shipped baseline is pinned empty by test")
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule table and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in _rules.all_rules():
            kind = "program " if rule.program else "per-file"
            print(f"{rule.code}  {kind}  {rule.title}")
        return 0

    selected = (args.rules.upper().split(",")
                if args.rules else None)
    paths = args.paths
    if not paths:
        import ray_tpu

        paths = [os.path.dirname(os.path.abspath(ray_tpu.__file__))]

    findings = []
    for path in paths:
        if not os.path.exists(path):
            print(f"raycheck: no such path: {path}", file=sys.stderr)
            return 2
        findings.extend(raycheck.check_tree(path, rules=selected))

    if args.update_baseline:
        out = raycheck.save_baseline(
            (f.key for f in findings), args.baseline)
        print(f"raycheck: baseline updated with {len(findings)} "
              f"key(s): {out}")
        return 0

    baseline = raycheck.load_baseline(args.baseline)
    fresh = [f for f in findings if f.key not in baseline]
    baselined = len(findings) - len(fresh)
    if args.as_json:
        print(json.dumps({
            "findings": [f.to_dict() for f in fresh],
            "count": len(fresh),
            "baselined": baselined,
            "clean": not fresh,
        }, indent=2))
        return 1 if fresh else 0
    for finding in fresh:
        print(finding.render())
    tail = f" ({baselined} baselined)" if baselined else ""
    if fresh:
        print(f"raycheck: {len(fresh)} finding(s){tail}")
        return 1
    print(f"raycheck: clean{tail}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
