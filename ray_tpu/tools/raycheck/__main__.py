"""CLI: ``python -m ray_tpu.tools.raycheck [paths...]``.

With no paths, scans the installed ``ray_tpu`` package. Exit status 0
means no unsuppressed, non-baselined findings; 1 means findings were
printed; 2 means usage error."""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from ray_tpu.tools import raycheck
from ray_tpu.tools.raycheck import rules as _rules


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.tools.raycheck",
        description="repo-specific static analysis: concurrency & "
                    "determinism invariants (RC01..RC05)")
    parser.add_argument(
        "paths", nargs="*",
        help="files or directories to scan (default: the ray_tpu "
             "package)")
    parser.add_argument(
        "--baseline", default=None,
        help="baseline file of grandfathered finding keys "
             "(default: the shipped — empty — baseline.txt)")
    parser.add_argument(
        "--rules", default=None,
        help="comma-separated rule codes to run (default: all)")
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule table and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in _rules.all_rules():
            print(f"{rule.code}  {rule.title}")
        return 0

    selected = (args.rules.upper().split(",")
                if args.rules else None)
    paths = args.paths
    if not paths:
        import ray_tpu

        paths = [os.path.dirname(os.path.abspath(ray_tpu.__file__))]

    findings = []
    for path in paths:
        if not os.path.exists(path):
            print(f"raycheck: no such path: {path}", file=sys.stderr)
            return 2
        findings.extend(raycheck.check_tree(path, rules=selected))

    baseline = raycheck.load_baseline(args.baseline)
    fresh = [f for f in findings if f.key not in baseline]
    for finding in fresh:
        print(finding.render())
    baselined = len(findings) - len(fresh)
    tail = f" ({baselined} baselined)" if baselined else ""
    if fresh:
        print(f"raycheck: {len(fresh)} finding(s){tail}")
        return 1
    print(f"raycheck: clean{tail}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
