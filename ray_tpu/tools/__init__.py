"""Developer tooling shipped with the repo (reference: the ``ci/``
tree — custom lint, sanitizer drivers — that gates merges on
repo-specific invariants rather than generic style)."""
