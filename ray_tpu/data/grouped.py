"""GroupedDataset + aggregate functions.

Reference: python/ray/data/grouped_dataset.py (AggregateFn protocol with
init/accumulate/merge/finalize; groupby is a hash-shuffle of rows to
per-key partitions followed by parallel per-partition aggregation).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Union

import ray_tpu
from ray_tpu.data.block import BlockAccessor, build_output_block


@dataclass
class AggregateFn:
    init: Callable[[Any], Any]
    accumulate: Callable[[Any, Any], Any]
    merge: Callable[[Any, Any], Any]
    finalize: Callable[[Any], Any]
    name: str = "agg"


def _on_fn(on: Union[str, Callable, None]) -> Callable:
    if on is None:
        return lambda r: r
    if callable(on):
        return on
    return lambda r: r[on]


def Count() -> AggregateFn:
    return AggregateFn(lambda k: 0, lambda a, r: a + 1, lambda a, b: a + b,
                       lambda a: a, "count()")


def Sum(on=None) -> AggregateFn:
    f = _on_fn(on)
    return AggregateFn(lambda k: 0, lambda a, r: a + f(r),
                       lambda a, b: a + b, lambda a: a, f"sum({on})")


def Min(on=None) -> AggregateFn:
    f = _on_fn(on)
    return AggregateFn(lambda k: None,
                       lambda a, r: f(r) if a is None else min(a, f(r)),
                       lambda a, b: b if a is None else
                       (a if b is None else min(a, b)),
                       lambda a: a, f"min({on})")


def Max(on=None) -> AggregateFn:
    f = _on_fn(on)
    return AggregateFn(lambda k: None,
                       lambda a, r: f(r) if a is None else max(a, f(r)),
                       lambda a, b: b if a is None else
                       (a if b is None else max(a, b)),
                       lambda a: a, f"max({on})")


def Mean(on=None) -> AggregateFn:
    f = _on_fn(on)
    return AggregateFn(lambda k: (0.0, 0),
                       lambda a, r: (a[0] + f(r), a[1] + 1),
                       lambda a, b: (a[0] + b[0], a[1] + b[1]),
                       lambda a: a[0] / a[1] if a[1] else None,
                       f"mean({on})")


def Std(on=None, ddof: int = 1) -> AggregateFn:
    f = _on_fn(on)

    def _finalize(a):
        s, s2, n = a
        if n <= ddof:
            return None
        var = (s2 - s * s / n) / (n - ddof)
        return max(var, 0.0) ** 0.5

    return AggregateFn(lambda k: (0.0, 0.0, 0),
                       lambda a, r: (a[0] + f(r), a[1] + f(r) ** 2, a[2] + 1),
                       lambda a, b: (a[0] + b[0], a[1] + b[1], a[2] + b[2]),
                       _finalize, f"std({on})")


class GroupedDataset:
    def __init__(self, dataset, key: Union[str, Callable, None]):
        self._dataset = dataset
        self._key = key

    def _key_fn(self) -> Callable:
        key = self._key
        if key is None:
            return lambda r: None
        if callable(key):
            return key
        return lambda r: r[key]

    def aggregate(self, *aggs: AggregateFn):
        """Hash-partition rows by key across tasks, aggregate partitions in
        parallel, merge on the driver."""
        from ray_tpu.data.dataset import Dataset

        kf = self._key_fn()
        nparts = max(self._dataset.num_blocks(), 1)

        @ray_tpu.remote(num_returns=max(nparts, 1))
        def partition(block):
            parts: List[list] = [[] for _ in range(nparts)]
            for r in BlockAccessor.for_block(block).iter_rows():
                parts[hash(kf(r)) % nparts].append(r)
            out = [build_output_block(p) for p in parts]
            return out if nparts > 1 else out[0]

        @ray_tpu.remote
        def agg_partition(*parts):
            states: dict = {}
            for p in parts:
                for r in BlockAccessor.for_block(p).iter_rows():
                    k = kf(r)
                    if k not in states:
                        states[k] = [a.init(k) for a in aggs]
                    st = states[k]
                    for i, a in enumerate(aggs):
                        st[i] = a.accumulate(st[i], r)
            return states

        map_out = [partition.remote(ref)
                   for ref in self._dataset.get_internal_block_refs()]
        if nparts == 1:
            map_out = [[m] for m in map_out]
        part_states = ray_tpu.get([
            agg_partition.remote(*[m[j] for m in map_out])
            for j in range(nparts)])
        merged: dict = {}
        for states in part_states:
            for k, st in states.items():
                if k not in merged:
                    merged[k] = st
                else:
                    merged[k] = [a.merge(x, y) for a, x, y in
                                 zip(aggs, merged[k], st)]
        rows = []
        for k in sorted(merged.keys(), key=lambda x: (x is None, x)):
            finals = [a.finalize(s) for a, s in zip(aggs, merged[k])]
            if isinstance(self._key, str):
                row = {self._key: k}
                for a, v in zip(aggs, finals):
                    row[a.name] = v
                rows.append(row)
            elif len(aggs) == 1:
                rows.append((k, finals[0]) if self._key is not None
                            else finals[0])
            else:
                rows.append((k, *finals))
        block = build_output_block(rows)
        meta = BlockAccessor.for_block(block).get_metadata()
        return Dataset([ray_tpu.put(block)], [meta])

    def count(self):
        return self.aggregate(Count())

    def sum(self, on=None):
        return self.aggregate(Sum(on))

    def min(self, on=None):
        return self.aggregate(Min(on))

    def max(self, on=None):
        return self.aggregate(Max(on))

    def mean(self, on=None):
        return self.aggregate(Mean(on))

    def std(self, on=None, ddof: int = 1):
        return self.aggregate(Std(on, ddof))

    def map_groups(self, fn: Callable[[List[Any]], Any]):
        """Apply fn to the full row list of each group."""
        from ray_tpu.data.dataset import Dataset

        kf = self._key_fn()
        groups: dict = {}
        for r in self._dataset.iter_rows():
            groups.setdefault(kf(r), []).append(r)

        @ray_tpu.remote
        def apply(rows):
            out = fn(rows)
            return out if isinstance(out, list) else [out]

        results = ray_tpu.get([apply.remote(v) for _, v in
                               sorted(groups.items(),
                                      key=lambda kv: (kv[0] is None, kv[0]))])
        rows = [r for rs in results for r in rs]
        block = build_output_block(rows)
        meta = BlockAccessor.for_block(block).get_metadata()
        return Dataset([ray_tpu.put(block)], [meta])
