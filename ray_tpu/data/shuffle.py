"""Push-based two-stage shuffle (reference: python/ray/data/impl/shuffle.py).

Stage 1 (map): each input block is split into ``num_out`` sub-blocks by
hash (repartition) or uniform-random assignment (random_shuffle).
Stage 2 (reduce): each output block concatenates its sub-blocks from
every mapper and, for random_shuffle, permutes rows locally.

Both stages are stateless tasks, so the object store carries all
intermediate data — this path is the object-store stressor the reference
uses for its nightly shuffle benchmarks (release/nightly_tests/shuffle/).
"""

from __future__ import annotations

import random
from typing import List, Optional, Tuple

import ray_tpu
from ray_tpu.data.block import (
    Block,
    BlockAccessor,
    BlockMetadata,
    build_output_block,
)


def shuffle_blocks(block_refs: List["ray_tpu.ObjectRef"], num_out: int,
                   randomize: bool, seed: Optional[int] = None
                   ) -> Tuple[List["ray_tpu.ObjectRef"],
                              List[BlockMetadata]]:
    if not block_refs:
        return [], []

    @ray_tpu.remote(num_returns=num_out)
    def shuffle_map(block: Block, map_idx: int):
        acc = BlockAccessor.for_block(block)
        rows = list(acc.iter_rows())
        if randomize:
            rng = random.Random(None if seed is None else seed + map_idx)
            rng.shuffle(rows)
            parts = [rows[i::num_out] for i in range(num_out)]
        else:
            per = (len(rows) + num_out - 1) // max(num_out, 1)
            parts = [rows[i * per:(i + 1) * per] for i in range(num_out)]
        out = [build_output_block(p) for p in parts]
        return out if num_out > 1 else out[0]

    @ray_tpu.remote(num_returns=2)
    def shuffle_reduce(reduce_idx: int, *parts: Block):
        rows: list = []
        for p in parts:
            rows.extend(BlockAccessor.for_block(p).iter_rows())
        if randomize:
            rng = random.Random(None if seed is None else seed * 31 +
                                reduce_idx)
            rng.shuffle(rows)
        block = build_output_block(rows)
        return block, BlockAccessor.for_block(block).get_metadata()

    map_out = [shuffle_map.remote(ref, i)
               for i, ref in enumerate(block_refs)]
    if num_out == 1:
        map_out = [[r] if not isinstance(r, list) else r for r in map_out]
    out_refs, meta_refs = [], []
    for j in range(num_out):
        parts = [m[j] for m in map_out]
        b, meta = shuffle_reduce.remote(j, *parts)
        out_refs.append(b)
        meta_refs.append(meta)
    metas = ray_tpu.get(meta_refs)
    return out_refs, metas
