"""Per-stage dataset statistics (reference: python/ray/data/impl/stats.py)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional


@dataclass
class StageStats:
    name: str
    wall_time_s: float
    num_blocks: int
    num_rows: int
    size_bytes: int


@dataclass
class DatasetStats:
    stages: List[StageStats] = field(default_factory=list)

    def child(self, name: str, wall_time_s: float, metas) -> "DatasetStats":
        rows = sum((m.num_rows or 0) for m in metas if m)
        size = sum((m.size_bytes or 0) for m in metas if m)
        new = DatasetStats(list(self.stages))
        new.stages.append(StageStats(name, wall_time_s, len(metas), rows,
                                     size))
        return new

    def summary(self) -> str:
        lines = []
        for s in self.stages:
            lines.append(
                f"Stage {s.name}: {s.num_blocks} blocks, {s.num_rows} rows, "
                f"{s.size_bytes} bytes, {s.wall_time_s * 1e3:.2f}ms")
        return "\n".join(lines) or "(no stages executed)"
