"""Dataset creation + IO (reference: python/ray/data/read_api.py).

Reads fan out as one task per file/partition; each task returns a block
into the object store. Formats: parquet/csv/json/text/binary/numpy via
pyarrow+pandas (both baked in; gated imports all the same).
"""

from __future__ import annotations

import builtins
import glob as _glob
import json as _json
import os
from typing import Any, Callable, List, Optional, Union

import numpy as np

import ray_tpu
from ray_tpu.data.block import (
    BlockAccessor,
    BlockMetadata,
    build_output_block,
)
from ray_tpu.data.dataset import Dataset

try:
    import pyarrow as pa
except Exception:  # pragma: no cover
    pa = None
try:
    import pandas as pd
except Exception:  # pragma: no cover
    pd = None


def _make_dataset(blocks: List[Any],
                  input_files: Optional[List[str]] = None) -> Dataset:
    refs, metas = [], []
    for b in blocks:
        refs.append(ray_tpu.put(b))
        metas.append(BlockAccessor.for_block(b).get_metadata(input_files))
    return Dataset(refs, metas)


def from_items(items: List[Any], *, parallelism: int = 8) -> Dataset:
    n = max(1, min(parallelism, len(items) or 1))
    per = (len(items) + n - 1) // n
    blocks = [build_output_block(items[i * per:(i + 1) * per])
              for i in builtins.range(n)]
    return _make_dataset([b for b in blocks
                          if BlockAccessor.for_block(b).num_rows() or n == 1])


def range(n: int, *, parallelism: int = 8) -> Dataset:  # noqa: A001


    per = (n + parallelism - 1) // max(parallelism, 1)
    blocks = [list(builtins.range(i, min(i + per, n)))
              for i in builtins.range(0, n, per)] or [[]]
    return _make_dataset(blocks)


def range_table(n: int, *, parallelism: int = 8) -> Dataset:


    per = (n + parallelism - 1) // max(parallelism, 1)
    blocks = []
    for i in builtins.range(0, n, per):
        vals = np.arange(i, min(i + per, n))
        blocks.append(pa.table({"value": pa.array(vals)}))
    return _make_dataset(blocks or [pa.table({"value": pa.array([])})])


def from_numpy(arrays: Union[np.ndarray, List[np.ndarray]]) -> Dataset:
    if isinstance(arrays, np.ndarray):
        arrays = [arrays]
    blocks = [pa.table({"value": pa.array(list(a))}) for a in arrays]
    return _make_dataset(blocks)


def from_pandas(dfs: Union["pd.DataFrame", List["pd.DataFrame"]]) -> Dataset:
    if pd is not None and isinstance(dfs, pd.DataFrame):
        dfs = [dfs]
    blocks = [pa.Table.from_pandas(df, preserve_index=False) for df in dfs]
    return _make_dataset(blocks)


def from_arrow(tables: Union["pa.Table", List["pa.Table"]]) -> Dataset:
    if pa is not None and isinstance(tables, pa.Table):
        tables = [tables]
    return _make_dataset(list(tables))


def _expand_paths(paths: Union[str, List[str]]) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(
                os.path.join(p, f) for f in os.listdir(p)
                if not f.startswith(".")))
        elif any(c in p for c in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    return out


def _read_files(paths, read_one: Callable[[str], Any]) -> Dataset:
    files = _expand_paths(paths)
    if not files:
        raise FileNotFoundError(f"no input files at {paths}")

    @ray_tpu.remote(num_returns=2)
    def _read(path: str):
        block = read_one(path)
        return block, BlockAccessor.for_block(block).get_metadata([path])

    refs, meta_refs = [], []
    for f in files:
        b, m = _read.remote(f)
        refs.append(b)
        meta_refs.append(m)
    return Dataset(refs, ray_tpu.get(meta_refs))


def read_parquet(paths, **kwargs) -> Dataset:
    import pyarrow.parquet as pq

    return _read_files(paths, lambda p: pq.read_table(p, **kwargs))


def read_csv(paths, **kwargs) -> Dataset:
    from pyarrow import csv as pa_csv

    return _read_files(paths, lambda p: pa_csv.read_csv(p, **kwargs))


def read_json(paths, **kwargs) -> Dataset:
    def _read_one(p: str):
        rows = []
        with open(p) as f:
            for line in f:
                line = line.strip()
                if line:
                    rows.append(_json.loads(line))
        return build_output_block(rows)

    return _read_files(paths, _read_one)


def read_text(paths, *, encoding: str = "utf-8") -> Dataset:
    def _read_one(p: str):
        with open(p, encoding=encoding) as f:
            return [ln.rstrip("\n") for ln in f]

    return _read_files(paths, _read_one)


def read_binary_files(paths) -> Dataset:
    def _read_one(p: str):
        with open(p, "rb") as f:
            return [f.read()]

    return _read_files(paths, _read_one)


def read_numpy(paths) -> Dataset:
    def _read_one(p: str):
        arr = np.load(p)
        return pa.table({"value": pa.array(list(arr))})

    return _read_files(paths, _read_one)


# --------------------------------------------------------------------- write
def _write_blocks(block_refs, path: str, fmt: str) -> None:
    os.makedirs(path, exist_ok=True)

    @ray_tpu.remote
    def _write(block, out_path: str):
        acc = BlockAccessor.for_block(block)
        if fmt == "parquet":
            import pyarrow.parquet as pq

            pq.write_table(acc.to_arrow(), out_path)
        elif fmt == "csv":
            acc.to_pandas().to_csv(out_path, index=False)
        elif fmt == "json":
            with open(out_path, "w") as f:
                for row in acc.iter_rows():
                    f.write(_json.dumps(_jsonable(row)) + "\n")
        return out_path

    ext = {"parquet": "parquet", "csv": "csv", "json": "json"}[fmt]
    ray_tpu.get([
        _write.remote(ref, os.path.join(path, f"part-{i:05d}.{ext}"))
        for i, ref in enumerate(block_refs)])


def _jsonable(row: Any) -> Any:
    if isinstance(row, dict):
        return {k: _jsonable(v) for k, v in row.items()}
    if isinstance(row, (np.integer,)):
        return int(row)
    if isinstance(row, (np.floating,)):
        return float(row)
    if isinstance(row, np.ndarray):
        return row.tolist()
    return row
