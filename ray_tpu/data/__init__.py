"""ray_tpu.data — distributed datasets on the object store.

Reference surface: python/ray/data/__init__.py (Dataset, read_* creation
APIs, GroupedDataset aggregates, DatasetPipeline).
"""

from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata  # noqa: F401
from ray_tpu.data.compute import ActorPoolStrategy, TaskPoolStrategy  # noqa: F401
from ray_tpu.data.dataset import Dataset  # noqa: F401
from ray_tpu.data.grouped import (  # noqa: F401
    AggregateFn,
    Count,
    GroupedDataset,
    Max,
    Mean,
    Min,
    Std,
    Sum,
)
from ray_tpu.data.pipeline import DatasetPipeline  # noqa: F401
from ray_tpu.data.read_api import (  # noqa: F401
    from_arrow,
    from_items,
    from_numpy,
    from_pandas,
    range,
    range_table,
    read_binary_files,
    read_csv,
    read_json,
    read_numpy,
    read_parquet,
    read_text,
)

__all__ = [
    "Dataset", "DatasetPipeline", "GroupedDataset", "AggregateFn",
    "BlockAccessor", "BlockMetadata", "Block",
    "ActorPoolStrategy", "TaskPoolStrategy",
    "Count", "Sum", "Min", "Max", "Mean", "Std",
    "from_items", "from_numpy", "from_pandas", "from_arrow",
    "range", "range_table",
    "read_parquet", "read_csv", "read_json", "read_text",
    "read_binary_files", "read_numpy",
]
