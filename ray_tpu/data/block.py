"""Blocks — the unit of distributed data.

A Dataset is a list of ObjectRefs to *blocks* (reference:
python/ray/data/impl/block_list.py, arrow_block.py, simple_block.py).
Two physical layouts:

  - **list blocks**: plain Python lists of rows (the reference's
    SimpleBlock) — universal fallback.
  - **table blocks**: pyarrow.Table (the reference's ArrowBlock) — used
    for structured data; zero-copy to numpy columns, which is the path
    that feeds jax.device_put for TPU training.

``BlockAccessor.for_block`` dispatches on the physical type, exactly like
the reference's ``BlockAccessor.for_block`` (python/ray/data/block.py).
"""

from __future__ import annotations

import random
import sys
from dataclasses import dataclass
from typing import Any, Callable, Iterator, List, Optional, Tuple, Union

import numpy as np

try:  # gated: table blocks need pyarrow
    import pyarrow as pa
except Exception:  # pragma: no cover
    pa = None

try:
    import pandas as pd
except Exception:  # pragma: no cover
    pd = None

Block = Union[list, "pa.Table"]


@dataclass
class BlockMetadata:
    """Sidecar stats carried next to every block ref (reference:
    python/ray/data/block.py BlockMetadata)."""
    num_rows: Optional[int]
    size_bytes: Optional[int]
    schema: Optional[Any] = None
    input_files: Optional[List[str]] = None
    # node that produced the block (reference: block locations feed
    # dataset.py:735's locality-aware split); None = location unknown
    node_id: Optional[str] = None


class BlockAccessor:
    """Uniform view over a physical block."""

    @staticmethod
    def for_block(block: Block) -> "BlockAccessor":
        if pa is not None and isinstance(block, pa.Table):
            return ArrowBlockAccessor(block)
        if pd is not None and isinstance(block, pd.DataFrame):
            return ArrowBlockAccessor(pa.Table.from_pandas(block))
        if isinstance(block, (list, tuple)):
            return SimpleBlockAccessor(list(block))
        if isinstance(block, np.ndarray):
            return ArrowBlockAccessor(
                pa.table({"value": pa.array(list(block))}))
        raise TypeError(f"not a block type: {type(block)}")

    @staticmethod
    def builder_for(block: Block) -> "BlockBuilder":
        if pa is not None and isinstance(block, pa.Table):
            return ArrowBlockBuilder()
        return SimpleBlockBuilder()

    # --- interface -------------------------------------------------------
    def num_rows(self) -> int:
        raise NotImplementedError

    def size_bytes(self) -> int:
        raise NotImplementedError

    def iter_rows(self) -> Iterator[Any]:
        raise NotImplementedError

    def slice(self, start: int, end: int) -> Block:
        raise NotImplementedError

    def take(self, indices: List[int]) -> Block:
        raise NotImplementedError

    def to_pandas(self):
        raise NotImplementedError

    def to_numpy(self, column: Optional[str] = None):
        raise NotImplementedError

    def to_arrow(self):
        raise NotImplementedError

    def to_batch(self, batch_format: str):
        """Materialize in the caller-requested format ('native', 'pandas',
        'pyarrow', 'numpy')."""
        if batch_format in ("native", "default"):
            return self.to_native()
        if batch_format == "pandas":
            return self.to_pandas()
        if batch_format == "pyarrow":
            return self.to_arrow()
        if batch_format == "numpy":
            return self.to_numpy()
        raise ValueError(f"unknown batch_format: {batch_format}")

    def to_native(self) -> Block:
        raise NotImplementedError

    def schema(self) -> Any:
        raise NotImplementedError

    def sample(self, n: int, key: Optional[Callable] = None) -> List[Any]:
        rows = list(self.iter_rows())
        if not rows:
            return []
        picks = random.sample(rows, min(n, len(rows)))
        return [key(r) if key else r for r in picks]

    def get_metadata(self, input_files: Optional[List[str]] = None
                     ) -> BlockMetadata:
        return BlockMetadata(self.num_rows(), self.size_bytes(),
                             self.schema(), input_files)


# =========================================================================
class SimpleBlockAccessor(BlockAccessor):
    def __init__(self, block: list):
        self._block = block

    def num_rows(self) -> int:
        return len(self._block)

    def size_bytes(self) -> int:
        return sum(sys.getsizeof(r) for r in self._block)

    def iter_rows(self) -> Iterator[Any]:
        return iter(self._block)

    def slice(self, start: int, end: int) -> Block:
        return self._block[start:end]

    def take(self, indices: List[int]) -> Block:
        return [self._block[i] for i in indices]

    def to_pandas(self):
        return pd.DataFrame({"value": self._block})

    def to_numpy(self, column: Optional[str] = None):
        return np.array(self._block)

    def to_arrow(self):
        return pa.table({"value": pa.array(self._block)})

    def to_native(self) -> Block:
        return self._block

    def schema(self) -> Any:
        return type(self._block[0]) if self._block else None


class ArrowBlockAccessor(BlockAccessor):
    def __init__(self, table: "pa.Table"):
        self._table = table

    def num_rows(self) -> int:
        return self._table.num_rows

    def size_bytes(self) -> int:
        return self._table.nbytes

    def iter_rows(self) -> Iterator[dict]:
        for batch in self._table.to_batches():
            cols = {name: batch.column(i)
                    for i, name in enumerate(batch.schema.names)}
            for i in range(batch.num_rows):
                yield {n: c[i].as_py() for n, c in cols.items()}

    def slice(self, start: int, end: int) -> Block:
        return self._table.slice(start, end - start)

    def take(self, indices: List[int]) -> Block:
        return self._table.take(pa.array(indices, type=pa.int64()))

    def to_pandas(self):
        return self._table.to_pandas()

    def to_numpy(self, column: Optional[str] = None):
        if column is not None:
            return self._table.column(column).to_numpy(zero_copy_only=False)
        return {n: self._table.column(n).to_numpy(zero_copy_only=False)
                for n in self._table.schema.names}

    def to_arrow(self):
        return self._table

    def to_native(self) -> Block:
        return self._table

    def schema(self) -> Any:
        return self._table.schema


# =========================================================================
class BlockBuilder:
    def add(self, row: Any) -> None:
        raise NotImplementedError

    def add_block(self, block: Block) -> None:
        raise NotImplementedError

    def num_rows(self) -> int:
        raise NotImplementedError

    def build(self) -> Block:
        raise NotImplementedError


class SimpleBlockBuilder(BlockBuilder):
    def __init__(self):
        self._rows: list = []

    def add(self, row: Any) -> None:
        self._rows.append(row)

    def add_block(self, block: Block) -> None:
        self._rows.extend(BlockAccessor.for_block(block).iter_rows())

    def num_rows(self) -> int:
        return len(self._rows)

    def build(self) -> Block:
        return self._rows


class ArrowBlockBuilder(BlockBuilder):
    def __init__(self):
        self._tables: List["pa.Table"] = []
        self._rows: List[dict] = []

    def add(self, row: Any) -> None:
        if not isinstance(row, dict):
            row = {"value": row}
        self._rows.append(row)

    def add_block(self, block: Block) -> None:
        if pa is not None and isinstance(block, pa.Table):
            self._tables.append(block)
        else:
            for r in BlockAccessor.for_block(block).iter_rows():
                self.add(r)

    def num_rows(self) -> int:
        return (sum(t.num_rows for t in self._tables) + len(self._rows))

    def build(self) -> Block:
        tables = list(self._tables)
        if self._rows:
            cols = {k: [r.get(k) for r in self._rows]
                    for k in self._rows[0].keys()}
            tables.append(pa.table(cols))
        if not tables:
            return pa.table({})
        if len(tables) == 1:
            return tables[0]
        return pa.concat_tables(tables, promote_options="default")


def build_output_block(rows: List[Any]) -> Block:
    """Pick the physical layout from the row type, like the reference's
    DelegatingArrowBlockBuilder (python/ray/data/impl/arrow_block.py)."""
    if rows and isinstance(rows[0], dict) and pa is not None:
        b = ArrowBlockBuilder()
        for r in rows:
            b.add(r)
        return b.build()
    return list(rows)


def batch_to_block(batch: Any) -> Block:
    """Normalize a user map_batches return value to a block."""
    if pa is not None and isinstance(batch, pa.Table):
        return batch
    if pd is not None and isinstance(batch, pd.DataFrame):
        return pa.Table.from_pandas(batch, preserve_index=False)
    if isinstance(batch, np.ndarray):
        return pa.table({"value": pa.array(list(batch))})
    if isinstance(batch, dict):
        return pa.table({k: pa.array(np.asarray(v)) for k, v in batch.items()})
    if isinstance(batch, list):
        return build_output_block(batch)
    raise TypeError(f"cannot convert batch of type {type(batch)} to a block")
