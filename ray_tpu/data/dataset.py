"""Dataset — distributed data as a list of block ObjectRefs.

Reference: python/ray/data/dataset.py:90. Every transform ships a
block-level function to stateless tasks (or an actor pool), producing a
new Dataset; nothing is materialized on the driver until take()/to_*.

TPU-first additions over the reference surface:
  - ``iter_batches(batch_format="numpy")`` feeds zero-copy numpy columns,
  - ``to_jax(...)`` yields ready-to-device jnp batches (and can shard
    them over a Mesh axis for data-parallel input pipelines).
"""

from __future__ import annotations

import itertools
import math
import random
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple, Union

import numpy as np

import ray_tpu
from ray_tpu.data.block import (
    Block,
    BlockAccessor,
    BlockMetadata,
    batch_to_block,
    build_output_block,
)
from ray_tpu.data.compute import get_compute
from ray_tpu.data.stats import DatasetStats


class Dataset:
    def __init__(self, block_refs: List["ray_tpu.ObjectRef"],
                 metadata: Optional[List[BlockMetadata]] = None,
                 stats: Optional[DatasetStats] = None):
        self._blocks = list(block_refs)
        self._metadata = list(metadata) if metadata is not None else [
            None] * len(self._blocks)
        self._stats = stats or DatasetStats()

    # ------------------------------------------------------------ plumbing
    def _ensure_metadata(self) -> List[BlockMetadata]:
        missing = [i for i, m in enumerate(self._metadata) if m is None]
        if missing:
            blocks = ray_tpu.get([self._blocks[i] for i in missing])
            for i, b in zip(missing, blocks):
                self._metadata[i] = BlockAccessor.for_block(b).get_metadata()
        return self._metadata

    def get_internal_block_refs(self) -> List["ray_tpu.ObjectRef"]:
        return list(self._blocks)

    def num_blocks(self) -> int:
        return len(self._blocks)

    def size_bytes(self) -> Optional[int]:
        metas = self._ensure_metadata()
        sizes = [m.size_bytes for m in metas if m and m.size_bytes is not None]
        return sum(sizes) if sizes else None

    def schema(self) -> Any:
        for m in self._metadata:
            if m is not None and m.schema is not None:
                return m.schema
        if not self._blocks:
            return None
        block = ray_tpu.get([self._blocks[0]])[0]
        return BlockAccessor.for_block(block).schema()

    def stats(self) -> str:
        return self._stats.summary()

    def _map_block_fn(self, name: str, fn: Callable[[Block], Block],
                      compute=None, **remote_args) -> "Dataset":
        t0 = time.perf_counter()
        strategy = get_compute(compute)
        refs, metas = strategy.apply(fn, remote_args, self._blocks)
        stats = self._stats.child(name, time.perf_counter() - t0, metas)
        return Dataset(refs, metas, stats)

    # ---------------------------------------------------------- transforms
    def map(self, fn: Callable[[Any], Any], *, compute=None,
            **remote_args) -> "Dataset":
        def _map(block: Block) -> Block:
            acc = BlockAccessor.for_block(block)
            return build_output_block([fn(r) for r in acc.iter_rows()])
        return self._map_block_fn("map", _map, compute, **remote_args)

    def flat_map(self, fn: Callable[[Any], List[Any]], *, compute=None,
                 **remote_args) -> "Dataset":
        def _fmap(block: Block) -> Block:
            acc = BlockAccessor.for_block(block)
            out: List[Any] = []
            for r in acc.iter_rows():
                out.extend(fn(r))
            return build_output_block(out)
        return self._map_block_fn("flat_map", _fmap, compute, **remote_args)

    def filter(self, fn: Callable[[Any], bool], *, compute=None,
               **remote_args) -> "Dataset":
        def _filter(block: Block) -> Block:
            acc = BlockAccessor.for_block(block)
            rows = [r for r in acc.iter_rows() if fn(r)]
            if not rows:
                builder = BlockAccessor.builder_for(block)
                return builder.build()
            return build_output_block(rows)
        return self._map_block_fn("filter", _filter, compute, **remote_args)

    # -- column ops (reference: data/dataset.py add_column /
    # drop_columns / select_columns over pandas batches) ---------------
    @staticmethod
    def _column_op_frame(block: Block):
        """Block -> DataFrame for the column ops, or None for empty
        SCHEMALESS blocks (an emptied list block, or the zero-column
        Arrow table `pa.table({})` that filter() builds, has no columns
        to transform; an empty Arrow block WITH a schema still goes
        through the op so schema() stays consistent)."""
        acc = BlockAccessor.for_block(block)
        if acc.num_rows() == 0:
            if isinstance(block, list):
                return None
            df = acc.to_pandas()
            return None if df.shape[1] == 0 else df
        return acc.to_pandas()

    def add_column(self, col: str, fn: Callable[[Any], Any], *,
                   compute=None, **remote_args) -> "Dataset":
        """fn receives each block as a pandas DataFrame and returns the
        new column's values."""
        from ray_tpu.data.block import batch_to_block

        def _add(block: Block) -> Block:
            df = self._column_op_frame(block)
            if df is None:
                return block
            df[col] = fn(df)
            return batch_to_block(df)
        return self._map_block_fn("add_column", _add, compute,
                                  **remote_args)

    def drop_columns(self, cols: List[str], *, compute=None,
                     **remote_args) -> "Dataset":
        from ray_tpu.data.block import batch_to_block

        def _drop(block: Block) -> Block:
            df = self._column_op_frame(block)
            if df is None:
                return block
            return batch_to_block(df.drop(columns=list(cols)))
        return self._map_block_fn("drop_columns", _drop, compute,
                                  **remote_args)

    def select_columns(self, cols: List[str], *, compute=None,
                       **remote_args) -> "Dataset":
        from ray_tpu.data.block import batch_to_block

        def _select(block: Block) -> Block:
            df = self._column_op_frame(block)
            if df is None:
                return block
            return batch_to_block(df[list(cols)])
        return self._map_block_fn("select_columns", _select, compute,
                                  **remote_args)

    def map_batches(self, fn: Callable[[Any], Any], *,
                    batch_size: Optional[int] = None,
                    batch_format: str = "native", compute=None,
                    **remote_args) -> "Dataset":
        def _map_batches(block: Block) -> Block:
            acc = BlockAccessor.for_block(block)
            n = acc.num_rows()
            size = batch_size or max(n, 1)
            outs = []
            for start in range(0, max(n, 1), size):
                if n == 0:
                    break
                sub = BlockAccessor.for_block(
                    acc.slice(start, min(start + size, n)))
                result = fn(sub.to_batch(batch_format))
                outs.append(batch_to_block(result))
            if not outs:
                return block
            builder = BlockAccessor.builder_for(outs[0])
            for o in outs:
                builder.add_block(o)
            return builder.build()
        return self._map_block_fn("map_batches", _map_batches, compute,
                                  **remote_args)

    # -------------------------------------------------------- restructure
    def repartition(self, num_blocks: int, *, shuffle: bool = False
                    ) -> "Dataset":
        if shuffle:
            from ray_tpu.data.shuffle import shuffle_blocks
            refs, metas = shuffle_blocks(self._blocks, num_blocks,
                                         randomize=False)
            return Dataset(refs, metas,
                           self._stats.child("repartition", 0.0, metas))
        total = self.count()
        per = math.ceil(total / max(num_blocks, 1)) if total else 0

        rows_iter = self.iter_rows()
        blocks: List[Block] = []
        for _ in range(num_blocks):
            chunk = list(itertools.islice(rows_iter, per)) if per else []
            blocks.append(build_output_block(chunk))
        refs = [ray_tpu.put(b) for b in blocks]
        metas = [BlockAccessor.for_block(b).get_metadata() for b in blocks]
        return Dataset(refs, metas,
                       self._stats.child("repartition", 0.0, metas))

    def random_shuffle(self, *, seed: Optional[int] = None,
                       num_blocks: Optional[int] = None) -> "Dataset":
        from ray_tpu.data.shuffle import shuffle_blocks
        t0 = time.perf_counter()
        refs, metas = shuffle_blocks(
            self._blocks, num_blocks or len(self._blocks) or 1,
            randomize=True, seed=seed)
        return Dataset(refs, metas, self._stats.child(
            "random_shuffle", time.perf_counter() - t0, metas))

    def sort(self, key: Optional[Union[str, Callable]] = None,
             descending: bool = False) -> "Dataset":
        from ray_tpu.data.sort import sort_blocks
        t0 = time.perf_counter()
        refs, metas = sort_blocks(self._blocks, key, descending)
        return Dataset(refs, metas, self._stats.child(
            "sort", time.perf_counter() - t0, metas))

    def groupby(self, key: Optional[Union[str, Callable]]) -> "GroupedDataset":
        from ray_tpu.data.grouped import GroupedDataset
        return GroupedDataset(self, key)

    def zip(self, other: "Dataset") -> "Dataset":
        rows_a = list(self.iter_rows())
        rows_b = list(other.iter_rows())
        if len(rows_a) != len(rows_b):
            raise ValueError("zip requires datasets of equal length")
        out = []
        for a, b in zip(rows_a, rows_b):
            if isinstance(a, dict) and isinstance(b, dict):
                merged = dict(a)
                for k, v in b.items():
                    merged[k if k not in merged else f"{k}_1"] = v
                out.append(merged)
            else:
                out.append((a, b))
        block = build_output_block(out)
        meta = BlockAccessor.for_block(block).get_metadata()
        return Dataset([ray_tpu.put(block)], [meta],
                       self._stats.child("zip", 0.0, [meta]))

    def union(self, *others: "Dataset") -> "Dataset":
        refs = list(self._blocks)
        metas = list(self._metadata)
        for o in others:
            refs.extend(o._blocks)
            metas.extend(o._metadata)
        return Dataset(refs, metas, self._stats.child("union", 0.0, []))

    def limit(self, limit: int) -> "Dataset":
        metas = self._ensure_metadata()
        refs, out_metas, taken = [], [], 0
        for ref, meta in zip(self._blocks, metas):
            if taken >= limit:
                break
            n = meta.num_rows or 0
            if taken + n <= limit:
                refs.append(ref)
                out_metas.append(meta)
                taken += n
            else:
                block = ray_tpu.get([ref])[0]
                acc = BlockAccessor.for_block(block)
                cut = acc.slice(0, limit - taken)
                refs.append(ray_tpu.put(cut))
                out_metas.append(BlockAccessor.for_block(cut).get_metadata())
                taken = limit
        return Dataset(refs, out_metas, self._stats.child("limit", 0.0,
                                                          out_metas))

    def split(self, n: int, *, equal: bool = False,
              locality_hints: Optional[List[Any]] = None
              ) -> List["Dataset"]:
        """Split into n sub-datasets by whole blocks (reference:
        dataset.py:514). With ``locality_hints`` (one actor handle per
        output split), blocks are assigned to the split whose actor
        lives on the block's producing node (block metadata carries
        node_id), balanced so no split exceeds ceil(blocks/n) —
        reference dataset.py:735's locality-aware assignment."""
        if n <= 0:
            raise ValueError("n must be positive")
        if equal and locality_hints is not None:
            raise ValueError(
                "equal=True re-chunks rows into fresh driver-side "
                "blocks, so locality_hints cannot be honored; pass one "
                "or the other (reference rejects the combination too)")
        if equal:
            total = self.count()
            per = total // n
            rows_iter = self.iter_rows()
            out = []
            for i in range(n):
                chunk = list(itertools.islice(rows_iter, per))
                block = build_output_block(chunk)
                meta = BlockAccessor.for_block(block).get_metadata()
                out.append(Dataset([ray_tpu.put(block)], [meta]))
            return out
        metas = self._ensure_metadata()
        shards: List[Tuple[List, List]] = [([], []) for _ in range(n)]
        if locality_hints is not None:
            if len(locality_hints) != n:
                raise ValueError(
                    f"len(locality_hints)={len(locality_hints)} != n={n}")
            return self._split_with_locality(n, metas, locality_hints)
        for i, (ref, meta) in enumerate(zip(self._blocks, metas)):
            shards[i % n][0].append(ref)
            shards[i % n][1].append(meta)
        return [Dataset(refs, ms) for refs, ms in shards]

    def _split_with_locality(self, n: int, metas,
                             locality_hints: List[Any]) -> List["Dataset"]:
        """Greedy locality assignment: each block goes to a split whose
        hint actor sits on the block's producing node if one still has
        room (cap ceil(blocks/n), so locality never unbalances the
        shards); leftovers fill the emptiest splits."""
        import math as _math

        hint_nodes = []
        for hint in locality_hints:
            try:
                from ray_tpu.gcs.state import actor_node_of

                node = actor_node_of(hint)
            except Exception:
                node = None
            hint_nodes.append(node)
        cap = _math.ceil(len(self._blocks) / n)
        shards: List[Tuple[List, List]] = [([], []) for _ in range(n)]
        leftovers = []
        for ref, meta in zip(self._blocks, metas):
            node = getattr(meta, "node_id", None)
            placed = False
            if node is not None:
                for i, hint_node in enumerate(hint_nodes):
                    if hint_node == node and len(shards[i][0]) < cap:
                        shards[i][0].append(ref)
                        shards[i][1].append(meta)
                        placed = True
                        break
            if not placed:
                leftovers.append((ref, meta))
        for ref, meta in leftovers:
            i = min(range(n), key=lambda j: len(shards[j][0]))
            shards[i][0].append(ref)
            shards[i][1].append(meta)
        return [Dataset(refs, ms) for refs, ms in shards]

    def split_at_indices(self, indices: List[int]) -> List["Dataset"]:
        rows = list(self.iter_rows())
        bounds = [0] + list(indices) + [len(rows)]
        out = []
        for lo, hi in zip(bounds[:-1], bounds[1:]):
            block = build_output_block(rows[lo:hi])
            meta = BlockAccessor.for_block(block).get_metadata()
            out.append(Dataset([ray_tpu.put(block)], [meta]))
        return out

    def random_sample(self, fraction: float,
                      seed: Optional[int] = None) -> "Dataset":
        rng = random.Random(seed)
        return self.filter(lambda _r: rng.random() < fraction)

    # ----------------------------------------------------------- consumers
    def iter_rows(self) -> Iterator[Any]:
        for ref in self._blocks:
            block = ray_tpu.get([ref])[0]
            yield from BlockAccessor.for_block(block).iter_rows()

    def iter_batches(self, *, batch_size: Optional[int] = None,
                     batch_format: str = "native",
                     drop_last: bool = False) -> Iterator[Any]:
        buffer: List[Any] = []
        last_block: Optional[Block] = None
        for ref in self._blocks:
            block = ray_tpu.get([ref])[0]
            last_block = block
            acc = BlockAccessor.for_block(block)
            if batch_size is None:
                if acc.num_rows():
                    yield acc.to_batch(batch_format)
                continue
            buffer.extend(acc.iter_rows())
            while len(buffer) >= batch_size:
                chunk, buffer = buffer[:batch_size], buffer[batch_size:]
                yield BlockAccessor.for_block(
                    build_output_block(chunk)).to_batch(batch_format)
        if buffer and not drop_last:
            yield BlockAccessor.for_block(
                build_output_block(buffer)).to_batch(batch_format)
        if batch_size is None and last_block is None:
            return

    @staticmethod
    def _split_features(batch: dict, columns, label_column):
        """columns-else-all-but-label feature split, shared by
        to_jax/to_tf (one definition, one semantics)."""
        if columns:
            return {c: batch[c] for c in columns}
        return {k: v for k, v in batch.items() if k != label_column}

    def to_jax(self, *, batch_size: int,
               columns: Optional[List[str]] = None,
               label_column: Optional[str] = None,
               drop_last: bool = True,
               device_put: bool = True) -> Iterator[Any]:
        """Yield jnp batches ready for a jit'd step function. The TPU-first
        input pipeline: numpy column batches → jax.device_put (which lands
        in HBM); keep batch_size static so the step compiles once."""
        import jax
        import jax.numpy as jnp

        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy",
                                       drop_last=drop_last):
            if isinstance(batch, dict):
                feats = self._split_features(batch, columns, label_column)
                arrs = {k: jnp.asarray(v) for k, v in feats.items()}
                if label_column is not None:
                    labels = jnp.asarray(batch[label_column])
                    out = (arrs, labels)
                else:
                    out = arrs
            else:
                out = jnp.asarray(batch)
            if device_put:
                out = jax.device_put(out)
            yield out

    def to_torch(self, *, batch_size: int,
                 label_column: Optional[str] = None,
                 drop_last: bool = False) -> Iterator[Any]:
        import torch

        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy",
                                       drop_last=drop_last):
            if isinstance(batch, dict) and label_column is not None:
                feats = {k: torch.as_tensor(np.asarray(v))
                         for k, v in batch.items() if k != label_column}
                yield feats, torch.as_tensor(np.asarray(batch[label_column]))
            elif isinstance(batch, dict):
                yield {k: torch.as_tensor(np.asarray(v))
                       for k, v in batch.items()}
            else:
                yield torch.as_tensor(np.asarray(batch))

    def to_tf(self, *, batch_size: int,
              columns: Optional[List[str]] = None,
              label_column: Optional[str] = None,
              drop_last: bool = False):
        """A ``tf.data.Dataset`` over this dataset's blocks (reference:
        dataset.py to_tf): numpy column batches flow through
        ``from_generator`` with an inferred output signature, yielding
        ``features_dict`` or ``(features_dict, labels)``."""
        import tensorflow as tf

        def gen():
            for batch in self.iter_batches(batch_size=batch_size,
                                           batch_format="numpy",
                                           drop_last=drop_last):
                if not isinstance(batch, dict):
                    yield np.asarray(batch)
                    continue
                feats = {k: np.asarray(v) for k, v in
                         self._split_features(batch, columns,
                                              label_column).items()}
                if label_column is not None:
                    yield feats, np.asarray(batch[label_column])
                else:
                    yield feats

        # infer the signature from a ONE-ROW probe over limit(1):
        # dtypes + trailing shapes are batch-size-invariant, so this
        # avoids materializing (and discarding) a full first batch,
        # and a small dataset under drop_last=True still gets a
        # signature (yielding an empty tf Dataset, not an error)
        probe = next(iter(self.limit(1).to_tf_probe_batches(
            columns, label_column)), None)
        if probe is None:
            raise ValueError("to_tf on an empty dataset")

        def spec_of(arr):
            return tf.TensorSpec(shape=(None,) + arr.shape[1:],
                                 dtype=arr.dtype)

        if isinstance(probe, tuple):
            feats, labels = probe
            signature = ({k: spec_of(v) for k, v in feats.items()},
                         spec_of(labels))
        elif isinstance(probe, dict):
            signature = {k: spec_of(v) for k, v in probe.items()}
        else:
            signature = spec_of(probe)
        return tf.data.Dataset.from_generator(
            gen, output_signature=signature)

    def to_tf_probe_batches(self, columns, label_column):
        """One-row batches in to_tf's output structure (signature
        inference only)."""
        for batch in self.iter_batches(batch_size=1,
                                       batch_format="numpy"):
            if not isinstance(batch, dict):
                yield np.asarray(batch)
                continue
            feats = {k: np.asarray(v) for k, v in
                     self._split_features(batch, columns,
                                          label_column).items()}
            if label_column is not None:
                yield feats, np.asarray(batch[label_column])
            else:
                yield feats

    def to_pandas(self, limit: Optional[int] = None):
        import pandas as pd

        frames = []
        taken = 0
        for ref in self._blocks:
            block = ray_tpu.get([ref])[0]
            frames.append(BlockAccessor.for_block(block).to_pandas())
            taken += len(frames[-1])
            if limit is not None and taken >= limit:
                break
        if not frames:
            return pd.DataFrame()
        df = pd.concat(frames, ignore_index=True)
        return df.head(limit) if limit is not None else df

    def to_numpy_refs(self) -> List["ray_tpu.ObjectRef"]:
        @ray_tpu.remote
        def _to_numpy(block):
            return BlockAccessor.for_block(block).to_numpy()
        return [_to_numpy.remote(ref) for ref in self._blocks]

    def to_arrow_refs(self) -> List["ray_tpu.ObjectRef"]:
        @ray_tpu.remote
        def _to_arrow(block):
            return BlockAccessor.for_block(block).to_arrow()
        return [_to_arrow.remote(ref) for ref in self._blocks]

    def take(self, limit: int = 20) -> List[Any]:
        out = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= limit:
                break
        return out

    def take_all(self) -> List[Any]:
        return list(self.iter_rows())

    def show(self, limit: int = 20) -> None:
        for row in self.take(limit):
            print(row)

    def count(self) -> int:
        metas = self._ensure_metadata()
        return sum(m.num_rows or 0 for m in metas)

    def _reduce_rows(self, fn, initial, key=None):
        acc = initial
        for row in self.iter_rows():
            v = _get_key(row, key) if key is not None else row
            acc = fn(acc, v)
        return acc

    def sum(self, on: Optional[Union[str, Callable]] = None):
        return self._reduce_rows(lambda a, b: a + b, 0, on)

    def min(self, on: Optional[Union[str, Callable]] = None):
        vals = [(_get_key(r, on) if on is not None else r)
                for r in self.iter_rows()]
        return min(vals) if vals else None

    def max(self, on: Optional[Union[str, Callable]] = None):
        vals = [(_get_key(r, on) if on is not None else r)
                for r in self.iter_rows()]
        return max(vals) if vals else None

    def mean(self, on: Optional[Union[str, Callable]] = None):
        vals = [(_get_key(r, on) if on is not None else r)
                for r in self.iter_rows()]
        return sum(vals) / len(vals) if vals else None

    def std(self, on: Optional[Union[str, Callable]] = None, ddof: int = 1):
        vals = np.array([(_get_key(r, on) if on is not None else r)
                         for r in self.iter_rows()], dtype=np.float64)
        return float(np.std(vals, ddof=ddof)) if len(vals) > ddof else None

    # --------------------------------------------------------------- write
    def write_parquet(self, path: str) -> None:
        from ray_tpu.data.read_api import _write_blocks
        _write_blocks(self._blocks, path, "parquet")

    def write_csv(self, path: str) -> None:
        from ray_tpu.data.read_api import _write_blocks
        _write_blocks(self._blocks, path, "csv")

    def write_json(self, path: str) -> None:
        from ray_tpu.data.read_api import _write_blocks
        _write_blocks(self._blocks, path, "json")

    # ------------------------------------------------------------ pipeline
    def window(self, *, blocks_per_window: int = 10) -> "DatasetPipeline":
        from ray_tpu.data.pipeline import DatasetPipeline
        return DatasetPipeline.from_dataset_windows(self, blocks_per_window)

    def repeat(self, times: Optional[int] = None) -> "DatasetPipeline":
        from ray_tpu.data.pipeline import DatasetPipeline
        return DatasetPipeline.from_dataset_repeat(self, times)

    def fully_executed(self) -> "Dataset":
        ray_tpu.get(self._blocks)
        return self

    def __repr__(self) -> str:
        metas = self._metadata
        rows = sum(m.num_rows or 0 for m in metas if m) if any(metas) else "?"
        return (f"Dataset(num_blocks={len(self._blocks)}, num_rows={rows}, "
                f"schema={_short_schema(self)})")


def _short_schema(ds: Dataset) -> str:
    try:
        s = ds.schema()
    except Exception:
        return "?"
    if s is None:
        return "None"
    if hasattr(s, "names"):
        return "{" + ", ".join(
            f"{n}: {t}" for n, t in zip(s.names, s.types)) + "}"
    return getattr(s, "__name__", str(s))


def _get_key(row: Any, key: Union[str, Callable, None]) -> Any:
    if key is None:
        return row
    if callable(key):
        return key(row)
    return row[key]
