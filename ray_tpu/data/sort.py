"""Distributed sort: sample → range partition → per-partition sort.

Reference: python/ray/data/impl/sort.py (sample boundaries, shuffle rows
into boundary-delimited partitions, sort each partition in parallel).
"""

from __future__ import annotations

import bisect
from typing import Callable, List, Optional, Tuple, Union

import ray_tpu
from ray_tpu.data.block import (
    Block,
    BlockAccessor,
    BlockMetadata,
    build_output_block,
)

SAMPLES_PER_BLOCK = 10


def _key_fn(key: Union[str, Callable, None]) -> Callable:
    if key is None:
        return lambda r: r
    if callable(key):
        return key
    return lambda r: r[key]


def sort_blocks(block_refs: List["ray_tpu.ObjectRef"],
                key: Union[str, Callable, None], descending: bool
                ) -> Tuple[List["ray_tpu.ObjectRef"], List[BlockMetadata]]:
    if not block_refs:
        return [], []
    kf = _key_fn(key)
    num_out = len(block_refs)

    @ray_tpu.remote
    def sample_block(block: Block):
        return BlockAccessor.for_block(block).sample(SAMPLES_PER_BLOCK, kf)

    samples = sorted(
        s for part in ray_tpu.get(
            [sample_block.remote(r) for r in block_refs]) for s in part)
    if samples:
        step = max(len(samples) // num_out, 1)
        boundaries = [samples[i * step] for i in range(1, num_out)
                      if i * step < len(samples)]
    else:
        boundaries = []
    nparts = len(boundaries) + 1

    @ray_tpu.remote(num_returns=max(nparts, 1))
    def partition_block(block: Block):
        acc = BlockAccessor.for_block(block)
        parts: List[list] = [[] for _ in range(nparts)]
        for r in acc.iter_rows():
            parts[bisect.bisect_left(boundaries, kf(r))].append(r)
        out = [build_output_block(p) for p in parts]
        return out if nparts > 1 else out[0]

    @ray_tpu.remote(num_returns=2)
    def merge_sorted(*parts: Block):
        rows: list = []
        for p in parts:
            rows.extend(BlockAccessor.for_block(p).iter_rows())
        rows.sort(key=kf, reverse=descending)
        block = build_output_block(rows)
        return block, BlockAccessor.for_block(block).get_metadata()

    map_out = [partition_block.remote(r) for r in block_refs]
    if nparts == 1:
        map_out = [[m] for m in map_out]
    part_order = (range(nparts - 1, -1, -1) if descending
                  else range(nparts))
    out_refs, meta_refs = [], []
    for j in part_order:
        b, meta = merge_sorted.remote(*[m[j] for m in map_out])
        out_refs.append(b)
        meta_refs.append(meta)
    metas = ray_tpu.get(meta_refs)
    return out_refs, metas
