"""DatasetPipeline — windowed / repeated streaming over a Dataset.

Reference: python/ray/data/dataset_pipeline.py. A pipeline is a lazy
iterator of Datasets (windows); per-window transforms are recorded and
applied as each window is produced, overlapping epochs with consumption.
"""

from __future__ import annotations

from typing import Any, Callable, Iterator, List, Optional


class DatasetPipeline:
    def __init__(self, window_fn: Callable[[], Iterator["Dataset"]],
                 length: Optional[int] = None):
        self._window_fn = window_fn
        self._length = length
        self._stages: List[Callable[["Dataset"], "Dataset"]] = []

    # ------------------------------------------------------------ creation
    @classmethod
    def from_dataset_windows(cls, ds, blocks_per_window: int
                             ) -> "DatasetPipeline":
        from ray_tpu.data.dataset import Dataset

        refs = ds.get_internal_block_refs()
        metas = ds._ensure_metadata()

        def gen():
            for i in range(0, len(refs), blocks_per_window):
                yield Dataset(refs[i:i + blocks_per_window],
                              metas[i:i + blocks_per_window])

        n = (len(refs) + blocks_per_window - 1) // max(blocks_per_window, 1)
        return cls(gen, n)

    @classmethod
    def from_dataset_repeat(cls, ds, times: Optional[int]
                            ) -> "DatasetPipeline":
        def gen():
            i = 0
            while times is None or i < times:
                yield ds
                i += 1

        return cls(gen, times)

    # ---------------------------------------------------------- transforms
    def _with_stage(self, stage: Callable) -> "DatasetPipeline":
        p = DatasetPipeline(self._window_fn, self._length)
        p._stages = self._stages + [stage]
        return p

    def map(self, fn, **kw) -> "DatasetPipeline":
        return self._with_stage(lambda ds: ds.map(fn, **kw))

    def map_batches(self, fn, **kw) -> "DatasetPipeline":
        return self._with_stage(lambda ds: ds.map_batches(fn, **kw))

    def filter(self, fn, **kw) -> "DatasetPipeline":
        return self._with_stage(lambda ds: ds.filter(fn, **kw))

    def flat_map(self, fn, **kw) -> "DatasetPipeline":
        return self._with_stage(lambda ds: ds.flat_map(fn, **kw))

    def random_shuffle_each_window(self, **kw) -> "DatasetPipeline":
        return self._with_stage(lambda ds: ds.random_shuffle(**kw))

    def repartition_each_window(self, n: int, **kw) -> "DatasetPipeline":
        return self._with_stage(lambda ds: ds.repartition(n, **kw))

    def foreach_window(self, fn: Callable) -> "DatasetPipeline":
        return self._with_stage(fn)

    # ----------------------------------------------------------- consumers
    def iter_datasets(self) -> Iterator["Dataset"]:
        for ds in self._window_fn():
            for stage in self._stages:
                ds = stage(ds)
            yield ds

    def iter_rows(self) -> Iterator[Any]:
        for ds in self.iter_datasets():
            yield from ds.iter_rows()

    def iter_batches(self, **kw) -> Iterator[Any]:
        for ds in self.iter_datasets():
            yield from ds.iter_batches(**kw)

    def to_jax(self, **kw) -> Iterator[Any]:
        for ds in self.iter_datasets():
            yield from ds.to_jax(**kw)

    def take(self, limit: int = 20) -> List[Any]:
        out: List[Any] = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= limit:
                break
        return out

    def count(self) -> int:
        return sum(ds.count() for ds in self.iter_datasets())

    def split(self, n: int) -> List["DatasetPipeline"]:
        """Split each window across n consumers (for per-worker shards)."""
        outs = []
        for i in range(n):
            def gen(i=i):
                for ds in self.iter_datasets():
                    yield ds.split(n)[i]
            outs.append(DatasetPipeline(gen, self._length))
        return outs

    def __repr__(self) -> str:
        return (f"DatasetPipeline(num_windows={self._length}, "
                f"num_stages={len(self._stages)})")
