"""Compute strategies: stateless tasks vs an autoscaling actor pool.

Reference: python/ray/data/impl/compute.py (TaskPool vs ActorPool). The
actor pool exists for stateful/expensive-setup UDFs (e.g. a model reused
across batches); tasks are the default.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Tuple

import ray_tpu
from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata


def _apply_fn(fn: Callable, block: Block) -> Tuple[Block, BlockMetadata]:
    out = fn(block)
    meta = BlockAccessor.for_block(out).get_metadata()
    try:  # record WHERE the block materialized, for locality-aware split
        import ray_tpu

        meta.node_id = ray_tpu.get_runtime_context().get_node_id()
    except Exception:
        pass
    return out, meta


class ComputeStrategy:
    def apply(self, fn: Callable[[Block], Block], remote_args: dict,
              block_refs: List["ray_tpu.ObjectRef"]
              ) -> Tuple[List["ray_tpu.ObjectRef"], List[BlockMetadata]]:
        raise NotImplementedError


class TaskPoolStrategy(ComputeStrategy):
    def apply(self, fn, remote_args, block_refs):
        remote_args = dict(remote_args or {})
        remote_args.setdefault("num_cpus", 0.25)

        @ray_tpu.remote(**remote_args, num_returns=2)
        def _map_block(block):
            return _apply_fn(fn, block)

        out_refs, meta_refs = [], []
        for ref in block_refs:
            b, m = _map_block.remote(ref)
            out_refs.append(b)
            meta_refs.append(m)
        metas = ray_tpu.get(meta_refs)
        return out_refs, metas


class ActorPoolStrategy(ComputeStrategy):
    """Fixed-size (min_size..max_size) pool of worker actors; each holds
    the instantiated UDF (reference: data/impl/compute.py:ActorPool)."""

    def __init__(self, min_size: int = 1, max_size: Optional[int] = None):
        self.min_size = min_size
        self.max_size = max_size or min_size

    def apply(self, fn, remote_args, block_refs):
        remote_args = dict(remote_args or {})
        remote_args.setdefault("num_cpus", 0.25)

        @ray_tpu.remote(**remote_args)
        class _BlockWorker:
            def map_block(self, block):
                return _apply_fn(fn, block)

        n = max(self.min_size, min(self.max_size, len(block_refs)))
        workers = [_BlockWorker.remote() for _ in range(n)]
        from ray_tpu.util.actor_pool import ActorPool

        pool = ActorPool(workers)
        results = list(pool.map(
            lambda a, ref: a.map_block.remote(ref), block_refs))
        for w in workers:
            ray_tpu.kill(w)
        out_refs = [ray_tpu.put(b) for b, _ in results]
        metas = [m for _, m in results]
        return out_refs, metas


def get_compute(compute: Any) -> ComputeStrategy:
    if compute is None or compute == "tasks":
        return TaskPoolStrategy()
    if compute == "actors":
        return ActorPoolStrategy()
    if isinstance(compute, ComputeStrategy):
        return compute
    raise ValueError(f"unknown compute strategy: {compute!r}")
