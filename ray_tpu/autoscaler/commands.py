"""`ray up` / `ray down` command layer.

Reference: python/ray/autoscaler/_private/commands.py
(create_or_update_cluster:121, teardown_cluster:211, get_head_node_ip)
driven by scripts/scripts.py. The cluster YAML schema is the reference's
(cluster_name, provider, max_workers, available_node_types,
head_node_type, idle_timeout_minutes); setup/init commands are accepted
but ignored by the local providers (no SSH surface on one host).

Providers resolve through a registry (reference:
python/ray/autoscaler/node_provider.py _get_node_provider):
  fake_multinode — nodes inside the current in-process runtime
  process       — one REAL raylet OS process per node against a GCS
                  server process (cluster/process_cluster.py machinery)
  command       — the SSH shape: nodes come up by running a shell
                  command template that announces a raylet on stdout
  external      — dotted path to a user NodeProvider subclass
"""

from __future__ import annotations

import importlib
import logging
import threading
import uuid
from typing import Any, Dict, List, Optional

from ray_tpu.autoscaler.node_provider import (
    NODE_KIND_HEAD,
    NODE_KIND_WORKER,
    STATUS_UP_TO_DATE,
    TAG_NODE_KIND,
    TAG_NODE_STATUS,
    TAG_USER_NODE_TYPE,
    FakeMultiNodeProvider,
    NodeProvider,
)

logger = logging.getLogger(__name__)


# --------------------------------------------------------------------------
# config loading (reference: autoscaler/_private/util.py prepare_config)
# --------------------------------------------------------------------------

def load_cluster_config(path_or_dict) -> Dict[str, Any]:
    """Read + validate + fill defaults for a cluster config (YAML path,
    YAML string, or dict)."""
    if isinstance(path_or_dict, dict):
        config = dict(path_or_dict)
    else:
        import os

        import yaml

        if os.path.exists(path_or_dict):
            with open(path_or_dict) as f:
                config = yaml.safe_load(f)
        else:
            config = yaml.safe_load(path_or_dict)
        if not isinstance(config, dict):
            raise ValueError("cluster config must be a mapping")
    return prepare_config(config)


def prepare_config(config: Dict[str, Any]) -> Dict[str, Any]:
    config = dict(config)
    config.setdefault("cluster_name", "default")
    provider = config.setdefault("provider", {"type": "fake_multinode"})
    if "type" not in provider:
        raise ValueError("provider.type is required")
    types = config.setdefault("available_node_types", {
        "head": {"resources": {"CPU": 1}, "min_workers": 0,
                 "max_workers": 0},
        "worker": {"resources": {"CPU": 1}, "min_workers": 0,
                   "max_workers": 2},
    })
    config.setdefault("head_node_type", next(iter(types)))
    if config["head_node_type"] not in types:
        raise ValueError(
            f"head_node_type {config['head_node_type']!r} is not in "
            f"available_node_types {sorted(types)}")
    for name, spec in types.items():
        if not isinstance(spec.get("resources", {}), dict):
            raise ValueError(f"node type {name}: resources must be a map")
        spec.setdefault("resources", {"CPU": 1})
        spec.setdefault("min_workers", 0)
        spec.setdefault("max_workers", config.get("max_workers", 2))
    config.setdefault(
        "max_workers",
        sum(t["max_workers"] for n, t in types.items()
            if n != config["head_node_type"]))
    config.setdefault("idle_timeout_minutes", 5)
    return config


# --------------------------------------------------------------------------
# provider registry
# --------------------------------------------------------------------------

_PROVIDERS: Dict[str, Any] = {}


def register_node_provider(type_name: str, cls) -> None:
    _PROVIDERS[type_name] = cls


def _get_node_provider(provider_config: Dict[str, Any],
                       cluster_name: str) -> NodeProvider:
    ptype = provider_config["type"]
    if ptype == "external":
        module_path, _, cls_name = provider_config["module"].rpartition(".")
        cls = getattr(importlib.import_module(module_path), cls_name)
        return cls(provider_config, cluster_name)
    if ptype in _PROVIDERS:
        return _PROVIDERS[ptype](provider_config, cluster_name)
    if ptype == "fake_multinode":
        return FakeMultiNodeProvider(provider_config, cluster_name)
    if ptype == "process":
        return ProcessNodeProvider(provider_config, cluster_name)
    if ptype == "command":
        return CommandNodeProvider(provider_config, cluster_name)
    if ptype == "inventory":
        from ray_tpu.autoscaler.inventory_provider import (
            InventoryNodeProvider,
        )

        return InventoryNodeProvider(provider_config, cluster_name)
    if ptype == "aws":
        from ray_tpu.autoscaler.aws_provider import AwsNodeProvider

        return AwsNodeProvider(provider_config, cluster_name)
    raise ValueError(f"unknown provider type {ptype!r}")


class CommandNodeProvider(NodeProvider):
    """SSH-shape provider: a node comes up by RUNNING A COMMAND whose
    stdout announces the raylet it started (reference: the SSH command
    runner under autoscaler/_private/command_runner.py behind the
    NodeProvider plugin surface — on a real fleet the template is
    ``ssh {host} python -m ray_tpu.cluster.raylet_server --gcs ...``;
    the announce line rides the ssh stdout the same way).

    provider config keys:
      gcs_address            optional external control plane; when
                             absent the provider starts a GCS server
                             process (the head's control plane)
      create_node_command    template; placeholders {gcs_address},
                             {resources_json}, {num_cpus}. Default
                             spawns a raylet via this interpreter —
                             the loopback stand-in for ssh.
      terminate_node_command optional template; placeholders
                             {node_id}, {address}, {pid}. Default:
                             terminate the locally-tracked process.
    """

    DEFAULT_CREATE = (
        "exec %s -m ray_tpu.cluster.raylet_server "
        "--gcs {gcs_address} --resources '{resources_json}'")

    def __init__(self, provider_config: Dict[str, Any],
                 cluster_name: str = "command"):
        super().__init__(provider_config, cluster_name)
        import sys

        self._gcs_proc = None
        self.gcs_address = provider_config.get("gcs_address")
        if not self.gcs_address:
            from ray_tpu.cluster.process_cluster import _spawn

            self._gcs_proc, fields = _spawn(
                ["ray_tpu.cluster.gcs_server",
                 "--heartbeat-period-ms",
                 str(provider_config.get("heartbeat_period_ms", 100)),
                 "--num-heartbeats-timeout",
                 str(provider_config.get("num_heartbeats_timeout", 20))],
                "GCS_ADDRESS")
            self.gcs_address = fields[1]
        self._create_cmd = provider_config.get(
            "create_node_command", self.DEFAULT_CREATE % sys.executable)
        self._terminate_cmd = provider_config.get("terminate_node_command")
        self._lock = threading.Lock()
        self._nodes: Dict[str, Dict[str, Any]] = {}

    def _run_create(self, node_config: Dict[str, Any]) -> str:
        import json
        import os
        import select
        import subprocess
        import time as _time

        resources = dict(node_config.get("resources", {"CPU": 1}))
        cmd = self._create_cmd.format(
            gcs_address=self.gcs_address,
            resources_json=json.dumps(resources),
            num_cpus=resources.get("CPU", 1))
        # shared child-env hygiene (cluster/child_env.py): no eager
        # accelerator hooks, a resolvable JAX backend, ray_tpu
        # importable regardless of the caller's cwd
        from ray_tpu.cluster.child_env import sanitized_env

        env = sanitized_env(pin_pythonpath=True)
        proc = subprocess.Popen(cmd, shell=True, stdout=subprocess.PIPE,
                                env=env, text=True)
        deadline = _time.monotonic() + 60.0
        buf = ""
        try:
            os.set_blocking(proc.stdout.fileno(), False)
            while _time.monotonic() < deadline:
                # select-bounded read: a silent command must FAIL after
                # the deadline, not park the monitor thread in readline
                ready, _, _ = select.select(
                    [proc.stdout], [], [],
                    max(0.0, deadline - _time.monotonic()))
                if not ready:
                    continue
                chunk = proc.stdout.read()
                if chunk == "" and proc.poll() is not None:
                    raise RuntimeError(
                        f"create command exited rc={proc.poll()}: {cmd}")
                buf += chunk or ""
                for line in buf.splitlines():
                    if line.startswith("RAYLET_ADDRESS"):
                        fields = line.split()
                        nid = f"cmd-{uuid.uuid4().hex[:8]}"
                        with self._lock:
                            self._nodes[nid] = {
                                "tags": {}, "raylet": fields[3],
                                "address": fields[1], "proc": proc,
                            }
                        return nid
            raise RuntimeError(f"create command never announced: {cmd}")
        except BaseException:
            # never leak the process: an unannounced raylet may already
            # be registered with the GCS and would be unreapable
            try:
                proc.kill()
                proc.wait(timeout=10)
            except Exception:
                pass
            raise

    def create_head(self, node_config: Dict[str, Any],
                    node_type: str) -> str:
        nid = self._run_create(node_config)
        with self._lock:
            self._nodes[nid]["tags"] = {
                TAG_NODE_KIND: NODE_KIND_HEAD,
                TAG_NODE_STATUS: STATUS_UP_TO_DATE,
                TAG_USER_NODE_TYPE: node_type,
            }
        return nid

    def create_node(self, node_config: Dict[str, Any],
                    tags: Dict[str, str], count: int) -> None:
        for _ in range(count):
            nid = self._run_create(node_config)
            with self._lock:
                self._nodes[nid]["tags"] = {
                    **tags, TAG_NODE_STATUS: STATUS_UP_TO_DATE}

    def non_terminated_nodes(self, tag_filters: Dict[str, str]
                             ) -> List[str]:
        with self._lock:
            return [nid for nid, info in self._nodes.items()
                    if all(info["tags"].get(k) == v
                           for k, v in tag_filters.items())]

    def is_running(self, node_id: str) -> bool:
        with self._lock:
            info = self._nodes.get(node_id)
        return info is not None and info["proc"].poll() is None

    def node_tags(self, node_id: str) -> Dict[str, str]:
        with self._lock:
            return dict(self._nodes[node_id]["tags"])

    def internal_ip(self, node_id: str) -> str:
        with self._lock:
            return self._nodes[node_id]["address"].rsplit(":", 1)[0]

    def raylet_node_id(self, node_id: str) -> Optional[str]:
        with self._lock:
            info = self._nodes.get(node_id)
        return None if info is None else info["raylet"]

    def terminate_node(self, node_id: str) -> None:
        import subprocess

        with self._lock:
            info = self._nodes.pop(node_id, None)
        if info is None:
            return
        if self._terminate_cmd:
            subprocess.run(self._terminate_cmd.format(
                node_id=info["raylet"], address=info["address"],
                pid=info["proc"].pid), shell=True, timeout=60)
        else:
            info["proc"].terminate()
        try:
            info["proc"].wait(timeout=10)
        except Exception:
            info["proc"].kill()

    def shutdown(self) -> None:
        with self._lock:
            nodes = list(self._nodes)
        for nid in nodes:
            try:
                self.terminate_node(nid)
            except Exception:
                pass
        if self._gcs_proc is not None:
            self._gcs_proc.kill()
            try:
                self._gcs_proc.wait(timeout=5)
            except Exception:
                pass

    def state(self) -> Dict[str, Any]:
        with self._lock:
            return {"gcs_address": self.gcs_address,
                    "nodes": {nid: info["address"]
                              for nid, info in self._nodes.items()}}


class ProcessNodeProvider(NodeProvider):
    """Real OS processes per node: the head is a GCS server process, each
    worker is a raylet server process registered to it (the single-host
    analogue of a cloud provider; reference:
    autoscaler/_private/fake_multi_node/node_provider.py but with real
    process isolation)."""

    def __init__(self, provider_config: Dict[str, Any],
                 cluster_name: str = "process"):
        super().__init__(provider_config, cluster_name)
        from ray_tpu.cluster.process_cluster import ProcessCluster

        self._cluster = ProcessCluster(
            heartbeat_period_ms=provider_config.get(
                "heartbeat_period_ms", 100),
            num_heartbeats_timeout=provider_config.get(
                "num_heartbeats_timeout", 20))
        self._lock = threading.Lock()
        self._nodes: Dict[str, Dict[str, Any]] = {}

    @property
    def gcs_address(self) -> str:
        return self._cluster.gcs_address

    def create_head(self, node_config: Dict[str, Any],
                    node_type: str) -> str:
        # the GCS process started in the ProcessCluster ctor IS the head
        # control plane; the head node also runs a raylet for its
        # resources, like the reference head node
        nid = self._create_raylet(node_config)
        with self._lock:
            self._nodes[nid]["tags"] = {
                TAG_NODE_KIND: NODE_KIND_HEAD,
                TAG_NODE_STATUS: STATUS_UP_TO_DATE,
                TAG_USER_NODE_TYPE: node_type,
            }
        return nid

    def _create_raylet(self, node_config: Dict[str, Any]) -> str:
        resources = dict(node_config.get("resources", {"CPU": 1}))
        cpus = float(resources.get("CPU", 1))
        raylet_node_id = self._cluster.add_node(
            num_cpus=cpus, resources={
                k: v for k, v in resources.items() if k != "CPU"})
        nid = f"proc-{uuid.uuid4().hex[:8]}"
        with self._lock:
            self._nodes[nid] = {"tags": {}, "raylet": raylet_node_id}
        return nid

    def non_terminated_nodes(self, tag_filters: Dict[str, str]
                             ) -> List[str]:
        with self._lock:
            return [nid for nid, info in self._nodes.items()
                    if all(info["tags"].get(k) == v
                           for k, v in tag_filters.items())]

    def is_running(self, node_id: str) -> bool:
        with self._lock:
            return node_id in self._nodes

    def node_tags(self, node_id: str) -> Dict[str, str]:
        with self._lock:
            return dict(self._nodes[node_id]["tags"])

    def internal_ip(self, node_id: str) -> str:
        return "127.0.0.1"

    def raylet_node_id(self, node_id: str) -> str:
        with self._lock:
            return self._nodes[node_id]["raylet"]

    def create_node(self, node_config: Dict[str, Any],
                    tags: Dict[str, str], count: int) -> None:
        for _ in range(count):
            nid = self._create_raylet(node_config)
            with self._lock:
                self._nodes[nid]["tags"] = {
                    **tags, TAG_NODE_STATUS: STATUS_UP_TO_DATE}

    def terminate_node(self, node_id: str) -> None:
        with self._lock:
            info = self._nodes.pop(node_id, None)
        if info is not None:
            try:
                self._cluster.remove_node(info["raylet"])
            except Exception:
                logger.exception("terminating node %s failed", node_id)

    def shutdown(self) -> None:
        self._cluster.shutdown()

    def state(self) -> Dict[str, Any]:
        return {
            "gcs_address": self._cluster.gcs_address,
            "pids": [self._cluster.gcs_proc.pid] + [
                p.pid for p in self._cluster.raylets.values()],
        }


# --------------------------------------------------------------------------
# commands (reference: commands.py create_or_update_cluster / teardown)
# --------------------------------------------------------------------------

_CLUSTERS: Dict[str, "ClusterHandle"] = {}
_CLUSTERS_LOCK = threading.Lock()
# serializes whole up/down operations: provider construction spawns real
# processes, and a check-then-create race would leak an entire cluster
_CREATE_LOCK = threading.Lock()


class ClusterHandle:
    """What `ray up` returns: the provider plus identity/introspection."""

    def __init__(self, config: Dict[str, Any], provider: NodeProvider,
                 head_id: str):
        self.config = config
        self.provider = provider
        self.head_id = head_id
        self.autoscaler = None
        self._monitor_stop: Optional[threading.Event] = None
        self._monitor_thread: Optional[threading.Thread] = None

    @property
    def name(self) -> str:
        return self.config["cluster_name"]

    def head_node_ip(self) -> str:
        return self.provider.internal_ip(self.head_id)

    def worker_ids(self) -> List[str]:
        return self.provider.non_terminated_nodes(
            {TAG_NODE_KIND: NODE_KIND_WORKER})

    def start_monitor(self, interval_s: float = 1.0) -> None:
        """Run the StandardAutoscaler reconcile loop in a thread
        (reference: monitor.py driving StandardAutoscaler.update).
        Idempotent: a second call stops the previous loop first — two
        concurrent loops would race node launches."""
        from ray_tpu.autoscaler.autoscaler import StandardAutoscaler

        self.stop_monitor()
        if self.autoscaler is None:
            self.autoscaler = StandardAutoscaler(self.config, self.provider)
        stop = threading.Event()
        self._monitor_stop = stop

        def loop():
            while not stop.wait(interval_s):
                try:
                    self.autoscaler.update()
                    # monitor launches/terminations change the process
                    # set: keep the state file current so a cross-process
                    # `ray down` can reap every node
                    _save_cluster_state(self)
                except Exception:
                    logger.exception("autoscaler tick failed")

        self._monitor_thread = threading.Thread(
            target=loop, daemon=True, name=f"monitor-{self.name}")
        self._monitor_thread.start()

    def stop_monitor(self) -> None:
        """Stop AND JOIN the loop: teardown must not race an in-flight
        tick that could relaunch nodes or resurrect the state file."""
        if self._monitor_stop is not None:
            self._monitor_stop.set()
            self._monitor_stop = None
        thread = getattr(self, "_monitor_thread", None)
        if thread is not None and thread.is_alive():
            thread.join(timeout=60.0)
        self._monitor_thread = None
        if self.autoscaler is not None:
            try:
                self.autoscaler.load_metrics.close()
            except Exception:
                pass


def create_or_update_cluster(config) -> ClusterHandle:
    """`ray up`: ensure the head node exists and min_workers of every
    node type are up (reference: commands.py:121 + get_or_create_head_node)."""
    config = load_cluster_config(config)
    name = config["cluster_name"]
    with _CREATE_LOCK:
        return _create_or_update_locked(config, name)


def _create_or_update_locked(config: Dict[str, Any],
                             name: str) -> ClusterHandle:
    with _CLUSTERS_LOCK:
        handle = _CLUSTERS.get(name)
    if handle is None:
        provider = _get_node_provider(config["provider"], name)
        head_type = config["head_node_type"]
        head_cfg = config["available_node_types"][head_type]
        if hasattr(provider, "create_head"):
            head_id = provider.create_head(head_cfg, head_type)
        else:
            heads = provider.non_terminated_nodes(
                {TAG_NODE_KIND: NODE_KIND_HEAD})
            head_id = heads[0] if heads else None
            if head_id is None:
                raise RuntimeError("provider has no head node")
        handle = ClusterHandle(config, provider, head_id)
        with _CLUSTERS_LOCK:
            _CLUSTERS[name] = handle
    else:
        handle.config = config  # ray up on a live cluster updates config
        if handle.autoscaler is not None:
            # the running monitor reads handle.autoscaler each tick:
            # rebuilding it makes updated YAML limits take effect
            from ray_tpu.autoscaler.autoscaler import StandardAutoscaler

            handle.autoscaler = StandardAutoscaler(config, handle.provider)
    # scale to min_workers per type (idempotent)
    for type_name, spec in config["available_node_types"].items():
        if type_name == config["head_node_type"]:
            continue
        want = spec.get("min_workers", 0)
        have = len(handle.provider.non_terminated_nodes(
            {TAG_NODE_KIND: NODE_KIND_WORKER,
             TAG_USER_NODE_TYPE: type_name}))
        if have < want:
            handle.provider.create_node(
                spec,
                {TAG_NODE_KIND: NODE_KIND_WORKER,
                 TAG_USER_NODE_TYPE: type_name},
                want - have)
    logger.info("cluster %s up: head=%s workers=%d", name,
                handle.head_id, len(handle.worker_ids()))
    _save_cluster_state(handle)
    return handle


def _state_path(name: str) -> str:
    import os

    d = os.path.join(os.path.expanduser("~"), ".ray_tpu", "clusters")
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, f"{name}.json")


def _save_cluster_state(handle: ClusterHandle) -> None:
    """Process-backed clusters outlive the `ray up` CLI process; persist
    enough for a later `ray down` in a fresh process to reap them
    (reference: ray up writes cluster state under ~/.ray)."""
    if not hasattr(handle.provider, "state"):
        return
    import json

    with open(_state_path(handle.name), "w") as f:
        json.dump(handle.provider.state(), f)


def _teardown_from_state_file(name: str) -> bool:
    import json
    import os
    import signal as _signal

    path = _state_path(name)
    if not os.path.exists(path):
        return False
    with open(path) as f:
        state = json.load(f)
    for pid in reversed(state.get("pids", [])):  # raylets, then GCS
        try:
            os.kill(pid, _signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            pass
    os.unlink(path)
    logger.info("cluster %s (from state file) torn down", name)
    return True


def teardown_cluster(config_or_name, keep_min_workers: bool = False) -> None:
    """`ray down` (reference: commands.py:211)."""
    if isinstance(config_or_name, str) and "\n" not in config_or_name \
            and not config_or_name.endswith((".yaml", ".yml")):
        name = config_or_name
    else:
        name = load_cluster_config(config_or_name)["cluster_name"]
    with _CLUSTERS_LOCK:
        handle = _CLUSTERS.pop(name, None)
    if handle is None:
        # a `ray up` in another (exited) process may have left a
        # process-backed cluster running: reap it from the state file
        if not _teardown_from_state_file(name):
            logger.warning("no live cluster named %s", name)
        return
    handle.stop_monitor()
    keep: Dict[str, int] = {}
    if keep_min_workers:
        for tname, spec in handle.config["available_node_types"].items():
            keep[tname] = spec.get("min_workers", 0)
    for nid in handle.worker_ids():
        tname = handle.provider.node_tags(nid).get(TAG_USER_NODE_TYPE)
        if keep.get(tname, 0) > 0:
            keep[tname] -= 1
            continue
        handle.provider.terminate_node(nid)
    if not keep_min_workers:
        handle.provider.terminate_node(handle.head_id)
        if hasattr(handle.provider, "shutdown"):
            handle.provider.shutdown()
        import os

        try:
            os.unlink(_state_path(name))
        except FileNotFoundError:
            pass
    else:
        with _CLUSTERS_LOCK:
            _CLUSTERS[name] = handle  # still alive, head retained
        # terminated workers must leave the persisted pid list too, or a
        # later cross-process down would SIGTERM recycled pids
        _save_cluster_state(handle)


def get_head_node_ip(config_or_name) -> str:
    handle = _resolve(config_or_name)
    return handle.head_node_ip()


def get_worker_node_ips(config_or_name) -> List[str]:
    handle = _resolve(config_or_name)
    return [handle.provider.internal_ip(n) for n in handle.worker_ids()]


def _resolve(config_or_name) -> ClusterHandle:
    if isinstance(config_or_name, str) and "\n" not in config_or_name \
            and not config_or_name.endswith((".yaml", ".yml")):
        name = config_or_name
    else:
        name = load_cluster_config(config_or_name)["cluster_name"]
    with _CLUSTERS_LOCK:
        handle = _CLUSTERS.get(name)
    if handle is None:
        raise RuntimeError(f"no live cluster named {name}")
    return handle
