"""LoadMetrics — the autoscaler's view of cluster load.

Reference: python/ray/autoscaler/_private/load_metrics.py: per-node
used/total resources, queued (pending + infeasible) resource demands,
and pending placement-group bundle demands; plus last-busy timestamps
for idle-node detection.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple


class LoadMetrics:
    def __init__(self):
        self.node_resources: Dict[str, Tuple[Dict[str, float],
                                             Dict[str, float]]] = {}
        self.pending_demands: List[Dict[str, float]] = []
        self.pending_pg_demands: List[List[Dict[str, float]]] = []
        self.last_used_time: Dict[str, float] = {}

    def update_from_runtime(self, runtime) -> None:
        """Poll the in-process cluster the way the reference monitor polls
        GCS resource reports (gcs_resource_report_poller.cc)."""
        now = time.time()
        self.pending_demands = []
        self.node_resources = {}
        for raylet in runtime.cluster_state.alive_raylets():
            ids = runtime.cluster_state.ids
            total = raylet.local_resources.to_map(ids)
            avail = raylet.local_resources.to_map(ids, available=True)
            key = raylet.node_id.hex()
            self.node_resources[key] = (total, avail)
            busy = False
            with raylet._lock:
                queued = list(raylet._pending) + list(raylet._infeasible)
                if raylet._running or raylet._dispatch_len or queued:
                    busy = True
                for task in queued:
                    self.pending_demands.append(dict(task.spec.resources))
            if busy or key not in self.last_used_time:
                self.last_used_time[key] = now
            # partially-used nodes also count as busy
            if any(avail.get(k, 0) < v for k, v in total.items()
                   if k in ("CPU", "GPU", "TPU")):
                self.last_used_time[key] = now
        self.pending_pg_demands = []
        pgm = getattr(runtime, "pg_manager", None)
        if pgm is not None:
            for pg in pgm.pending_pgs():
                self.pending_pg_demands.append(
                    [dict(b) for b in pg.bundles])

    def update_from_gcs(self, gcs_address: str) -> None:
        """Poll a PROCESS-backed cluster: node resources come from the
        GCS cluster view, per-demand task queues from each raylet
        process's node_stats (the queued_demands field — the process-
        tier equivalent of resource_load_by_shape in the reference's
        raylet resource reports). Closes the round-3 PARITY known-gap:
        raylet-process demand now drives LoadMetrics directly."""
        from ray_tpu.cluster.rpc import (
            RpcClient,
            RpcConnectionError,
            ReconnectingRpcClient,
        )

        if getattr(self, "_gcs_client", None) is None or \
                getattr(self, "_gcs_address", None) != gcs_address:
            self.close()
            self._gcs_address = gcs_address
            self._gcs_client = ReconnectingRpcClient(gcs_address)
            self._raylet_clients: Dict[str, RpcClient] = {}
        now = time.time()
        view = self._gcs_client.call("cluster_view", timeout=10.0)
        self.pending_demands = []
        self.node_resources = {}
        for node_id, info in view["nodes"].items():
            if not info["alive"]:
                stale = self._raylet_clients.pop(node_id, None)
                if stale is not None:
                    stale.close()  # else its reader thread + fd leak
                continue
            if info.get("state") == "DRAINING":
                # capacity on its way out (drain / preemption notice):
                # report none of it, so the demand scheduler plans the
                # replacement while the node winds down, and the idle
                # scan never double-terminates it
                self.last_used_time.pop(node_id, None)
                continue
            total = dict(info["resources"])
            avail = dict(info["available"])
            self.node_resources[node_id] = (total, avail)
            busy = False
            try:
                client = self._raylet_clients.get(node_id)
                if client is None or client.closed:
                    client = RpcClient(info["address"])
                    self._raylet_clients[node_id] = client
                stats = client.call("node_stats", timeout=10.0)
                self.pending_demands.extend(
                    stats.get("queued_demands", []))
                busy = bool(stats.get("queued") or stats.get("running")
                            or stats.get("actors"))
            except (RpcConnectionError, TimeoutError, OSError):
                pass  # node died between view and stats: next tick
            if any(avail.get(k, 0) < v for k, v in total.items()
                   if k in ("CPU", "GPU", "TPU")):
                busy = True
            if busy or node_id not in self.last_used_time:
                self.last_used_time[node_id] = now
        try:
            reply = self._gcs_client.call("pg_pending", timeout=10.0)
            self.pending_pg_demands = reply.get("pending", [])
        except Exception:
            self.pending_pg_demands = []

    def close(self) -> None:
        """Release the polling clients (the monitor loop is long-lived;
        without this, dead-node churn accumulates sockets + reader
        threads)."""
        for client in getattr(self, "_raylet_clients", {}).values():
            try:
                client.close()
            except Exception:
                pass
        self._raylet_clients = {}
        gcs = getattr(self, "_gcs_client", None)
        if gcs is not None:
            try:
                gcs.close()
            except Exception:
                pass
            self._gcs_client = None

    def idle_nodes(self, idle_timeout_s: float) -> List[str]:
        now = time.time()
        return [nid for nid, t in self.last_used_time.items()
                if nid in self.node_resources
                and now - t > idle_timeout_s]

    def summary(self) -> str:
        lines = [f"{len(self.node_resources)} nodes"]
        for nid, (total, avail) in self.node_resources.items():
            lines.append(f"  {nid[:8]}: avail={avail} total={total}")
        lines.append(f"pending demands: {len(self.pending_demands)}")
        return "\n".join(lines)
