"""LoadMetrics — the autoscaler's view of cluster load.

Reference: python/ray/autoscaler/_private/load_metrics.py: per-node
used/total resources, queued (pending + infeasible) resource demands,
and pending placement-group bundle demands; plus last-busy timestamps
for idle-node detection.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple


class LoadMetrics:
    def __init__(self):
        self.node_resources: Dict[str, Tuple[Dict[str, float],
                                             Dict[str, float]]] = {}
        self.pending_demands: List[Dict[str, float]] = []
        self.pending_pg_demands: List[List[Dict[str, float]]] = []
        self.last_used_time: Dict[str, float] = {}

    def update_from_runtime(self, runtime) -> None:
        """Poll the in-process cluster the way the reference monitor polls
        GCS resource reports (gcs_resource_report_poller.cc)."""
        now = time.time()
        self.pending_demands = []
        self.node_resources = {}
        for raylet in runtime.cluster_state.alive_raylets():
            ids = runtime.cluster_state.ids
            total = raylet.local_resources.to_map(ids)
            avail = raylet.local_resources.to_map(ids, available=True)
            key = raylet.node_id.hex()
            self.node_resources[key] = (total, avail)
            busy = False
            with raylet._lock:
                queued = list(raylet._pending) + list(raylet._infeasible)
                if raylet._running or raylet._dispatch_len or queued:
                    busy = True
                for task in queued:
                    self.pending_demands.append(dict(task.spec.resources))
            if busy or key not in self.last_used_time:
                self.last_used_time[key] = now
            # partially-used nodes also count as busy
            if any(avail.get(k, 0) < v for k, v in total.items()
                   if k in ("CPU", "GPU", "TPU")):
                self.last_used_time[key] = now
        self.pending_pg_demands = []
        pgm = getattr(runtime, "pg_manager", None)
        if pgm is not None:
            for pg in pgm.pending_pgs():
                self.pending_pg_demands.append(
                    [dict(b) for b in pg.bundles])

    def idle_nodes(self, idle_timeout_s: float) -> List[str]:
        now = time.time()
        return [nid for nid, t in self.last_used_time.items()
                if nid in self.node_resources
                and now - t > idle_timeout_s]

    def summary(self) -> str:
        lines = [f"{len(self.node_resources)} nodes"]
        for nid, (total, avail) in self.node_resources.items():
            lines.append(f"  {nid[:8]}: avail={avail} total={total}")
        lines.append(f"pending demands: {len(self.pending_demands)}")
        return "\n".join(lines)
