"""Inventory node provider: a fixed fleet of SSH-reachable machines.

Reference shape: python/ray/autoscaler/_private/local/node_provider.py
(the "local" provider — a list of machines instead of a cloud API)
composed with command_runner.py (exec over ssh) and updater.py (node
bootstrap). ``ray up`` against an inventory claims a free machine per
node, bootstraps it through a NodeUpdater (initialization / setup
commands, file mounts), and starts a raylet on it detached; the
raylet's announce line is polled out of a remote log file, so the
whole flow works identically over ssh and on local machines.

provider config keys:
  machines        [{"host": ..., "user": ..., "port": ..., "ssh_key":
                   ..., "local": true}] — "local": true runs commands
                   as local shells (LocalCommandRunner); otherwise an
                   SSHCommandRunner speaks to the host
  gcs_address     optional external control plane; when absent a GCS
                   server process is spawned (the head's control plane)
  initialization_commands / setup_commands   run on every node before
                   the raylet starts (reference cluster-config keys)
  file_mounts     {target: source} synced before setup
"""

from __future__ import annotations

import json
import logging
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from ray_tpu.autoscaler.command_runner import (
    LocalCommandRunner,
    SSHCommandRunner,
)
from ray_tpu.autoscaler.node_provider import (
    NODE_KIND_HEAD,
    NODE_KIND_WORKER,
    TAG_NODE_KIND,
    TAG_NODE_STATUS,
    TAG_USER_NODE_TYPE,
    NodeProvider,
)
from ray_tpu.autoscaler.updater import NodeUpdater

logger = logging.getLogger(__name__)


class InventoryNodeProvider(NodeProvider):
    def __init__(self, provider_config: Dict[str, Any],
                 cluster_name: str = "inventory"):
        super().__init__(provider_config, cluster_name)
        import sys

        self._python = provider_config.get("python", sys.executable)
        machines = provider_config.get("machines") or []
        if not machines:
            raise ValueError("inventory provider needs machines: [...]")
        self._machines: List[Dict[str, Any]] = [dict(m) for m in machines]
        self._claimed: Dict[int, str] = {}  # machine idx -> node id
        self._nodes: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.Lock()
        self._gcs_proc = None
        self.gcs_address = provider_config.get("gcs_address")
        if not self.gcs_address:
            from ray_tpu.cluster.process_cluster import _spawn

            self._gcs_proc, fields = _spawn(
                ["ray_tpu.cluster.gcs_server"], "GCS_ADDRESS")
            self.gcs_address = fields[1]

    # ------------------------------------------------------------- machines
    def _claim_machine(self) -> int:
        with self._lock:
            for idx in range(len(self._machines)):
                if idx not in self._claimed:
                    self._claimed[idx] = "pending"
                    return idx
        raise RuntimeError("inventory exhausted: no free machines")

    def _runner_for(self, machine: Dict[str, Any]):
        if machine.get("local"):
            return LocalCommandRunner()
        return SSHCommandRunner(
            host=machine["host"], user=machine.get("user", ""),
            port=int(machine.get("port", 22)),
            ssh_key=machine.get("ssh_key"))

    # -------------------------------------------------------------- factory
    def _launch(self, node_config: Dict[str, Any],
                tags: Dict[str, str]) -> str:
        idx = self._claim_machine()
        machine = self._machines[idx]
        nid = f"inv-{idx}-{uuid.uuid4().hex[:6]}"
        with self._lock:
            self._claimed[idx] = nid
            self._nodes[nid] = {"tags": dict(tags), "machine_idx": idx,
                                "raylet": None, "address": None}
        runner = self._runner_for(machine)
        resources = dict(node_config.get("resources", {"CPU": 1}))
        log = f"/tmp/ray_tpu_{self.cluster_name}_{nid}.log"
        pidfile = log + ".pid"
        start = (
            f"nohup {self._python} -m ray_tpu.cluster.raylet_server "
            f"--gcs {self.gcs_address} "
            f"--resources '{json.dumps(resources)}' "
            f"> {log} 2>&1 & echo $! > {pidfile}")
        updater = NodeUpdater(
            nid, self, runner,
            initialization_commands=self.provider_config.get(
                "initialization_commands", []),
            setup_commands=self.provider_config.get("setup_commands", []),
            start_commands=[start],
            file_mounts=self.provider_config.get("file_mounts", {}),
            ready_timeout_s=float(
                self.provider_config.get("ready_timeout_s", 60.0)))
        try:
            updater.run()
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                rc, out = runner.run(f"cat {log} 2>/dev/null || true")
                for line in out.splitlines():
                    if line.startswith("RAYLET_ADDRESS"):
                        fields = line.split()
                        with self._lock:
                            self._nodes[nid]["address"] = fields[1]
                            self._nodes[nid]["raylet"] = fields[3]
                            self._nodes[nid]["log"] = log
                            self._nodes[nid]["pidfile"] = pidfile
                        return nid
                time.sleep(0.5)
            raise RuntimeError(
                f"raylet on machine {machine.get('host', idx)} never "
                f"announced (see {log})")
        except BaseException:
            # reap any half-started raylet BEFORE releasing the machine:
            # a detached process that announces later would register as
            # a ghost node, and the next claim would double-book the
            # machine with its pidfile orphaned
            try:
                runner.run(f"[ -f {pidfile} ] && "
                           f"kill $(cat {pidfile}) 2>/dev/null; "
                           f"rm -f {pidfile}", timeout=30.0)
            except Exception:  # noqa: BLE001 — best-effort reap
                pass
            with self._lock:
                self._claimed.pop(idx, None)
                self._nodes.pop(nid, None)
            raise

    def create_head(self, node_config: Dict[str, Any],
                    node_type: str) -> str:
        return self._launch(node_config, {
            TAG_NODE_KIND: NODE_KIND_HEAD,
            TAG_USER_NODE_TYPE: node_type})

    def create_node(self, node_config: Dict[str, Any],
                    tags: Dict[str, str], count: int) -> None:
        for _ in range(count):
            self._launch(node_config, {TAG_NODE_KIND: NODE_KIND_WORKER,
                                       **tags})

    # ------------------------------------------------------------- surface
    def non_terminated_nodes(self, tag_filters: Dict[str, str]
                             ) -> List[str]:
        with self._lock:
            return [nid for nid, info in self._nodes.items()
                    if all(info["tags"].get(k) == v
                           for k, v in tag_filters.items())]

    def is_running(self, node_id: str) -> bool:
        with self._lock:
            return node_id in self._nodes

    def node_tags(self, node_id: str) -> Dict[str, str]:
        with self._lock:
            info = self._nodes.get(node_id)
            return dict(info["tags"]) if info else {}

    def set_node_tags(self, node_id: str, tags: Dict[str, str]) -> None:
        with self._lock:
            info = self._nodes.get(node_id)
            if info is not None:
                info["tags"].update(tags)

    def internal_ip(self, node_id: str) -> str:
        with self._lock:
            info = self._nodes.get(node_id)
        if not info:
            return ""
        machine = self._machines[info["machine_idx"]]
        return machine.get("host", "127.0.0.1")

    def raylet_node_id(self, node_id: str) -> Optional[str]:
        with self._lock:
            info = self._nodes.get(node_id)
            return info["raylet"] if info else None

    def terminate_node(self, node_id: str) -> None:
        with self._lock:
            info = self._nodes.pop(node_id, None)
            if info is not None:
                self._claimed.pop(info["machine_idx"], None)
        if info is None or info.get("pidfile") is None:
            return
        machine = self._machines[info["machine_idx"]]
        runner = self._runner_for(machine)
        try:
            runner.run(f"kill $(cat {info['pidfile']}) 2>/dev/null; "
                       f"rm -f {info['pidfile']}", timeout=30.0)
        except Exception:  # noqa: BLE001 — best-effort reap
            logger.warning("terminate of %s failed", node_id,
                           exc_info=True)

    def shutdown(self) -> None:
        for nid in list(self._nodes):
            self.terminate_node(nid)
        if self._gcs_proc is not None:
            self._gcs_proc.terminate()

    def state(self) -> Dict[str, Any]:
        """For ray down from a fresh process (commands state file)."""
        pids = []
        if self._gcs_proc is not None:
            pids.append(self._gcs_proc.pid)
        return {"pids": pids, "provider": "inventory"}
