"""StandardAutoscaler — the reconcile loop.

Reference: python/ray/autoscaler/_private/autoscaler.py:138
(StandardAutoscaler.update:284): each tick reads load metrics, plans
launches with the demand scheduler, creates/terminates nodes through the
NodeProvider, and scales down nodes idle past the timeout (never below
min_workers).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu.autoscaler.load_metrics import LoadMetrics
from ray_tpu.autoscaler.node_provider import (
    NODE_KIND_WORKER,
    NodeProvider,
    TAG_NODE_KIND,
    TAG_USER_NODE_TYPE,
)
from ray_tpu.autoscaler.resource_demand_scheduler import get_nodes_to_launch

logger = logging.getLogger(__name__)


class StandardAutoscaler:
    def __init__(self, config: Dict[str, Any], provider: NodeProvider,
                 load_metrics: Optional[LoadMetrics] = None):
        """config mirrors the reference's cluster YAML:
        {available_node_types: {name: {resources, min_workers,
        max_workers}}, max_workers, idle_timeout_minutes}."""
        from ray_tpu._private.config import Config

        self.config = config
        self.provider = provider
        self.load_metrics = load_metrics or LoadMetrics()
        self.node_types: Dict[str, dict] = config["available_node_types"]
        self.max_workers: int = config.get("max_workers", 20)
        # cluster-YAML keys win; the Config knob is the default when
        # the YAML names neither (reference: idle_timeout_minutes)
        if "idle_timeout_s" in config or "idle_timeout_minutes" in config:
            self.idle_timeout_s: float = config.get(
                "idle_timeout_s",
                config.get("idle_timeout_minutes", 5) * 60.0)
        else:
            self.idle_timeout_s = \
                Config.instance().autoscaler_idle_timeout_s
        self.demand_threshold: int = config.get(
            "demand_threshold",
            Config.instance().autoscaler_demand_threshold)
        self.num_launches = 0
        self.num_terminations = 0

    # ------------------------------------------------------------- update
    def update(self, runtime=None) -> Dict[str, int]:
        """One reconcile tick; returns the launch plan it executed.
        Demand comes from the provider's GCS (process-backed clusters:
        real raylet-process queue depth via node_stats) when the
        provider exposes one, else from the in-process runtime."""
        gcs_address = getattr(self.provider, "gcs_address", None)
        if gcs_address:
            self.load_metrics.update_from_gcs(gcs_address)
        else:
            if runtime is None:
                from ray_tpu.core import runtime as rt_mod

                runtime = rt_mod.global_runtime
            if runtime is not None:
                self.load_metrics.update_from_runtime(runtime)

        workers = self.provider.non_terminated_nodes(
            {TAG_NODE_KIND: NODE_KIND_WORKER})
        existing: Dict[str, int] = {}
        for nid in workers:
            t = self.provider.node_tags(nid).get(TAG_USER_NODE_TYPE, "?")
            existing[t] = existing.get(t, 0) + 1

        available = [avail for _, (_, avail) in
                     self.load_metrics.node_resources.items()]
        demands = self.load_metrics.pending_demands
        pg_demands = self.load_metrics.pending_pg_demands
        if len(demands) + len(pg_demands) < self.demand_threshold:
            # below the scale-up hysteresis threshold: don't launch for
            # a trickle of demand — plan only the min_workers floor
            # (the default threshold of 1 makes this a no-op)
            demands, pg_demands = [], []
        plan = get_nodes_to_launch(
            self.node_types,
            existing,
            available,
            demands,
            pg_demands,
            self.max_workers,
        )
        for tname, count in plan.items():
            self._launch(tname, count)
        self._terminate_idle(workers, existing, runtime)
        return plan

    def _launch(self, node_type: str, count: int) -> None:
        cfg = self.node_types[node_type]
        logger.info("autoscaler launching %d x %s", count, node_type)
        self.provider.create_node(
            {"resources": dict(cfg.get("resources", {}))},
            {TAG_NODE_KIND: NODE_KIND_WORKER,
             TAG_USER_NODE_TYPE: node_type},
            count)
        self.num_launches += count

    def _terminate_idle(self, workers: List[str],
                        existing: Dict[str, int], runtime) -> None:
        if runtime is None and not getattr(self.provider, "gcs_address",
                                           None):
            return
        idle = set(self.load_metrics.idle_nodes(self.idle_timeout_s))
        if not idle:
            return
        raylet_to_provider = {}
        for nid in workers:
            raylet_id = getattr(self.provider, "raylet_node_id",
                                lambda _x: None)(nid)
            if raylet_id is not None:
                key = (raylet_id if isinstance(raylet_id, str)
                       else raylet_id.hex())
                raylet_to_provider[key] = nid
        for raylet_hex in idle:
            provider_id = raylet_to_provider.get(raylet_hex)
            if provider_id is None:
                continue  # head node or unknown
            t = self.provider.node_tags(provider_id).get(
                TAG_USER_NODE_TYPE, "?")
            if existing.get(t, 0) <= self.node_types.get(t, {}).get(
                    "min_workers", 0):
                continue
            logger.info("autoscaler terminating idle node %s", provider_id)
            self.provider.terminate_node(provider_id)
            existing[t] = existing.get(t, 0) - 1
            self.num_terminations += 1
            self.load_metrics.last_used_time.pop(raylet_hex, None)


class Monitor:
    """Background loop driving autoscaler.update (reference:
    autoscaler/_private/monitor.py runs beside the GCS)."""

    def __init__(self, autoscaler: StandardAutoscaler,
                 interval_s: Optional[float] = None):
        from ray_tpu._private.config import Config

        self.autoscaler = autoscaler
        self.interval_s = (Config.instance().autoscaler_update_interval_s
                           if interval_s is None else interval_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.autoscaler.update()
            except Exception:  # noqa: BLE001 — monitor must survive
                logger.exception("autoscaler update failed")
            self._stop.wait(self.interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
