"""Per-node command execution for cluster bring-up.

Reference: python/ray/autoscaler/_private/command_runner.py — the
``CommandRunnerInterface`` implemented by ``SSHCommandRunner`` (exec on
a remote machine over ssh, file sync over rsync/scp) and a local
subprocess flavor. The updater (updater.py) drives a runner to
bootstrap a node: wait until reachable, sync file mounts, run setup
and start commands.

``SSHCommandRunner`` builds standard ssh/rsync argument vectors; the
process launcher is injectable (``exec_fn``) so the argv contract is
unit-testable on hosts without sshd — and on a real fleet the default
``subprocess.run`` launcher speaks to real machines unchanged.
"""

from __future__ import annotations

import os
import subprocess
from typing import Callable, Dict, List, Optional, Tuple

ExecFn = Callable[[List[str]], Tuple[int, str, str]]


def _default_exec(argv: List[str], timeout: float = 300.0,
                  env: Optional[Dict[str, str]] = None
                  ) -> Tuple[int, str, str]:
    proc = subprocess.run(argv, capture_output=True, text=True,
                          timeout=timeout, env=env)
    return proc.returncode, proc.stdout, proc.stderr


class CommandRunnerInterface:
    """What the NodeUpdater needs from a node (reference
    command_runner.py CommandRunnerInterface)."""

    def run(self, cmd: str, timeout: float = 300.0) -> Tuple[int, str]:
        """Run a shell command on the node; returns (rc, stdout)."""
        raise NotImplementedError

    def run_rsync_up(self, source: str, target: str) -> None:
        """Copy a local path onto the node."""
        raise NotImplementedError

    def run_rsync_down(self, source: str, target: str) -> None:
        """Copy a node path to the local machine."""
        raise NotImplementedError

    def remote_shell_command_str(self) -> str:
        """The command a human would use to reach the node."""
        raise NotImplementedError


class LocalCommandRunner(CommandRunnerInterface):
    """The node IS this machine (reference LocalNodeProvider posture):
    commands run as local shells with the sanitized child env, so
    bring-up never inherits the caller's accelerator hooks."""

    def __init__(self, env: Optional[Dict[str, str]] = None):
        if env is None:
            from ray_tpu.cluster.child_env import sanitized_env

            env = sanitized_env(pin_pythonpath=True)
        self._env = env

    def run(self, cmd: str, timeout: float = 300.0) -> Tuple[int, str]:
        proc = subprocess.run(["/bin/sh", "-c", cmd],
                              capture_output=True, text=True,
                              timeout=timeout, env=self._env)
        return proc.returncode, proc.stdout

    def run_rsync_up(self, source: str, target: str) -> None:
        self._copy(source, target)

    def run_rsync_down(self, source: str, target: str) -> None:
        self._copy(source, target)

    @staticmethod
    def _copy(source: str, target: str) -> None:
        import shutil

        os.makedirs(os.path.dirname(os.path.abspath(target)),
                    exist_ok=True)
        if os.path.isdir(source):
            shutil.copytree(source, target, dirs_exist_ok=True)
        else:
            shutil.copy2(source, target)

    def remote_shell_command_str(self) -> str:
        return "/bin/sh"


class SSHCommandRunner(CommandRunnerInterface):
    """Exec on a remote machine over ssh (reference SSHCommandRunner):
    BatchMode + ControlMaster multiplexing + IdentityFile, rsync for
    file sync. ``exec_fn`` defaults to a real subprocess launcher and
    is injectable for argv-contract tests."""

    SSH_OPTS = [
        "-o", "BatchMode=yes",
        "-o", "StrictHostKeyChecking=no",
        "-o", "UserKnownHostsFile=/dev/null",
        "-o", "ConnectTimeout=10",
        "-o", "ControlMaster=auto",
        "-o", "ControlPersist=60s",
    ]

    def __init__(self, host: str, user: str = "", port: int = 22,
                 ssh_key: Optional[str] = None,
                 control_path: Optional[str] = None,
                 exec_fn: Optional[ExecFn] = None):
        self.host = host
        self.user = user
        self.port = port
        self.ssh_key = ssh_key
        self.control_path = control_path or os.path.join(
            os.path.expanduser("~"), ".ray_tpu", "ssh_sockets",
            f"{user or 'x'}@{host}:{port}")
        os.makedirs(os.path.dirname(self.control_path), exist_ok=True)
        self._exec: ExecFn = exec_fn or (
            lambda argv: _default_exec(argv))

    @property
    def _target(self) -> str:
        return f"{self.user}@{self.host}" if self.user else self.host

    def _ssh_base(self) -> List[str]:
        argv = ["ssh"] + list(self.SSH_OPTS)
        argv += ["-o", f"ControlPath={self.control_path}"]
        argv += ["-p", str(self.port)]
        if self.ssh_key:
            argv += ["-i", self.ssh_key]
        return argv

    def run(self, cmd: str, timeout: float = 300.0) -> Tuple[int, str]:
        argv = self._ssh_base() + [self._target,
                                   f"bash -lc {_shquote(cmd)}"]
        rc, out, _err = self._exec(argv)
        return rc, out

    def _rsync(self, src: str, dst: str) -> None:
        argv = ["rsync", "-az", "-e", " ".join(self._ssh_base()),
                src, dst]
        rc, _out, err = self._exec(argv)
        if rc != 0:
            raise RuntimeError(f"rsync failed rc={rc}: {err}")

    def run_rsync_up(self, source: str, target: str) -> None:
        self._rsync(source, f"{self._target}:{target}")

    def run_rsync_down(self, source: str, target: str) -> None:
        self._rsync(f"{self._target}:{source}", target)

    def remote_shell_command_str(self) -> str:
        return " ".join(self._ssh_base() + [self._target])


def _shquote(s: str) -> str:
    import shlex

    return shlex.quote(s)
