"""Demand-driven launch planning: bin-pack pending demands over node types.

Reference: python/ray/autoscaler/_private/resource_demand_scheduler.py:56
(get_nodes_to_launch:151). Given

  - node_types: {name: {"resources": {...}, "min_workers", "max_workers"}}
  - currently available capacity per existing node
  - queued task/actor resource demands + placement-group bundle demands

produce {node_type: count} to launch. The packing is vectorized: demands
sort largest-first, each demand first tries the remaining capacity of existing +
already-planned nodes (first-fit), then opens a new node of the
best-scoring type (fewest wasted resources — the reference's
_utilization_score).
"""

from __future__ import annotations

import copy
from typing import Dict, List, Optional, Tuple

import numpy as np

NodeTypes = Dict[str, dict]


def _vec(demand: Dict[str, float], names: List[str]) -> np.ndarray:
    return np.array([float(demand.get(n, 0.0)) for n in names])


def _fits(capacity: np.ndarray, demand: np.ndarray) -> bool:
    return bool(np.all(capacity + 1e-9 >= demand))


def _utilization_score(node_res: np.ndarray, demand: np.ndarray
                       ) -> Optional[float]:
    """Higher = tighter fit (reference: prefers node types the demand
    uses most fully, so big nodes aren't wasted on small demands)."""
    if not _fits(node_res, demand):
        return None
    used = np.minimum(demand, node_res)
    with np.errstate(divide="ignore", invalid="ignore"):
        frac = np.where(node_res > 0, used / node_res, 0.0)
    return float(frac.sum())


def get_nodes_to_launch(
    node_types: NodeTypes,
    existing_nodes: Dict[str, int],
    available_capacity: List[Dict[str, float]],
    resource_demands: List[Dict[str, float]],
    pg_demands: Optional[List[List[Dict[str, float]]]] = None,
    max_workers: int = 100,
) -> Dict[str, int]:
    """Pure planning function — unit-testable with synthetic inputs, like
    the reference's test_resource_demand_scheduler.py drives it."""
    demands = [dict(d) for d in resource_demands]
    for bundles in (pg_demands or []):
        demands.extend(dict(b) for b in bundles)
    # strip PG shadow resources back to their base names so the planner
    # reasons in physical capacity (CPU_group_xxx -> CPU)
    demands = [_strip_pg_shadows(d) for d in demands]
    demands = [d for d in demands if d]
    if not demands:
        return _min_workers_to_launch(node_types, existing_nodes,
                                      max_workers)

    names = sorted({n for d in demands for n in d} |
                   {n for t in node_types.values()
                    for n in t.get("resources", {})})
    cap = [_vec(c, names) for c in available_capacity]
    dvecs = sorted((_vec(d, names) for d in demands),
                   key=lambda v: -float(v.sum()))

    to_launch: Dict[str, int] = {}
    planned_cap: List[np.ndarray] = []
    total_existing = sum(existing_nodes.values())

    def launched_of(t: str) -> int:
        return existing_nodes.get(t, 0) + to_launch.get(t, 0)

    for demand in dvecs:
        placed = False
        for pool in (cap, planned_cap):
            for c in pool:
                if _fits(c, demand):
                    c -= demand
                    placed = True
                    break
            if placed:
                break
        if placed:
            continue
        # open a new node of the best-fitting type
        best_type, best_score = None, None
        for tname, tcfg in node_types.items():
            if launched_of(tname) >= tcfg.get("max_workers", max_workers):
                continue
            if total_existing + sum(to_launch.values()) >= max_workers:
                break
            node_res = _vec(tcfg.get("resources", {}), names)
            score = _utilization_score(node_res, demand)
            if score is not None and (best_score is None
                                      or score > best_score):
                best_type, best_score = tname, score
        if best_type is None:
            continue  # infeasible on every launchable type
        to_launch[best_type] = to_launch.get(best_type, 0) + 1
        node_res = _vec(node_types[best_type].get("resources", {}), names)
        planned_cap.append(node_res - demand)

    # top up min_workers
    for tname, count in _min_workers_to_launch(
            node_types,
            {t: launched_of(t) for t in node_types},
            max_workers).items():
        to_launch[tname] = to_launch.get(tname, 0) + count
    return to_launch


def _min_workers_to_launch(node_types: NodeTypes,
                           existing_nodes: Dict[str, int],
                           max_workers: int) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for tname, tcfg in node_types.items():
        want = tcfg.get("min_workers", 0)
        have = existing_nodes.get(tname, 0)
        if want > have:
            out[tname] = min(want - have, max_workers)
    return out


def _strip_pg_shadows(demand: Dict[str, float]) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for name, amount in demand.items():
        base = name.split("_group_")[0] if "_group_" in name else name
        if base == "bundle":
            continue
        out[base] = out.get(base, 0.0) + amount
    return out
